"""Transformation DAG and stream graph.

reference: flink-core/.../api/dag/Transformation.java (the client-side DAG),
streaming/api/graph/StreamGraphGenerator.java:253 and
StreamingJobGraphGenerator.java:221 (chaining). Re-design: transformations
carry operator *factories*; the graph is a plain adjacency structure; chaining
is implicit because the local executor fuses all same-shard operators into one
Python call chain (no serialization boundary exists to begin with), and on
device XLA fusion plays the role of operator chaining (SURVEY.md §2.9).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence

_ids = itertools.count(1)


@dataclasses.dataclass
class Transformation:
    name: str
    kind: str  # 'source' | 'one_input' | 'union' | 'sink'
    operator_factory: Optional[Callable[[], Any]] = None
    inputs: List["Transformation"] = dataclasses.field(default_factory=list)
    #: None = unset -> the executor applies `parallelism.default` to keyed
    #: operators (reference: Transformation.parallelism=-1 sentinel +
    #: env default)
    parallelism: Optional[int] = None
    # source-specific
    source: Any = None
    watermark_strategy: Any = None
    # keyed-exchange marker: records must be routed by key group after this
    keyed: bool = False
    key_field: Optional[str] = None
    # side-output edge: this node consumes only TaggedBatches with this tag
    # (reference: OutputTag + DataStream.getSideOutput)
    side_tag: Optional[str] = None
    # broadcast edge: every parallel instance sees every record
    broadcast: bool = False
    #: slot sharing group (reference: Transformation.slotSharingGroup /
    #: SlotSharingGroup): subtasks of vertices in the SAME group share a
    #: slot; a distinct group forces its own slots. None inherits the
    #: input's group ("default" at sources).
    slot_group: Optional[str] = None
    uid: int = dataclasses.field(default_factory=lambda: next(_ids))

    def __hash__(self):
        return self.uid


class StreamGraph:
    """Topologically-ordered view of the transformation DAG."""

    def __init__(self, transformations: Sequence[Transformation]):
        self.nodes: List[Transformation] = self._topo_sort(transformations)
        self.downstream: Dict[int, List[Transformation]] = {}
        for t in self.nodes:
            for inp in t.inputs:
                self.downstream.setdefault(inp.uid, []).append(t)

    def slot_groups(self) -> Dict[int, str]:
        """uid -> resolved slot sharing group: an unset group inherits
        the (first) input's, sources default to "default" (reference:
        StreamGraphGenerator.determineSlotSharingGroup)."""
        out: Dict[int, str] = {}
        for t in self.nodes:
            if t.slot_group is not None:
                out[t.uid] = t.slot_group
            elif t.inputs:
                out[t.uid] = out[t.inputs[0].uid]
            else:
                out[t.uid] = "default"
        return out

    def distinct_slot_groups(self) -> List[str]:
        seen: List[str] = []
        for g in self.slot_groups().values():
            if g not in seen:
                seen.append(g)
        return seen

    @staticmethod
    def _topo_sort(sinks: Sequence[Transformation]) -> List[Transformation]:
        seen: Dict[int, Transformation] = {}
        order: List[Transformation] = []

        def visit(t: Transformation):
            if t.uid in seen:
                return
            seen[t.uid] = t
            for inp in t.inputs:
                visit(inp)
            order.append(t)

        for s in sinks:
            visit(s)
        return order

    @property
    def sources(self) -> List[Transformation]:
        return [t for t in self.nodes if t.kind == "source"]

    def stable_id(self, t: Transformation) -> str:
        """Process-independent operator identity for checkpoints: topological
        position + sanitized name (the reference uses explicit operator uids /
        generated uid hashes for the same purpose). Used as a filename
        component, so path-hostile characters are replaced."""
        import re

        safe = re.sub(r"[^A-Za-z0-9_.()-]", "_", t.name)
        return f"{self.nodes.index(t)}:{safe}"

    def children(self, t: Transformation) -> List[Transformation]:
        return self.downstream.get(t.uid, [])

    def input_index(self, parent: Transformation, child: Transformation) -> int:
        return [i.uid for i in child.inputs].index(parent.uid)
