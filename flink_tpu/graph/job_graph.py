"""JobGraph: chained operator vertices + ExecutionGraph expansion.

reference: StreamingJobGraphGenerator.java:221 turns the StreamGraph into a
JobGraph by CHAINING operators that can share a task (isChainable:
one-to-one forward edge, same parallelism, no exchange between them), then
DefaultExecutionGraph expands every JobVertex into `parallelism`
ExecutionVertex subtasks, each owning a key-group range
(ExecutionJobVertex + KeyGroupRangeAssignment).

Re-design: chaining here decides *process/thread placement*, not code
fusion — within a chain, operators hand batches by direct Python calls and
XLA fuses the device work, so the JobGraph's job is to mark where the
exchanges (key-group shuffles, broadcasts, side-output routes) are and how
many subtasks run each chain. The stage-parallel executor derives its
source/keyed stages from these vertices; the REST API serves the chained
plan (the reference's /jobs/:id/plan).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flink_tpu.graph.transformations import StreamGraph, Transformation

#: how records travel along a JobEdge
FORWARD = "FORWARD"        # same subtask, direct call (chained boundary)
HASH = "HASH"              # key-group routed exchange
REBALANCE = "REBALANCE"    # round-robin redistribute (parallelism change)
BROADCAST = "BROADCAST"    # replicated to every consumer subtask
SIDE = "SIDE"              # side-output tagged route


@dataclasses.dataclass
class JobVertex:
    """A chain of transformations executed as one task."""

    vid: int
    chained: List[Transformation]
    parallelism: int
    #: key field when this vertex's head consumes a keyed exchange
    key_field: Optional[str] = None

    @property
    def name(self) -> str:
        return " -> ".join(t.name for t in self.chained)

    @property
    def head(self) -> Transformation:
        return self.chained[0]

    @property
    def tail(self) -> Transformation:
        return self.chained[-1]

    @property
    def is_source(self) -> bool:
        return self.head.kind == "source"


@dataclasses.dataclass
class JobEdge:
    source_vid: int
    target_vid: int
    ship: str                      # FORWARD | HASH | BROADCAST | SIDE
    key_field: Optional[str] = None
    side_tag: Optional[str] = None


@dataclasses.dataclass
class JobGraph:
    vertices: List[JobVertex]
    edges: List[JobEdge]

    def vertex_of(self, t: Transformation) -> JobVertex:
        for v in self.vertices:
            if any(c.uid == t.uid for c in v.chained):
                return v
        raise KeyError(t.name)

    def to_json(self) -> dict:
        """The REST /jobs/:id/plan shape (reference: JsonPlanGenerator)."""
        return {
            "nodes": [{
                "id": v.vid,
                "description": v.name,
                "parallelism": v.parallelism,
                "operators": [t.name for t in v.chained],
                **({"key_field": v.key_field} if v.key_field else {}),
            } for v in self.vertices],
            "edges": [{
                "source": e.source_vid,
                "target": e.target_vid,
                "ship_strategy": e.ship,
                **({"key_field": e.key_field} if e.key_field else {}),
                **({"side_tag": e.side_tag} if e.side_tag else {}),
            } for e in self.edges],
        }


def _resolve_parallelisms(graph: StreamGraph,
                          default_parallelism: int) -> Dict[int, int]:
    """uid -> effective subtask count. Explicit set_parallelism wins;
    keyed operators take parallelism.default (the key-group axis size);
    other one-input operators INHERIT their input's parallelism (a sink
    after a parallel aggregation runs in each subtask — the reference's
    operators default to env parallelism uniformly, with chaining keeping
    them co-located); sources and multi-input nodes default to 1."""
    out: Dict[int, int] = {}
    for t in graph.nodes:
        if t.parallelism:
            out[t.uid] = t.parallelism
        elif t.keyed:
            out[t.uid] = default_parallelism
        elif len(t.inputs) == 1:
            out[t.uid] = out[t.inputs[0].uid]
        else:
            out[t.uid] = 1
    # backward pass: a key_by routing marker without explicit parallelism
    # adopts its same-key consumer's (the reference has no keyBy operator
    # at all — partitioning is an edge property; the marker must not
    # force an extra exchange by disagreeing with the operator it feeds)
    for t in reversed(graph.nodes):
        if t.keyed and not t.parallelism:
            children = graph.children(t)
            if len(children) == 1 and children[0].keyed \
                    and children[0].key_field == t.key_field:
                out[t.uid] = out[children[0].uid]
    return out


def _partitioning(graph: StreamGraph) -> Dict[int, Optional[str]]:
    """uid -> key field the stream is hash-partitioned by AT THE OUTPUT of
    that transformation (None = arbitrary). A keyed transformation
    (re)partitions; one-to-one forward edges preserve the upstream
    partitioning (the reference's KeyedStream property, which is why
    key_by -> window_agg is ONE exchange, not two)."""
    part: Dict[int, Optional[str]] = {}
    for t in graph.nodes:
        if t.keyed:
            part[t.uid] = t.key_field
        elif len(t.inputs) == 1 and not t.broadcast \
                and t.side_tag is None:
            part[t.uid] = part.get(t.inputs[0].uid)
        else:
            part[t.uid] = None
    return part


def _edge_ship(child: Transformation,
               upstream_partition: Optional[str],
               same_parallelism: bool = True
               ) -> Tuple[str, Optional[str]]:
    if child.keyed:
        if upstream_partition == child.key_field and same_parallelism:
            # already partitioned by this key AND 1:1 subtasks — a
            # parallelism change re-shuffles even on the same key (the
            # consumer's key-group ranges differ)
            return FORWARD, None
        return HASH, child.key_field
    if child.broadcast:
        return BROADCAST, None
    if child.side_tag is not None:
        return SIDE, None
    if not same_parallelism:
        # N -> M subtasks cannot be one-to-one (reference renders
        # REBALANCE/RESCALE for parallelism changes)
        return REBALANCE, None
    return FORWARD, None


def is_chainable(graph: StreamGraph, up: Transformation,
                 down: Transformation, par: Dict[int, int],
                 upstream_partition: Optional[str],
                 respect_parallelism: bool = True) -> bool:
    """reference: StreamingJobGraphGenerator.isChainable — one-to-one
    forward edge, equal parallelism, single input on the downstream side."""
    if len(down.inputs) != 1 or len(graph.children(up)) != 1:
        return False
    # with respect_parallelism off (stage planning), per-operator
    # parallelism is advisory — stages get their counts from config, so
    # a same-key edge stays forward regardless of the advisory values
    same_par = (not respect_parallelism) or par[up.uid] == par[down.uid]
    ship, _ = _edge_ship(down, upstream_partition, same_parallelism=same_par)
    if ship != FORWARD:
        return False
    return same_par


def build_job_graph(graph: StreamGraph,
                    default_parallelism: int = 1,
                    respect_parallelism: bool = True) -> JobGraph:
    """Greedy chaining along topological order (each transformation joins
    its upstream's chain when chainable, else starts a new vertex).

    ``respect_parallelism=False`` chains across parallelism mismatches —
    the stage planner uses it because each stage's subtask count comes
    from config (source/stage parallelism), not per-operator settings."""
    part = _partitioning(graph)
    par = _resolve_parallelisms(graph, default_parallelism)
    vertex_of: Dict[int, JobVertex] = {}
    vertices: List[JobVertex] = []
    for t in graph.nodes:
        up = t.inputs[0] if len(t.inputs) == 1 else None
        if up is not None and up.uid in vertex_of and \
                vertex_of[up.uid].tail.uid == up.uid and \
                is_chainable(graph, up, t, par, part.get(up.uid),
                             respect_parallelism):
            v = vertex_of[up.uid]
            v.chained.append(t)
            if t.keyed and v.key_field is None:
                v.key_field = t.key_field
        else:
            v = JobVertex(vid=len(vertices), chained=[t],
                          parallelism=par[t.uid],
                          key_field=t.key_field if t.keyed else None)
            vertices.append(v)
        vertex_of[t.uid] = v
    edges: List[JobEdge] = []
    for t in graph.nodes:
        for inp in t.inputs:
            sv, tv = vertex_of[inp.uid], vertex_of[t.uid]
            if sv.vid == tv.vid:
                continue  # chained: direct call, no exchange
            ship, key = _edge_ship(
                t, part.get(inp.uid),
                same_parallelism=par[inp.uid] == par[t.uid])
            edges.append(JobEdge(sv.vid, tv.vid, ship, key, t.side_tag))
    return JobGraph(vertices, edges)


# ---------------------------------------------------------------------------
# ExecutionGraph: subtask expansion
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExecutionVertex:
    """One subtask of a JobVertex (reference: ExecutionVertex — the unit
    Execution.deploy ships to a slot)."""

    vertex: JobVertex
    subtask_index: int
    #: inclusive key-group range owned by this subtask (None: not keyed)
    key_group_range: Optional[Tuple[int, int]] = None

    @property
    def name(self) -> str:
        return (f"{self.vertex.name} "
                f"({self.subtask_index + 1}/{self.vertex.parallelism})")


class ExecutionGraph:
    """JobGraph expanded subtask-by-subtask with key-group assignment
    (reference: DefaultExecutionGraph.attachJobGraph +
    KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex)."""

    def __init__(self, job_graph: JobGraph, max_parallelism: int = 128):
        from flink_tpu.state.keygroups import compute_key_group_range

        self.job_graph = job_graph
        self.max_parallelism = max_parallelism
        self.execution_vertices: List[ExecutionVertex] = []
        for v in job_graph.vertices:
            for i in range(v.parallelism):
                kgr = None
                if v.key_field is not None:
                    kgr = compute_key_group_range(
                        max_parallelism, v.parallelism, i)
                self.execution_vertices.append(
                    ExecutionVertex(v, i, key_group_range=kgr))

    def subtasks_of(self, v: JobVertex) -> List[ExecutionVertex]:
        return [ev for ev in self.execution_vertices
                if ev.vertex.vid == v.vid]
