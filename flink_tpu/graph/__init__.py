from flink_tpu.graph.transformations import Transformation, StreamGraph

__all__ = ["Transformation", "StreamGraph"]
