"""JAX platform selection helper.

Some environments install a sitecustomize hook that force-registers an
accelerator backend and sets ``jax_platforms`` via ``jax.config`` at
interpreter start — which silently overrides the ``JAX_PLATFORMS`` env var.
``sync_platform()`` re-asserts the env var (when set) so drivers, benchmarks
and tests get the backend they asked for.
"""

from __future__ import annotations

import os


def sync_platform() -> None:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        try:
            jax.config.update("jax_platforms", p)
        except Exception:
            pass
