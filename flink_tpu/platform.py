"""JAX platform selection + compilation-cache setup.

Some environments install a sitecustomize hook that force-registers an
accelerator backend and sets ``jax_platforms`` via ``jax.config`` at
interpreter start — which silently overrides the ``JAX_PLATFORMS`` env var.
``sync_platform()`` re-asserts the env var (when set) so drivers, benchmarks
and tests get the backend they asked for.

It also enables JAX's persistent compilation cache (XLA compiles dominate
cold-start cost on remote/tunneled TPU backends — several seconds per
program shape). The cache directory defaults to ``.jax_cache`` next to this
package; override with ``FLINK_TPU_COMPILE_CACHE=<dir>`` or disable with
``FLINK_TPU_COMPILE_CACHE=off``.
"""

from __future__ import annotations

import os

_cache_enabled = False


def enable_compilation_cache() -> None:
    global _cache_enabled
    if _cache_enabled:
        return
    setting = os.environ.get("FLINK_TPU_COMPILE_CACHE", "")
    if setting.lower() in ("off", "0", "false", "none"):
        return
    cache_dir = setting or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _cache_enabled = True
    except Exception:
        pass


def sync_platform() -> None:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        try:
            jax.config.update("jax_platforms", p)
        except Exception:
            pass
    enable_compilation_cache()
