"""JAX platform selection + compilation-cache setup.

Some environments install a sitecustomize hook that force-registers an
accelerator backend and sets ``jax_platforms`` via ``jax.config`` at
interpreter start — which silently overrides the ``JAX_PLATFORMS`` env var.
``sync_platform()`` re-asserts the env var (when set) so drivers, benchmarks
and tests get the backend they asked for.

It also enables JAX's persistent compilation cache (XLA compiles dominate
cold-start cost on remote/tunneled TPU backends — several seconds per
program shape). The cache directory defaults to ``.jax_cache`` next to this
package; override with ``FLINK_TPU_COMPILE_CACHE=<dir>`` or disable with
``FLINK_TPU_COMPILE_CACHE=off``.
"""

from __future__ import annotations

import os

_cache_enabled = False


def enable_compilation_cache() -> None:
    global _cache_enabled
    if _cache_enabled:
        return
    setting = os.environ.get("FLINK_TPU_COMPILE_CACHE", "")
    if setting.lower() in ("off", "0", "false", "none"):
        return
    cache_dir = setting or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _cache_enabled = True
    except Exception:
        pass


def sync_platform() -> None:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        try:
            jax.config.update("jax_platforms", p)
        except Exception:
            pass
    enable_compilation_cache()


#: memoized ensure_live_backend decision ("<platform>" once probed)
_live_backend = None


def _probe_cache_path(selection: str) -> str:
    import hashlib
    import tempfile

    h = hashlib.sha1(selection.encode()).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(),
                        f"flink_tpu_backend_probe_{h}.json")


def _read_probe_cache(selection: str):
    """Cross-process probe verdict ("live"/"dead") if fresh, else None."""
    import json
    import time

    ttl = float(os.environ.get("FLINK_TPU_BACKEND_PROBE_CACHE_TTL", 300))
    if ttl <= 0:
        return None
    try:
        with open(_probe_cache_path(selection)) as f:
            d = json.load(f)
        if time.time() - d["ts"] <= ttl and d.get("selection") == selection:
            return d["verdict"]
    except Exception:
        pass
    return None


def _write_probe_cache(selection: str, verdict: str) -> None:
    import json
    import time

    ttl = float(os.environ.get("FLINK_TPU_BACKEND_PROBE_CACHE_TTL", 300))
    if ttl <= 0:  # cache disabled: don't poison other processes either
        return
    try:
        path = _probe_cache_path(selection)
        tmp = path + f".{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"selection": selection, "verdict": verdict,
                       "ts": time.time()}, f)
        os.replace(tmp, path)
    except Exception:
        pass


def ensure_live_backend(timeout: float = 45.0) -> str:
    """Bounded accelerator-backend probe with CPU fallback.

    Remote/tunneled accelerator plugins can hang *indefinitely* inside
    native client creation when their transport is down (observed here:
    the relay refusing TCP while the plugin retries forever —
    ``tpu_results/diagnose_latest.json``). An ``env.execute()`` that
    trusts the configured platform then hangs before the first batch.

    This probes backend init in a SUBPROCESS (a hung native call cannot
    be cancelled in-process) with a bounded timeout; on failure it
    falls back to CPU via ``jax.config`` and returns "cpu". The result
    is memoized per process — callers can invoke it on every execute().

    Environment knobs: ``FLINK_TPU_BACKEND_PROBE_TIMEOUT`` overrides
    the timeout (seconds); ``FLINK_TPU_BACKEND_PROBE=off`` trusts the
    configured platform without probing (production clusters where the
    backend is known-good and first-init cost is owned elsewhere);
    ``FLINK_TPU_BACKEND_PROBE_CACHE_TTL`` (seconds, default 300)
    bounds how long a probe verdict is shared across processes via a
    marker file — so a fleet of short-lived processes pays the dead-
    backend timeout once per machine per TTL window, not once each.

    Returns the platform name compute will run on.

    reference analog: a TaskExecutor that cannot reach its accelerator
    fails fast and lets the scheduler reroute, rather than wedging the
    task thread (flink-runtime TaskExecutor startup fails loudly on
    unavailable managed memory/devices).
    """
    global _live_backend
    if _live_backend is not None:
        return _live_backend
    sync_platform()
    import jax

    if os.environ.get("FLINK_TPU_BACKEND_PROBE", "").lower() in (
            "off", "0", "false"):
        _live_backend = "unprobed"
        return _live_backend
    selection = os.environ.get("JAX_PLATFORMS") or ""
    try:
        selection = selection or (jax.config.jax_platforms or "")
    except Exception:
        pass
    first = selection.split(",")[0].strip().lower() if selection else ""
    if first in ("", "cpu"):
        _live_backend = first or "default"
        return _live_backend
    import subprocess
    import sys

    timeout = float(os.environ.get("FLINK_TPU_BACKEND_PROBE_TIMEOUT",
                                   timeout))
    cached = _read_probe_cache(selection)
    if cached is not None:
        if cached == "dead":
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            _live_backend = "cpu"
        else:
            _live_backend = first
        return _live_backend
    # the probe re-asserts the selection after import because
    # sitecustomize hooks may override it via jax.config (the exact
    # failure mode sync_platform exists for)
    code = (
        "import os, jax\n"
        f"jax.config.update('jax_platforms', {selection!r})\n"
        "jax.devices()\n"
        "print('BACKEND_LIVE')\n")
    ok = False
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
        ok = proc.returncode == 0 and "BACKEND_LIVE" in proc.stdout
    except Exception:
        ok = False
    _write_probe_cache(selection, "live" if ok else "dead")
    if ok:
        _live_backend = first
    else:
        import warnings

        warnings.warn(
            f"backend {first!r} failed to initialize within {timeout:.0f}s"
            " — falling back to CPU for this process (set "
            "FLINK_TPU_BACKEND_PROBE=off to trust the configured "
            "platform, FLINK_TPU_BACKEND_PROBE_TIMEOUT to wait longer)",
            RuntimeWarning, stacklevel=2)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        _live_backend = "cpu"
    return _live_backend
