"""DataStream V2 API — the reference's next-generation stream surface.

reference: flink-datastream-api
(flink-datastream-api/src/main/java/org/apache/flink/datastream/api/
ExecutionEnvironment.java, stream/NonKeyedPartitionStream.java,
stream/KeyedPartitionStream.java, stream/GlobalStream.java,
stream/BroadcastStream.java, function/OneInputStreamProcessFunction.java,
function/TwoInputNonBroadcastStreamProcessFunction.java,
function/TwoOutputStreamProcessFunction.java). The V2 design:
partitioning is a property of the STREAM TYPE (non-keyed / keyed /
global / broadcast), every transformation is ``process`` with a process
function receiving (input, output collector, partitioned context), and
side outputs are a second typed collector instead of OutputTags.

Batch-granular re-design (the house rule): process functions see whole
``RecordBatch``es; the two-output function receives two collectors;
keyed streams carry a key selector and expose keyed state + timers
through the context, exactly as V1's keyed process operator does — the
V2 facade maps onto the SAME engine (operators, state plane, executor),
so everything it runs inherits checkpointing, rescale, and the device
state plane. V1 and V2 programs can coexist in one process.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from flink_tpu.core.annotations import public
from flink_tpu.core.config import Configuration
from flink_tpu.core.records import RecordBatch


@public
class OneInputStreamProcessFunction:
    """reference: function/OneInputStreamProcessFunction.java —
    processRecord(record, output, ctx); here batch-granular."""

    def open(self, ctx) -> None:
        pass

    def process_batch(self, batch: RecordBatch, out, ctx) -> None:
        raise NotImplementedError

    def on_timer(self, key_ids, timestamps, out, ctx) -> None:
        pass

    def close(self) -> None:
        pass


@public
class TwoInputNonBroadcastStreamProcessFunction:
    """reference: function/TwoInputNonBroadcastStreamProcessFunction.java
    — processRecordFromFirstInput / processRecordFromSecondInput."""

    def open(self, ctx) -> None:
        pass

    def process_batch_first(self, batch, out, ctx) -> None:
        raise NotImplementedError

    def process_batch_second(self, batch, out, ctx) -> None:
        raise NotImplementedError

    def on_timer(self, key_ids, timestamps, out, ctx) -> None:
        pass

    def close(self) -> None:
        pass


@public
class TwoInputBroadcastStreamProcessFunction:
    """reference: function/TwoInputBroadcastStreamProcessFunction.java —
    the non-broadcast side is processed per partition, the broadcast
    side is delivered to every partition."""

    def open(self, ctx) -> None:
        pass

    def process_batch(self, batch, out, ctx, broadcast_state) -> None:
        raise NotImplementedError

    def process_broadcast_batch(self, batch, out, ctx,
                                broadcast_state) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


@public
class TwoOutputStreamProcessFunction:
    """reference: function/TwoOutputStreamProcessFunction.java —
    processRecord(record, output1, output2, ctx): typed side output as
    a SECOND COLLECTOR instead of V1's OutputTag."""

    def open(self, ctx) -> None:
        pass

    def process_batch(self, batch, out1, out2, ctx) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _Collector:
    """The V2 output collector; a thin adapter onto the V1 context."""

    def __init__(self, emit: Callable[[RecordBatch], None]):
        self._emit = emit

    def collect(self, batch: RecordBatch) -> None:
        if batch is not None and len(batch):
            self._emit(batch)


class _V2Context:
    """reference: context/PartitionedContext — state + timers for keyed
    partitions; watermark access everywhere."""

    def __init__(self, v1_ctx, keyed: bool):
        self._ctx = v1_ctx
        self._keyed = keyed

    @property
    def current_watermark(self) -> int:
        return self._ctx.current_watermark

    def timer_service(self):
        if not self._keyed:
            raise RuntimeError("timers require a KeyedPartitionStream")
        return self._ctx.timer_service()

    def state(self, descriptor):
        if not self._keyed:
            raise RuntimeError(
                "keyed state requires a KeyedPartitionStream")
        return self._ctx.state(descriptor)

    def async_state(self, descriptor):
        if not self._keyed:
            raise RuntimeError(
                "keyed state requires a KeyedPartitionStream")
        return self._ctx.async_state(descriptor)


def _wrap_one_input(fn: OneInputStreamProcessFunction, keyed: bool):
    """V2 function -> V1 ProcessFunction driving the same operator."""
    from flink_tpu.runtime.process import ProcessFunction

    class _Adapter(ProcessFunction):
        def open(self, ctx) -> None:
            fn.open(_V2Context(ctx, keyed) if ctx is not None else None)

        def process_batch(self, batch, ctx) -> None:
            fn.process_batch(batch, _Collector(ctx.collect),
                             _V2Context(ctx, keyed))

        def on_timer(self, key_ids, timestamps, ctx) -> None:
            fn.on_timer(key_ids, timestamps, _Collector(ctx.collect),
                        _V2Context(ctx, keyed))

        def close(self, ctx) -> None:
            fn.close()

    return _Adapter()


@public
class NonKeyedPartitionStream:
    """reference: stream/NonKeyedPartitionStream.java."""

    def __init__(self, v1_stream, keyed: bool = False):
        self._s = v1_stream
        self._keyed = keyed

    # -- transformations -----------------------------------------------------

    def process(self, fn) -> "NonKeyedPartitionStream":
        if isinstance(fn, TwoOutputStreamProcessFunction):
            raise TypeError("use process_two_output for two-output "
                            "functions (returns both streams)")
        out = self._s.process(_wrap_one_input(fn, self._keyed))
        return NonKeyedPartitionStream(out)

    def process_two_output(self, fn: TwoOutputStreamProcessFunction
                           ) -> tuple:
        """Returns (main_stream, side_stream) — V2's typed second
        output, mapped onto the engine's side-output routing."""
        from flink_tpu.runtime.process import (
            OutputTag,
            ProcessFunction,
        )

        tag = OutputTag("v2-second-output")
        keyed = self._keyed

        class _Adapter(ProcessFunction):
            def open(self, ctx) -> None:
                fn.open(_V2Context(ctx, keyed) if ctx is not None
                        else None)

            def process_batch(self, batch, ctx) -> None:
                fn.process_batch(
                    batch, _Collector(ctx.collect),
                    _Collector(lambda b: ctx.output(tag, b)),
                    _V2Context(ctx, keyed))

            def close(self, ctx) -> None:
                fn.close()

        main = self._s.process(_Adapter())
        side = main.get_side_output(tag)
        return (NonKeyedPartitionStream(main),
                NonKeyedPartitionStream(side))

    def connect_and_process(self, other, fn) -> "NonKeyedPartitionStream":
        """reference: NonKeyedPartitionStream.connectAndProcess — two
        plain inputs, or a BroadcastStream second input."""
        if isinstance(other, BroadcastStream):
            return other._connect(self, fn)
        keyed = self._keyed
        if keyed != other._keyed:
            raise TypeError(
                "connectAndProcess requires both streams keyed or both "
                "non-keyed (reference: KeyedPartitionStream connects "
                "with another KeyedPartitionStream)")
        from flink_tpu.runtime.process import CoProcessFunction

        class _Adapter(CoProcessFunction):
            def open(self, ctx) -> None:
                fn.open(_V2Context(ctx, keyed) if ctx is not None
                        else None)

            def process_batch1(self, batch, ctx) -> None:
                fn.process_batch_first(batch, _Collector(ctx.collect),
                                       _V2Context(ctx, keyed))

            def process_batch2(self, batch, ctx) -> None:
                fn.process_batch_second(batch, _Collector(ctx.collect),
                                        _V2Context(ctx, keyed))

            def on_timer(self, key_ids, timestamps, ctx) -> None:
                fn.on_timer(key_ids, timestamps,
                            _Collector(ctx.collect),
                            _V2Context(ctx, keyed))

            def close(self, ctx) -> None:
                fn.close()

        connected = self._s.connect(other._s)
        if keyed:
            # the V1 streams are already KeyedStreams; re-keying by the
            # same fields marks the ConnectedStreams keyed so the
            # co-process operator opens a state store
            connected = connected.key_by(self._s.key_field,
                                         other._s.key_field)
        out = connected.process(_Adapter())
        return NonKeyedPartitionStream(out)

    # -- repartitioning ------------------------------------------------------

    def key_by(self, key_field: str) -> "KeyedPartitionStream":
        return KeyedPartitionStream(self._s.key_by(key_field))

    def global_(self) -> "GlobalStream":
        return GlobalStream(self._s)

    def broadcast(self) -> "BroadcastStream":
        return BroadcastStream(self._s)

    # -- sinks ---------------------------------------------------------------

    def to_sink(self, sink) -> None:
        self._s.sink_to(sink)


@public
class KeyedPartitionStream(NonKeyedPartitionStream):
    """reference: stream/KeyedPartitionStream.java — per-key partitions
    with keyed state + timers in the process context."""

    def __init__(self, v1_keyed_stream):
        super().__init__(v1_keyed_stream, keyed=True)

    def process(self, fn) -> NonKeyedPartitionStream:
        if isinstance(fn, TwoOutputStreamProcessFunction):
            raise TypeError("use process_two_output for two-output "
                            "functions (returns both streams)")
        out = self._s.process(_wrap_one_input(fn, True))
        return NonKeyedPartitionStream(out)

    # windows stay available on keyed streams (the V2 extension ships
    # window support as a built-in extension; here it is the engine's
    # native windowing)
    def window(self, assigner):
        return self._s.window(assigner)


@public
class GlobalStream(NonKeyedPartitionStream):
    """reference: stream/GlobalStream.java — all records in ONE
    partition. In this engine a non-keyed pipeline IS a single
    partition (subtask expansion applies to keyed stages), so the
    wrapper is the type-level marker the V2 API wants."""

    def __init__(self, v1_stream):
        super().__init__(v1_stream, keyed=False)


@public
class BroadcastStream:
    """reference: stream/BroadcastStream.java — every downstream
    partition sees every record; combined with a keyed/non-keyed stream
    via connectAndProcess."""

    def __init__(self, v1_stream):
        self._s = v1_stream

    def _connect(self, data: NonKeyedPartitionStream,
                 fn: TwoInputBroadcastStreamProcessFunction
                 ) -> NonKeyedPartitionStream:
        from flink_tpu.runtime.process import BroadcastProcessFunction

        class _Adapter(BroadcastProcessFunction):
            def open(self, ctx) -> None:
                fn.open(_V2Context(ctx, data._keyed)
                        if ctx is not None else None)

            def process_batch(self, batch, ctx, broadcast_state) -> None:
                fn.process_batch(batch, _Collector(ctx.collect),
                                 _V2Context(ctx, data._keyed),
                                 broadcast_state)

            def process_broadcast(self, batch, ctx,
                                  broadcast_state) -> None:
                fn.process_broadcast_batch(
                    batch, _Collector(ctx.collect),
                    _V2Context(ctx, data._keyed), broadcast_state)

            def close(self, ctx) -> None:
                fn.close()

        out = data._s.connect(self._s.broadcast()).process(_Adapter())
        return NonKeyedPartitionStream(out)


@public
class ExecutionEnvironment:
    """reference: ExecutionEnvironment.java — getInstance() +
    fromSource() + execute(). Wraps the V1 environment so both APIs
    share one engine, one config surface, one executor."""

    def __init__(self, config: Optional[Configuration] = None):
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )

        self._env = StreamExecutionEnvironment(config or Configuration({}))

    @staticmethod
    def get_instance(config: Optional[Configuration] = None
                     ) -> "ExecutionEnvironment":
        return ExecutionEnvironment(config)

    @property
    def config(self) -> Configuration:
        return self._env.config

    def from_source(self, source, watermark_strategy=None,
                    name: str = "v2-source") -> NonKeyedPartitionStream:
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        strategy = watermark_strategy or \
            WatermarkStrategy.for_bounded_out_of_orderness(0)
        return NonKeyedPartitionStream(
            self._env.from_source(source, strategy, name=name))

    def execute(self, job_name: str = "v2-job"):
        return self._env.execute(job_name)
