from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.datastream.stream import DataStream, KeyedStream, WindowedStream

__all__ = ["StreamExecutionEnvironment", "DataStream", "KeyedStream",
           "WindowedStream"]
