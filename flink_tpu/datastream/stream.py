"""The DataStream fluent API.

reference: streaming/api/datastream/DataStream.java, KeyedStream.java,
WindowedStream.java (e.g. WindowedStream.aggregate at
streaming/api/datastream/WindowedStream.java:310). The fluent surface is kept;
the semantics of each method build ``Transformation`` nodes that the executor
turns into batched operators.

User functions are vectorized (RecordBatch -> RecordBatch / mask); see
flink_tpu.runtime.operators.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import Transformation
from flink_tpu.runtime.operators import (
    FilterOperator,
    FlatMapOperator,
    KeyByOperator,
    MapOperator,
    SinkOperator,
    UnionOperator,
    WindowAggOperator,
)
from flink_tpu.windowing.aggregates import (
    AggregateFunction,
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MinAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import WindowAssigner

if TYPE_CHECKING:
    from flink_tpu.connectors.sinks import Sink
    from flink_tpu.datastream.environment import StreamExecutionEnvironment


class DataStream:
    def __init__(self, env: "StreamExecutionEnvironment",
                 transformation: Transformation):
        self.env = env
        self.transformation = transformation

    # ------------------------------------------------------------ stateless

    def map(self, fn: Callable[[RecordBatch], RecordBatch],
            name: str = "map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: MapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def filter(self, predicate: Callable[[RecordBatch], np.ndarray],
               name: str = "filter") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FilterOperator(predicate),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def flat_map(self, fn: Callable[[RecordBatch], List[RecordBatch]],
                 name: str = "flat_map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FlatMapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def union(self, *others: "DataStream") -> "DataStream":
        t = Transformation(
            name="union", kind="union",
            operator_factory=UnionOperator,
            inputs=[self.transformation] + [o.transformation for o in others])
        return DataStream(self.env, t)

    # ---------------------------------------------------------------- joins

    def join(self, other: "DataStream") -> "JoinedStreams":
        """Window equi-join builder (reference:
        streaming/api/datastream/JoinedStreams.java):
        ``a.join(b).where(f).equal_to(g).window(assigner).apply()``."""
        return JoinedStreams(self, other)

    # --------------------------------------------------------------- keying

    def key_by(self, key_field: str) -> "KeyedStream":
        t = Transformation(
            name=f"key_by({key_field})", kind="one_input",
            operator_factory=lambda: KeyByOperator(key_field),
            inputs=[self.transformation], keyed=True, key_field=key_field)
        return KeyedStream(self.env, t, key_field)

    # ---------------------------------------------------------------- sinks

    def sink_to(self, sink: "Sink", name: str = "sink") -> "DataStreamSink":
        t = Transformation(name=name, kind="sink",
                           operator_factory=lambda: SinkOperator(sink),
                           inputs=[self.transformation])
        self.env._sinks.append(t)
        return DataStreamSink(self.env, t, sink)

    def print(self, label: str = "") -> "DataStreamSink":
        from flink_tpu.connectors.sinks import PrintSink

        return self.sink_to(PrintSink(label), name="print")

    def execute_and_collect(self) -> RecordBatch:
        """Convenience: attach a collect sink, run, return the result batch."""
        from flink_tpu.connectors.sinks import CollectSink

        sink = CollectSink()
        self.sink_to(sink, name="collect")
        self.env.execute()
        return sink.result()


class DataStreamSink:
    def __init__(self, env, transformation, sink):
        self.env = env
        self.transformation = transformation
        self.sink = sink


class JoinedStreams:
    def __init__(self, left: DataStream, right: DataStream):
        self.left = left
        self.right = right
        self.left_key: Optional[str] = None
        self.right_key: Optional[str] = None

    def where(self, left_key: str) -> "JoinedStreams":
        self.left_key = left_key
        return self

    def equal_to(self, right_key: str) -> "JoinedStreams":
        self.right_key = right_key
        return self

    def window(self, assigner: WindowAssigner) -> "WindowedJoin":
        if self.left_key is None or self.right_key is None:
            raise ValueError(
                "call .where(left_key).equal_to(right_key) before .window()")
        return WindowedJoin(self, assigner)


class WindowedJoin:
    def __init__(self, joined: JoinedStreams, assigner: WindowAssigner):
        self.joined = joined
        self.assigner = assigner

    def apply(self, suffixes=("_l", "_r"), name: str = "window_join"
              ) -> DataStream:
        from flink_tpu.runtime.join_operators import WindowJoinOperator

        j = self.joined
        left_keyed = j.left.key_by(j.left_key).transformation
        right_keyed = j.right.key_by(j.right_key).transformation
        assigner = self.assigner
        key_fields = (j.left_key, j.right_key)
        t = Transformation(
            name=name, kind="two_input",
            operator_factory=lambda: WindowJoinOperator(
                assigner, suffixes, key_fields=key_fields),
            inputs=[left_keyed, right_keyed], keyed=True)
        return DataStream(j.left.env, t)


class KeyedStream(DataStream):
    def __init__(self, env, transformation, key_field: str):
        super().__init__(env, transformation)
        self.key_field = key_field

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    def interval_join(self, other: "KeyedStream") -> "IntervalJoinBuilder":
        """reference: KeyedStream.intervalJoin / IntervalJoinOperator."""
        return IntervalJoinBuilder(self, other)


class IntervalJoinBuilder:
    def __init__(self, left: "KeyedStream", right: "KeyedStream"):
        self.left = left
        self.right = right

    def between(self, lower_ms: int, upper_ms: int,
                suffixes=("_l", "_r")) -> DataStream:
        from flink_tpu.runtime.join_operators import IntervalJoinOperator

        t = Transformation(
            name="interval_join", kind="two_input",
            operator_factory=lambda: IntervalJoinOperator(
                lower_ms, upper_ms, suffixes),
            inputs=[self.left.transformation, self.right.transformation],
            keyed=True)
        return DataStream(self.left.env, t)

    # keyed running aggregates without windows (KeyedStream.sum/reduce in the
    # reference) can be expressed as a GlobalWindow; deferred to the table
    # runtime's GroupAggOperator equivalent.


class WindowedStream:
    """reference: streaming/api/datastream/WindowedStream.java."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self.keyed = keyed
        self.assigner = assigner
        self._allowed_lateness = 0

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._allowed_lateness = ms
        return self

    def aggregate(self, agg: AggregateFunction,
                  name: Optional[str] = None) -> DataStream:
        env = self.keyed.env
        capacity = env.state_slot_capacity
        key_field = self.keyed.key_field
        assigner = self.assigner
        lateness = self._allowed_lateness
        if getattr(assigner, "is_merging", False):
            from flink_tpu.runtime.operators import SessionWindowAggOperator

            gap = assigner.gap
            factory = lambda: SessionWindowAggOperator(  # noqa: E731
                gap, agg, key_field, capacity=capacity,
                allowed_lateness=lateness)
        else:
            factory = lambda: WindowAggOperator(  # noqa: E731
                assigner, agg, key_field, capacity=capacity,
                allowed_lateness=lateness)
        t = Transformation(
            name=name or f"window_agg({type(agg).__name__})",
            kind="one_input",
            operator_factory=factory,
            inputs=[self.keyed.transformation],
            keyed=True, key_field=key_field)
        return DataStream(env, t)

    # SQL-ish shorthands
    def sum(self, field: str) -> DataStream:
        return self.aggregate(SumAggregate(field))

    def count(self) -> DataStream:
        return self.aggregate(CountAggregate())

    def max(self, field: str) -> DataStream:
        return self.aggregate(MaxAggregate(field))

    def min(self, field: str) -> DataStream:
        return self.aggregate(MinAggregate(field))

    def avg(self, field: str) -> DataStream:
        return self.aggregate(AvgAggregate(field))

    def aggregate_all(self, aggs: Sequence[AggregateFunction]) -> DataStream:
        return self.aggregate(MultiAggregate(aggs))
