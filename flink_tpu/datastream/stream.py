"""The DataStream fluent API.

reference: streaming/api/datastream/DataStream.java, KeyedStream.java,
WindowedStream.java (e.g. WindowedStream.aggregate at
streaming/api/datastream/WindowedStream.java:310). The fluent surface is kept;
the semantics of each method build ``Transformation`` nodes that the executor
turns into batched operators.

User functions are vectorized (RecordBatch -> RecordBatch / mask); see
flink_tpu.runtime.operators.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import Transformation
from flink_tpu.runtime.operators import (
    FilterOperator,
    FlatMapOperator,
    KeyByOperator,
    MapOperator,
    SinkOperator,
    UnionOperator,
    WindowAggOperator,
)
from flink_tpu.windowing.aggregates import (
    AggregateFunction,
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MinAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import WindowAssigner

if TYPE_CHECKING:
    from flink_tpu.connectors.sinks import Sink
    from flink_tpu.datastream.environment import StreamExecutionEnvironment


class DataStream:
    def __init__(self, env: "StreamExecutionEnvironment",
                 transformation: Transformation):
        self.env = env
        self.transformation = transformation

    # ------------------------------------------------------------ stateless

    def map(self, fn: Callable[[RecordBatch], RecordBatch],
            name: str = "map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: MapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def filter(self, predicate: Callable[[RecordBatch], np.ndarray],
               name: str = "filter") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FilterOperator(predicate),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def flat_map(self, fn: Callable[[RecordBatch], List[RecordBatch]],
                 name: str = "flat_map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FlatMapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def union(self, *others: "DataStream") -> "DataStream":
        t = Transformation(
            name="union", kind="union",
            operator_factory=UnionOperator,
            inputs=[self.transformation] + [o.transformation for o in others])
        return DataStream(self.env, t)

    # --------------------------------------------------------------- keying

    def key_by(self, key_field: str) -> "KeyedStream":
        t = Transformation(
            name=f"key_by({key_field})", kind="one_input",
            operator_factory=lambda: KeyByOperator(key_field),
            inputs=[self.transformation], keyed=True, key_field=key_field)
        return KeyedStream(self.env, t, key_field)

    # ---------------------------------------------------------------- sinks

    def sink_to(self, sink: "Sink", name: str = "sink") -> "DataStreamSink":
        sink.open()
        t = Transformation(name=name, kind="sink",
                           operator_factory=lambda: SinkOperator(sink.write),
                           inputs=[self.transformation])
        self.env._sinks.append(t)
        return DataStreamSink(self.env, t, sink)

    def print(self, label: str = "") -> "DataStreamSink":
        from flink_tpu.connectors.sinks import PrintSink

        return self.sink_to(PrintSink(label), name="print")

    def execute_and_collect(self) -> RecordBatch:
        """Convenience: attach a collect sink, run, return the result batch."""
        from flink_tpu.connectors.sinks import CollectSink

        sink = CollectSink()
        self.sink_to(sink, name="collect")
        self.env.execute()
        return sink.result()


class DataStreamSink:
    def __init__(self, env, transformation, sink):
        self.env = env
        self.transformation = transformation
        self.sink = sink


class KeyedStream(DataStream):
    def __init__(self, env, transformation, key_field: str):
        super().__init__(env, transformation)
        self.key_field = key_field

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    # keyed running aggregates without windows (KeyedStream.sum/reduce in the
    # reference) can be expressed as a GlobalWindow; deferred to the table
    # runtime's GroupAggOperator equivalent.


class WindowedStream:
    """reference: streaming/api/datastream/WindowedStream.java."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self.keyed = keyed
        self.assigner = assigner
        self._allowed_lateness = 0

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._allowed_lateness = ms
        return self

    def aggregate(self, agg: AggregateFunction,
                  name: Optional[str] = None) -> DataStream:
        env = self.keyed.env
        capacity = env.state_slot_capacity
        key_field = self.keyed.key_field
        assigner = self.assigner
        lateness = self._allowed_lateness
        if getattr(assigner, "is_merging", False):
            from flink_tpu.runtime.operators import SessionWindowAggOperator

            gap = assigner.gap
            factory = lambda: SessionWindowAggOperator(  # noqa: E731
                gap, agg, key_field, capacity=capacity,
                allowed_lateness=lateness)
        else:
            factory = lambda: WindowAggOperator(  # noqa: E731
                assigner, agg, key_field, capacity=capacity,
                allowed_lateness=lateness)
        t = Transformation(
            name=name or f"window_agg({type(agg).__name__})",
            kind="one_input",
            operator_factory=factory,
            inputs=[self.keyed.transformation],
            keyed=True, key_field=key_field)
        return DataStream(env, t)

    # SQL-ish shorthands
    def sum(self, field: str) -> DataStream:
        return self.aggregate(SumAggregate(field))

    def count(self) -> DataStream:
        return self.aggregate(CountAggregate())

    def max(self, field: str) -> DataStream:
        return self.aggregate(MaxAggregate(field))

    def min(self, field: str) -> DataStream:
        return self.aggregate(MinAggregate(field))

    def avg(self, field: str) -> DataStream:
        return self.aggregate(AvgAggregate(field))

    def aggregate_all(self, aggs: Sequence[AggregateFunction]) -> DataStream:
        return self.aggregate(MultiAggregate(aggs))
