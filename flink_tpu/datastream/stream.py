"""The DataStream fluent API.

reference: streaming/api/datastream/DataStream.java, KeyedStream.java,
WindowedStream.java (e.g. WindowedStream.aggregate at
streaming/api/datastream/WindowedStream.java:310). The fluent surface is kept;
the semantics of each method build ``Transformation`` nodes that the executor
turns into batched operators.

User functions are vectorized (RecordBatch -> RecordBatch / mask); see
flink_tpu.runtime.operators.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import Transformation
from flink_tpu.runtime.operators import (
    FilterOperator,
    FlatMapOperator,
    KeyByOperator,
    MapOperator,
    SinkOperator,
    UnionOperator,
    WindowAggOperator,
)
from flink_tpu.windowing.aggregates import (
    AggregateFunction,
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MinAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import WindowAssigner

if TYPE_CHECKING:
    from flink_tpu.connectors.sinks import Sink
    from flink_tpu.datastream.environment import StreamExecutionEnvironment


from flink_tpu.core.annotations import public, public_evolving

@public
class DataStream:
    def __init__(self, env: "StreamExecutionEnvironment",
                 transformation: Transformation):
        self.env = env
        self.transformation = transformation

    def set_parallelism(self, parallelism: int) -> "DataStream":
        """Parallelism of this operator (reference:
        SingleOutputStreamOperator.setParallelism). For keyed window
        operators, parallelism N > 1 executes on an N-device mesh with
        state sharded over the key-group axis (MeshWindowEngine); the
        config default is ``parallelism.default``."""
        if parallelism < 1:
            raise ValueError(f"parallelism must be >= 1, got {parallelism}")
        self.transformation.parallelism = parallelism
        return self

    # ------------------------------------------------------------ stateless

    def map(self, fn: Callable[[RecordBatch], RecordBatch],
            name: str = "map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: MapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def ml_predict(self, model, input_fields=None, output_prefix: str = "",
                   asynchronous: bool = False, capacity: int = 4,
                   name: str = "ml_predict") -> "DataStream":
        """Batched model inference appending the model's output columns
        (reference: SQL ML_PREDICT / MLPredictRunner; flink-models). With
        ``asynchronous=True``, inference overlaps upstream work under a
        bounded in-flight budget (AsyncMLPredictRunner)."""
        from flink_tpu.ml.operators import (
            AsyncMLPredictOperator,
            MLPredictOperator,
        )

        if asynchronous:
            factory = lambda: AsyncMLPredictOperator(  # noqa: E731
                model, input_fields, output_prefix, capacity=capacity)
        else:
            factory = lambda: MLPredictOperator(  # noqa: E731
                model, input_fields, output_prefix)
        t = Transformation(name=name, kind="one_input",
                           operator_factory=factory,
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def filter(self, predicate: Callable[[RecordBatch], np.ndarray],
               name: str = "filter") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FilterOperator(predicate),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def flat_map(self, fn: Callable[[RecordBatch], List[RecordBatch]],
                 name: str = "flat_map") -> "DataStream":
        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: FlatMapOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def union(self, *others: "DataStream",
              _require_consistent_time: bool = False) -> "DataStream":
        """Merge streams. The DataStream API permits mixing timed and
        untimed inputs (valid when nothing downstream uses event time);
        SQL UNION ALL passes the strict flag because its result feeds
        relational operators that do."""
        t = Transformation(
            name="union", kind="union",
            operator_factory=lambda: UnionOperator(
                require_consistent_time=_require_consistent_time),
            inputs=[self.transformation] + [o.transformation for o in others])
        return DataStream(self.env, t)

    # ----------------------------------------------------- process functions

    def process(self, fn, name: str = "process") -> "DataStream":
        """Low-level processing with timers and side outputs
        (reference: DataStream.process ->
        streaming/api/operators/ProcessOperator.java)."""
        from flink_tpu.runtime.process import ProcessOperator

        t = Transformation(name=name, kind="one_input",
                           operator_factory=lambda: ProcessOperator(fn),
                           inputs=[self.transformation])
        return DataStream(self.env, t)

    def slot_sharing_group(self, name: str) -> "DataStream":
        """Put this transformation (and, by inheritance, its downstream
        chain) into slot sharing group ``name`` — subtasks of the SAME
        group share a slot, a distinct group forces additional slots
        (reference: DataStream.slotSharingGroup / SlotSharingGroup)."""
        self.transformation.slot_group = name
        return self

    def get_side_output(self, tag) -> "DataStream":
        """reference: SingleOutputStreamOperator.getSideOutput(OutputTag)."""
        from flink_tpu.runtime.process import OutputTag, SideOutputSelectOperator

        if isinstance(tag, str):
            tag = OutputTag(tag)
        t = Transformation(
            name=f"side_output({tag.name})", kind="one_input",
            operator_factory=lambda: SideOutputSelectOperator(tag),
            inputs=[self.transformation], side_tag=tag.name)
        return DataStream(self.env, t)

    def connect(self, other: "DataStream") -> "ConnectedStreams":
        """reference: DataStream.connect -> ConnectedStreams (co-process) or
        BroadcastConnectedStream when ``other`` is ``.broadcast()``."""
        if isinstance(other, BroadcastStream):
            return BroadcastConnectedStream(self, other)
        return ConnectedStreams(self, other)

    def broadcast(self) -> "BroadcastStream":
        """reference: DataStream.broadcast(MapStateDescriptor...)."""
        return BroadcastStream(self)

    # ---------------------------------------------------------------- joins

    def join(self, other: "DataStream") -> "JoinedStreams":
        """Window equi-join builder (reference:
        streaming/api/datastream/JoinedStreams.java):
        ``a.join(b).where(f).equal_to(g).window(assigner).apply()``."""
        return JoinedStreams(self, other)

    # --------------------------------------------------------------- keying

    def key_by(self, key_field: str) -> "KeyedStream":
        t = Transformation(
            name=f"key_by({key_field})", kind="one_input",
            operator_factory=lambda: KeyByOperator(key_field),
            inputs=[self.transformation], keyed=True, key_field=key_field)
        return KeyedStream(self.env, t, key_field)

    # ---------------------------------------------------------------- sinks

    def sink_to(self, sink: "Sink", name: str = "sink") -> "DataStreamSink":
        from flink_tpu.connectors.two_phase import (
            TwoPhaseCommitSink,
            TwoPhaseSinkOperator,
        )

        if isinstance(sink, TwoPhaseCommitSink):
            factory = lambda: TwoPhaseSinkOperator(sink)  # noqa: E731
        else:
            factory = lambda: SinkOperator(sink)  # noqa: E731
        t = Transformation(name=name, kind="sink",
                           operator_factory=factory,
                           inputs=[self.transformation])
        self.env._sinks.append(t)
        return DataStreamSink(self.env, t, sink)

    def print(self, label: str = "") -> "DataStreamSink":
        from flink_tpu.connectors.sinks import PrintSink

        return self.sink_to(PrintSink(label), name="print")

    def execute_and_collect(self) -> RecordBatch:
        """Convenience: attach a collect sink, run, return the result batch."""
        from flink_tpu.connectors.sinks import CollectSink

        sink = CollectSink()
        self.sink_to(sink, name="collect")
        self.env.execute()
        return sink.result()


class DataStreamSink:
    def __init__(self, env, transformation, sink):
        self.env = env
        self.transformation = transformation
        self.sink = sink


class JoinedStreams:
    def __init__(self, left: DataStream, right: DataStream):
        self.left = left
        self.right = right
        self.left_key: Optional[str] = None
        self.right_key: Optional[str] = None

    def where(self, left_key: str) -> "JoinedStreams":
        self.left_key = left_key
        return self

    def equal_to(self, right_key: str) -> "JoinedStreams":
        self.right_key = right_key
        return self

    def window(self, assigner: WindowAssigner) -> "WindowedJoin":
        if self.left_key is None or self.right_key is None:
            raise ValueError(
                "call .where(left_key).equal_to(right_key) before .window()")
        return WindowedJoin(self, assigner)


class WindowedJoin:
    def __init__(self, joined: JoinedStreams, assigner: WindowAssigner):
        self.joined = joined
        self.assigner = assigner

    def apply(self, suffixes=("_l", "_r"), name: str = "window_join"
              ) -> DataStream:
        from flink_tpu.runtime.join_operators import WindowJoinOperator

        j = self.joined
        left_keyed = j.left.key_by(j.left_key).transformation
        right_keyed = j.right.key_by(j.right_key).transformation
        assigner = self.assigner
        key_fields = (j.left_key, j.right_key)
        t = Transformation(
            name=name, kind="two_input",
            operator_factory=lambda: WindowJoinOperator(
                assigner, suffixes, key_fields=key_fields),
            inputs=[left_keyed, right_keyed], keyed=True)
        return DataStream(j.left.env, t)


class ConnectedStreams:
    """reference: streaming/api/datastream/ConnectedStreams.java."""

    def __init__(self, first: DataStream, second: DataStream):
        self.first = first
        self.second = second
        self._keyed = False

    def key_by(self, first_key: str, second_key: str) -> "ConnectedStreams":
        c = ConnectedStreams(self.first.key_by(first_key),
                             self.second.key_by(second_key))
        c._keyed = True
        return c

    def process(self, fn, name: str = "co_process") -> DataStream:
        from flink_tpu.runtime.process import CoProcessOperator

        keyed = self._keyed
        t = Transformation(
            name=name, kind="two_input",
            operator_factory=lambda: CoProcessOperator(fn, keyed=keyed),
            inputs=[self.first.transformation, self.second.transformation],
            keyed=keyed)
        return DataStream(self.first.env, t)

    def map(self, fn1, fn2, name: str = "co_map") -> DataStream:
        """CoMap: fn1 on the first input's batches, fn2 on the second's."""
        from flink_tpu.runtime.process import CoProcessFunction

        class _CoMap(CoProcessFunction):
            def process_batch1(self, batch, ctx):
                ctx.collect(fn1(batch))

            def process_batch2(self, batch, ctx):
                ctx.collect(fn2(batch))

        return self.process(_CoMap(), name=name)


class BroadcastStream:
    """Marker wrapper produced by DataStream.broadcast()."""

    def __init__(self, stream: DataStream):
        self.stream = stream


class BroadcastConnectedStream:
    """reference: streaming/api/datastream/BroadcastConnectedStream.java."""

    def __init__(self, data: DataStream, broadcast: BroadcastStream):
        self.data = data
        self.broadcast = broadcast

    def process(self, fn, name: str = "broadcast_process") -> DataStream:
        from flink_tpu.runtime.process import BroadcastProcessOperator

        keyed = isinstance(self.data, KeyedStream)
        bt = Transformation(
            name="broadcast", kind="one_input",
            operator_factory=lambda: UnionOperator(),
            inputs=[self.broadcast.stream.transformation], broadcast=True)
        t = Transformation(
            name=name, kind="two_input",
            operator_factory=lambda: BroadcastProcessOperator(fn, keyed=keyed),
            inputs=[self.data.transformation, bt], keyed=keyed)
        return DataStream(self.data.env, t)


@public_evolving
class AsyncDataStream:
    """reference: streaming/api/datastream/AsyncDataStream.java."""

    @staticmethod
    def _wait(stream: DataStream, fn, ordered: bool, timeout_ms, capacity,
              name: str) -> DataStream:
        from flink_tpu.runtime.async_operator import AsyncWaitOperator

        t = Transformation(
            name=name, kind="one_input",
            operator_factory=lambda: AsyncWaitOperator(
                fn, ordered=ordered, capacity=capacity,
                timeout_ms=timeout_ms),
            inputs=[stream.transformation])
        return DataStream(stream.env, t)

    @staticmethod
    def ordered_wait(stream: DataStream, fn, timeout_ms: int = None,
                     capacity: int = 8) -> DataStream:
        return AsyncDataStream._wait(stream, fn, True, timeout_ms, capacity,
                                     "async_wait_ordered")

    @staticmethod
    def unordered_wait(stream: DataStream, fn, timeout_ms: int = None,
                       capacity: int = 8) -> DataStream:
        return AsyncDataStream._wait(stream, fn, False, timeout_ms, capacity,
                                     "async_wait_unordered")


@public
class KeyedStream(DataStream):
    def __init__(self, env, transformation, key_field: str):
        super().__init__(env, transformation)
        self.key_field = key_field

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    def interval_join(self, other: "KeyedStream") -> "IntervalJoinBuilder":
        """reference: KeyedStream.intervalJoin / IntervalJoinOperator."""
        return IntervalJoinBuilder(self, other)

    def process(self, fn, name: str = "keyed_process") -> DataStream:
        """reference: KeyedStream.process ->
        streaming/api/operators/KeyedProcessOperator.java (state + timers)."""
        from flink_tpu.runtime.process import ProcessOperator

        capacity = self.env.state_slot_capacity
        t = Transformation(
            name=name, kind="one_input",
            operator_factory=lambda: ProcessOperator(
                fn, keyed=True, state_capacity=capacity),
            inputs=[self.transformation], keyed=True,
            key_field=self.key_field)
        return DataStream(self.env, t)

    # -- running (unwindowed) keyed aggregates -------------------------------
    # reference: KeyedStream.sum/min/max/reduce — continuous per-key
    # aggregation with upsert emission, executed by the same slot-table
    # GroupAggOperator the SQL layer uses.

    def reduce(self, agg: AggregateFunction, name: str = None) -> DataStream:
        from flink_tpu.runtime.group_agg import GroupAggOperator

        capacity = self.env.state_slot_capacity
        key_field = self.key_field
        t = Transformation(
            name=name or f"keyed_reduce({type(agg).__name__})",
            kind="one_input",
            operator_factory=lambda: GroupAggOperator(
                agg, key_field, capacity=capacity),
            inputs=[self.transformation], keyed=True, key_field=key_field)
        return DataStream(self.env, t)

    def sum(self, field: str) -> DataStream:
        return self.reduce(SumAggregate(field))

    def max(self, field: str) -> DataStream:
        return self.reduce(MaxAggregate(field))

    def min(self, field: str) -> DataStream:
        return self.reduce(MinAggregate(field))


class IntervalJoinBuilder:
    def __init__(self, left: "KeyedStream", right: "KeyedStream"):
        self.left = left
        self.right = right

    def between(self, lower_ms: int, upper_ms: int,
                suffixes=("_l", "_r")) -> DataStream:
        from flink_tpu.core.config import DeploymentOptions
        from flink_tpu.runtime.join_operators import IntervalJoinOperator

        env = self.left.env
        if env.config.get(DeploymentOptions.JOIN_MODE) == "device":
            # the device-native path: dual keyed slot tables on the
            # mesh, banded segment-intersection kernel per batch
            # (flink_tpu/joins/) — the host operator stays the
            # semantics oracle and the join.mode=host fallback
            from flink_tpu.joins.operators import (
                DeviceIntervalJoinOperator,
            )

            capacity = env.state_slot_capacity
            spill = env.state_spill_options
            factory = lambda: DeviceIntervalJoinOperator(  # noqa: E731
                lower_ms, upper_ms, suffixes, capacity=capacity,
                max_device_slots=spill["max_device_slots"],
                spill_dir=spill["spill_dir"],
                spill_host_max_bytes=spill["spill_host_max_bytes"])
        else:
            factory = lambda: IntervalJoinOperator(  # noqa: E731
                lower_ms, upper_ms, suffixes)
        t = Transformation(
            name="interval_join", kind="two_input",
            operator_factory=factory,
            inputs=[self.left.transformation, self.right.transformation],
            keyed=True)
        return DataStream(self.left.env, t)

    # keyed running aggregates without windows (KeyedStream.sum/reduce in the
    # reference) can be expressed as a GlobalWindow; deferred to the table
    # runtime's GroupAggOperator equivalent.


@public
class WindowedStream:
    """reference: streaming/api/datastream/WindowedStream.java."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self.keyed = keyed
        self.assigner = assigner
        self._allowed_lateness = 0

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._allowed_lateness = ms
        return self

    def aggregate(self, agg: AggregateFunction,
                  name: Optional[str] = None,
                  fire_projector=None) -> DataStream:
        """``fire_projector`` (flink_tpu.windowing.fire_projectors) reduces
        each fired window's rows ON DEVICE before host transfer — the fused
        form of a Top-N/arg-max consumer directly downstream."""
        env = self.keyed.env
        capacity = env.state_slot_capacity
        key_field = self.keyed.key_field
        assigner = self.assigner
        lateness = self._allowed_lateness
        if getattr(assigner, "is_merging", False):
            from flink_tpu.runtime.operators import SessionWindowAggOperator

            if fire_projector is not None:
                raise ValueError(
                    "fire_projector is not supported for merging (session) "
                    "windows yet — a session fire emits one row per "
                    "(key, merged window), not one batch per aligned window")
            gap = assigner.gap
            spill = env.state_spill_options
            backend = env.state_backend
            factory = lambda: SessionWindowAggOperator(  # noqa: E731
                gap, agg, key_field, capacity=capacity,
                allowed_lateness=lateness, spill=spill,
                state_backend=backend)
        else:
            spill = env.state_spill_options
            layout = env.window_layout
            backend = env.state_backend
            factory = lambda: WindowAggOperator(  # noqa: E731
                assigner, agg, key_field, capacity=capacity,
                allowed_lateness=lateness, spill=spill,
                fire_projector=fire_projector, window_layout=layout,
                state_backend=backend)
        t = Transformation(
            name=name or f"window_agg({type(agg).__name__})",
            kind="one_input",
            operator_factory=factory,
            inputs=[self.keyed.transformation],
            keyed=True, key_field=key_field)
        return DataStream(env, t)

    # SQL-ish shorthands
    def sum(self, field: str) -> DataStream:
        return self.aggregate(SumAggregate(field))

    def count(self) -> DataStream:
        return self.aggregate(CountAggregate())

    def max(self, field: str) -> DataStream:
        return self.aggregate(MaxAggregate(field))

    def min(self, field: str) -> DataStream:
        return self.aggregate(MinAggregate(field))

    def avg(self, field: str) -> DataStream:
        return self.aggregate(AvgAggregate(field))

    def aggregate_all(self, aggs: Sequence[AggregateFunction]) -> DataStream:
        return self.aggregate(MultiAggregate(aggs))
