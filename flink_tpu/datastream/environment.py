"""StreamExecutionEnvironment — the API entry point.

reference: streaming/api/environment/StreamExecutionEnvironment.java
(execute :1823, getStreamGraph :2020). Re-design: the environment collects
sink transformations, builds a StreamGraph and hands it to an executor
(local single-process by default — the MiniCluster analog; see
flink_tpu.cluster). Executors are pluggable like the reference's
PipelineExecutor SPI (flink-core/.../core/execution/PipelineExecutor.java).
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence

from flink_tpu.core.config import (
    BatchOptions,
    CheckpointOptions,
    DeploymentOptions,
    Configuration,
    CoreOptions,
    StateOptions,
)
from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import StreamGraph, Transformation
from flink_tpu.runtime.watermarks import WatermarkStrategy


from flink_tpu.core.annotations import public

@public
class StreamExecutionEnvironment:
    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()
        self._sinks: List[Transformation] = []
        #: JobExecutionResult of the most recent execute(), None before
        #: the first run — convenience wrappers (execute_and_collect,
        #: SQL collect) discard the result; callers that still want the
        #: job metrics (e.g. bench fire-latency percentiles) read this
        self.last_execution_result = None

    def _effective_config(self) -> Configuration:
        """CLI `-D` dynamic properties override programmatic config —
        applied at execute() time so they win over any mutator the script
        called after constructing the environment (reference: CliFrontend
        dynamic properties > user Configuration)."""
        import json
        import os

        raw = os.environ.get("FLINK_TPU_DYNAMIC_PROPS")
        if not raw:
            return self.config
        try:
            props = json.loads(raw)
        except ValueError:
            return self.config
        return Configuration(props).with_fallback(self.config)

    @staticmethod
    def get_execution_environment(
        config: Optional[Configuration] = None,
    ) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(config)

    # ------------------------------------------------------------- settings

    @property
    def parallelism(self) -> int:
        return self._effective_config().get(CoreOptions.DEFAULT_PARALLELISM)

    def set_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.config.set(CoreOptions.DEFAULT_PARALLELISM, p)
        return self

    @property
    def max_parallelism(self) -> int:
        return self.config.get(CoreOptions.MAX_PARALLELISM)

    @property
    def batch_size(self) -> int:
        return self._effective_config().get(BatchOptions.BATCH_SIZE)

    @property
    def state_slot_capacity(self) -> int:
        return self.config.get(StateOptions.SLOT_CAPACITY)

    @property
    def state_spill_options(self) -> dict:
        """Beyond-HBM spill knobs handed to keyed-state operators."""
        return {
            "max_device_slots": self.config.get(
                StateOptions.MAX_DEVICE_SLOTS),
            "spill_dir": self.config.get(StateOptions.SPILL_DIR),
            "spill_host_max_bytes": self.config.get(
                StateOptions.SPILL_HOST_MAX_BYTES),
        }

    @property
    def window_layout(self) -> str:
        """state.window-layout: 'slots' | 'panes' | 'auto'."""
        return self.config.get(StateOptions.WINDOW_LAYOUT)

    @property
    def shuffle_mode(self) -> str:
        """shuffle.mode: 'device' (in-program keyBy exchange, default)
        | 'host' (explicit [shards, B] bucketing fallback)."""
        return self.config.get(DeploymentOptions.SHUFFLE_MODE)

    @property
    def state_backend(self) -> str:
        """state.backend: keyed-state placement (flink_tpu.state.backends)."""
        return self.config.get(StateOptions.BACKEND)

    def enable_checkpointing(self, interval_ms: int) -> "StreamExecutionEnvironment":
        self.config.set(CheckpointOptions.INTERVAL_MS, interval_ms)
        return self

    # -------------------------------------------------------------- sources

    def add_source(self, source, watermark_strategy: Optional[WatermarkStrategy]
                   = None, name: Optional[str] = None):
        from flink_tpu.datastream.stream import DataStream

        t = Transformation(
            name=name or type(source).__name__, kind="source",
            source=source,
            watermark_strategy=watermark_strategy
            or WatermarkStrategy.for_monotonous_timestamps())
        return DataStream(self, t)

    def from_source(self, source, watermark_strategy=None, name=None):
        return self.add_source(source, watermark_strategy, name)

    def from_collection(self, rows: Iterable[dict],
                        timestamp_field: Optional[str] = None,
                        watermark_strategy: Optional[WatermarkStrategy] = None):
        from flink_tpu.connectors.sources import CollectionSource

        src = CollectionSource.of_rows(rows, batch_size=self.batch_size)
        ws = watermark_strategy or WatermarkStrategy.for_monotonous_timestamps()
        if timestamp_field is not None:
            ws = ws.with_timestamp_field(timestamp_field)
        return self.add_source(src, ws, name="collection")

    def from_batches(self, batches: Sequence[RecordBatch],
                     watermark_strategy: Optional[WatermarkStrategy] = None):
        from flink_tpu.connectors.sources import CollectionSource

        return self.add_source(CollectionSource(list(batches)),
                               watermark_strategy, name="batches")

    # ------------------------------------------------------------ execution

    def get_stream_graph(self) -> StreamGraph:
        if not self._sinks:
            raise RuntimeError("no sinks defined — nothing to execute")
        return StreamGraph(self._sinks)

    def execute(self, job_name: str = "job",
                restore_from: Optional[str] = None,
                restore_mode: str = "no-claim") -> "JobExecutionResult":
        """Run the pipeline. ``restore_from`` points at a checkpoint root
        directory (latest completed checkpoint wins) or directly at a
        savepoint / single checkpoint directory. ``restore_mode`` is
        "no-claim" (default: the artifact stays user-owned and untouched) or
        "claim" (the job owns it and deletes it once subsumed) —
        reference: savepoint/restore CLI flow + claim modes."""
        import os

        if restore_from is None:  # CLI `run --restore` injects via env
            restore_from = os.environ.get("FLINK_TPU_RESTORE_FROM") or None
            restore_mode = os.environ.get("FLINK_TPU_RESTORE_MODE",
                                          restore_mode)
        graph = self.get_stream_graph()
        # bounded backend probe + CPU fallback BEFORE the first
        # device-touching op (but after cheap graph validation, so a
        # user error like "no sinks" doesn't pay the probe timeout):
        # a dead accelerator transport must degrade the job to CPU,
        # not hang it (see platform.ensure_live_backend)
        from flink_tpu.platform import ensure_live_backend

        ensure_live_backend()
        config = self._effective_config()
        # subtask-expansion mode (execution.stage-parallelism > 0) expands
        # the pipeline into source + keyed subtasks wired by the shuffle
        # SPI; unsupported shapes fall back to single-slot with a warning
        # (reference: ExecutionGraph parallel expansion / Execution.deploy)
        from flink_tpu.cluster.stage_executor import make_executor

        executor = make_executor(config, graph)
        result = executor.run(graph, job_name=job_name,
                              restore_from=restore_from,
                              restore_mode=restore_mode)
        self._sinks = []
        #: kept for callers that run through a convenience wrapper
        #: (execute_and_collect, SQL collect) and still want the job
        #: metrics — e.g. the bench suite's fire-latency percentiles
        self.last_execution_result = result
        return result


@public
class JobExecutionResult:
    def __init__(self, job_name: str, metrics: dict):
        self.job_name = job_name
        self.metrics = metrics
        #: MetricRegistry with the job's operator-scoped metrics
        self.registry = None
        #: TraceCollector with checkpoint/recovery spans
        self.traces = None

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"JobExecutionResult({self.job_name}, {self.metrics})"
