"""Command-line frontend.

reference: flink-clients CliFrontend (bin/flink run / list / info / cancel /
savepoint / stop) — the operational surface an operator scripts against.
Re-design: `run` executes a Python pipeline script with -D dynamic
properties and restore flags injected through the environment (the
reference injects dynamic properties into the client Configuration the
same way); cluster actions talk to the MiniCluster REST API.

    flink-tpu run pipeline.py -D execution.micro-batch.size=65536
    flink-tpu run pipeline.py --restore /ckpts/job --restore-mode claim
    flink-tpu list            --rest 127.0.0.1:8081
    flink-tpu info   <job-id> --rest ...
    flink-tpu cancel <job-id> --rest ...
    flink-tpu savepoint <job-id> /path [--stop] [--drain] --rest ...
    flink-tpu query  <job-id> <operator> <key> [--namespace N] --rest ...
    flink-tpu inspect /path/to/snapshot
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.parse
import urllib.request

#: env vars `run` uses to hand flags to StreamExecutionEnvironment
DYNAMIC_PROPS_ENV = "FLINK_TPU_DYNAMIC_PROPS"
RESTORE_FROM_ENV = "FLINK_TPU_RESTORE_FROM"
RESTORE_MODE_ENV = "FLINK_TPU_RESTORE_MODE"


def _http(url: str, body: dict = None):
    if body is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read() or b"{}")


def _base(args) -> str:
    rest = args.rest
    if "://" not in rest:
        rest = "http://" + rest
    return rest.rstrip("/")


def _parse_defines(defines) -> dict:
    """-D key=value pairs; malformed input exits 2 with a message (one
    parser for every subcommand)."""
    props = {}
    for d in defines or []:
        if "=" not in d:
            print(f"-D expects key=value, got {d!r}", file=sys.stderr)
            raise SystemExit(2)
        k, v = d.split("=", 1)
        props[k] = v
    return props


def cmd_run(args) -> int:
    props = _parse_defines(args.define)
    overrides = {}
    if props:
        overrides[DYNAMIC_PROPS_ENV] = json.dumps(props)
    if args.restore:
        overrides[RESTORE_FROM_ENV] = args.restore
        overrides[RESTORE_MODE_ENV] = args.restore_mode
    import runpy

    prior = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    argv_prior = sys.argv
    sys.argv = [args.script] + (args.script_args or [])
    try:
        runpy.run_path(args.script, run_name="__main__")
    finally:
        sys.argv = argv_prior
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return 0


def cmd_list(args) -> int:
    jobs = _http(f"{_base(args)}/jobs")["jobs"]
    for j in jobs:
        print(f"{j['job_id']}  {j['status']:<10}  attempt={j.get('attempt')}"
              f"  {j.get('name', '')}")
    if not jobs:
        print("(no jobs)")
    return 0


def cmd_info(args) -> int:
    print(json.dumps(_http(f"{_base(args)}/jobs/{args.job_id}"), indent=2))
    return 0


def cmd_cancel(args) -> int:
    out = _http(f"{_base(args)}/jobs/{args.job_id}/cancel", body={})
    print(json.dumps(out))
    return 0


def cmd_savepoint(args) -> int:
    out = _http(f"{_base(args)}/jobs/{args.job_id}/savepoints",
                body={"target": args.target, "stop": args.stop,
                      "drain": args.drain})
    print(json.dumps(out))
    return 0


def cmd_query(args) -> int:
    q = {"key": args.key, "key-type": args.key_type}
    if args.namespace is not None:
        q["namespace"] = str(args.namespace)
    op = urllib.parse.quote(args.operator, safe="")
    url = (f"{_base(args)}/jobs/{args.job_id}/state/{op}"
           f"?{urllib.parse.urlencode(q)}")
    print(json.dumps(_http(url), indent=2))
    return 0


def cmd_inspect(args) -> int:
    from flink_tpu.state_processor import SavepointReader

    reader = SavepointReader.load(args.path)
    print(f"snapshot: {reader.path}")
    print(f"job: {reader.job_name}  checkpoint_id: {reader.checkpoint_id}")
    for uid in reader.operators():
        state = reader.read_state(uid)
        if "source" in state:
            print(f"  {uid}: source position {state['source']}")
        elif reader.has_keyed_state(uid):
            batch = reader.read_keyed_state(uid)
            print(f"  {uid}: keyed state, {len(batch)} rows, "
                  f"columns {sorted(batch.columns)}")
        else:
            print(f"  {uid}: host state, keys {sorted(state)}")
    return 0


def _props_config(defines):
    from flink_tpu.core.config import Configuration

    return Configuration(_parse_defines(defines))


def cmd_jobmanager(args) -> int:
    """Standalone JobManager process (reference:
    StandaloneSessionClusterEntrypoint / jobmanager.sh)."""
    from flink_tpu.cluster.standalone import run_jobmanager
    from flink_tpu.platform import sync_platform

    sync_platform()  # honor JAX_PLATFORMS even under sitecustomize hooks

    cfg = _props_config(args.define)
    # explicit flags win; -D wins over the built-in defaults
    if args.port is not None:
        cfg.set("rpc.port", args.port)
    elif cfg.get_raw("rpc.port") is None:
        cfg.set("rpc.port", 6123)
    if args.rest_port is not None:
        cfg.set("rest.port", args.rest_port)
    elif cfg.get_raw("rest.port") is None:
        cfg.set("rest.port", 8081)
    run_jobmanager(cfg)
    return 0


def cmd_taskexecutor(args) -> int:
    """Standalone TaskExecutor process (reference: TaskManagerRunner /
    taskmanager.sh)."""
    from flink_tpu.cluster.standalone import TaskExecutorRunner
    from flink_tpu.platform import sync_platform

    sync_platform()  # honor JAX_PLATFORMS even under sitecustomize hooks

    cfg = _props_config(args.define)
    if args.slots is not None:
        cfg.set("taskmanager.numberOfTaskSlots", args.slots)
    runner = TaskExecutorRunner(args.jobmanager, cfg)
    print(f"taskexecutor {runner.executor_id} rpc on {runner.address}, "
          f"registering with {args.jobmanager}", flush=True)
    runner.run_forever()
    return 0


def cmd_deploy(args) -> int:
    """Kubernetes deployment driver (reference:
    KubernetesClusterDescriptor / KubernetesResourceManagerDriver)."""
    import json as _json

    from flink_tpu.cluster.deployment import (
        KubectlClient,
        KubernetesDeployment,
    )

    if args.action == "scale" and args.task_executors is None:
        print("deploy scale requires an explicit --task-executors count "
              "(refusing to silently scale to a default)",
              file=sys.stderr)
        return 2
    dep = KubernetesDeployment(
        args.cluster_id, config=_props_config(args.define),
        image=args.image,
        task_executors=(args.task_executors
                        if args.task_executors is not None else 2),
        slots_per_executor=args.slots,
        tpus_per_executor=args.tpus_per_executor,
        tpu_accelerator=args.tpu_accelerator,
        tpu_topology=args.tpu_topology,
        client=KubectlClient(namespace=args.namespace))
    if args.action == "kubernetes":
        if args.dry_run:
            for m in dep.manifests():
                print(_json.dumps(m, indent=2))
            return 0
        dep.deploy()
        print(f"deployed {dep.jm_name} + {dep.te_name} "
              f"(x{args.task_executors})")
    elif args.action == "scale":
        dep.scale_task_executors(args.task_executors)
        print(f"scaled {dep.te_name} to {args.task_executors}")
    else:
        dep.teardown()
        print(f"tore down cluster {args.cluster_id}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="flink-tpu",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    pj = sub.add_parser("jobmanager",
                        help="run a standalone JobManager process")
    pj.add_argument("--port", type=int, default=None,
                    help="control-plane gRPC port (default 6123; "
                    "-D rpc.port=... also works)")
    pj.add_argument("--rest-port", type=int, default=None,
                    help="REST port (default 8081)")
    pj.add_argument("-D", dest="define", action="append", metavar="K=V")
    pj.set_defaults(fn=cmd_jobmanager)

    pt = sub.add_parser("taskexecutor",
                        help="run a standalone TaskExecutor process")
    pt.add_argument("--jobmanager", default="127.0.0.1:6123",
                    help="JobManager rpc address host:port")
    pt.add_argument("--slots", type=int, default=None)
    pt.add_argument("-D", dest="define", action="append", metavar="K=V")
    pt.set_defaults(fn=cmd_taskexecutor)

    pk = sub.add_parser(
        "deploy", help="deploy / scale / tear down a Kubernetes cluster "
        "(reference: flink-kubernetes session deployment)")
    pk.add_argument("action", choices=["kubernetes", "scale", "teardown"])
    pk.add_argument("cluster_id")
    pk.add_argument("--image", default="flink-tpu:latest")
    pk.add_argument("--task-executors", type=int, default=None,
                    help="worker replica count (default 2 for deploy; "
                    "REQUIRED for scale)")
    pk.add_argument("--slots", type=int, default=1)
    pk.add_argument("--tpus-per-executor", type=int, default=0,
                    help="google.com/tpu devices each worker pod requests")
    pk.add_argument("--tpu-accelerator", default="tpu-v5-lite-podslice")
    pk.add_argument("--tpu-topology", default="1x1")
    pk.add_argument("--namespace", default="default")
    pk.add_argument("--dry-run", action="store_true",
                    help="print the manifests instead of applying them")
    pk.add_argument("-D", dest="define", action="append", metavar="K=V")
    pk.set_defaults(fn=cmd_deploy)

    pr = sub.add_parser("run", help="run a pipeline script")
    pr.add_argument("script")
    pr.add_argument("script_args", nargs="*")
    pr.add_argument("-D", dest="define", action="append", metavar="K=V",
                    help="dynamic config property (repeatable)")
    pr.add_argument("--restore", help="checkpoint root / savepoint to "
                    "restore from")
    pr.add_argument("--restore-mode", default="no-claim",
                    choices=["no-claim", "claim"])
    pr.set_defaults(fn=cmd_run)

    for name, fn in (("list", cmd_list),):
        ps = sub.add_parser(name, help="list cluster jobs")
        ps.add_argument("--rest", default="127.0.0.1:8081")
        ps.set_defaults(fn=fn)

    for name, fn in (("info", cmd_info), ("cancel", cmd_cancel)):
        ps = sub.add_parser(name, help=f"{name} a job")
        ps.add_argument("job_id")
        ps.add_argument("--rest", default="127.0.0.1:8081")
        ps.set_defaults(fn=fn)

    ps = sub.add_parser("savepoint", help="trigger (or stop with) savepoint")
    ps.add_argument("job_id")
    ps.add_argument("target")
    ps.add_argument("--stop", action="store_true")
    ps.add_argument("--drain", action="store_true")
    ps.add_argument("--rest", default="127.0.0.1:8081")
    ps.set_defaults(fn=cmd_savepoint)

    ps = sub.add_parser("query", help="queryable-state lookup")
    ps.add_argument("job_id")
    ps.add_argument("operator")
    ps.add_argument("key")
    ps.add_argument("--key-type", default="auto",
                    choices=["auto", "int", "float", "string"],
                    help="force the key's type (string keys that look "
                    "numeric need 'string')")
    ps.add_argument("--namespace", type=int)
    ps.add_argument("--rest", default="127.0.0.1:8081")
    ps.set_defaults(fn=cmd_query)

    ps = sub.add_parser("inspect", help="inspect a checkpoint/savepoint")
    ps.add_argument("path")
    ps.set_defaults(fn=cmd_inspect)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
