"""Nexmark queries (the reference's SQL smoke suite and the industry-standard
streaming benchmark; reference:
flink-table/flink-sql-client/src/test/resources/nexmark.sql).

Implemented on the DataStream API with vectorized operators:

- Q5 (hot items): which auctions received the most bids in the last sliding
  window? HOP count per auction + per-window arg-max. A fired batch contains
  one whole window, so the arg-max is a single vectorized pass over it.
- Q7 (highest bid): the bid(s) with the highest price per tumbling window —
  global windowed MAX joined back against the bids of the same window
  (two-stage: const-key MAX, then a price=max window join).
"""

from __future__ import annotations

import numpy as np

from flink_tpu.connectors.sources import Source
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


class BidSource(Source):
    """Synthetic Nexmark bid stream: (auction, bidder, price, ts).

    Deterministic and seedable; auction popularity follows a zipf-ish skew
    like the Nexmark generator's hot-auction bias. Content is a pure
    function of the GLOBAL record index (counter-based hashing, like
    DataGenSource), so re-reads, re-batching, and parallel splits all
    observe the same logical stream — subtasks own disjoint index ranges
    instead of running N independent generators.
    """

    def __init__(self, total_records: int, num_auctions: int = 10_000,
                 num_bidders: int = 50_000,
                 events_per_second_of_eventtime: int = 100_000,
                 hot_ratio: float = 0.5, seed: int = 42):
        self.total = int(total_records)
        self.num_auctions = num_auctions
        self.num_bidders = num_bidders
        self.rate = events_per_second_of_eventtime
        self.hot_ratio = hot_ratio
        self.seed = seed
        self._emitted = 0  # within this subtask's stride
        self._stride = 1
        self._offset = 0

    def estimate_records(self):
        return self.total

    def open(self, subtask_index=0, parallelism=1):
        # STRIDED split of the global index space (subtask k owns indices
        # k, k+P, k+2P, ...): event time is a function of the global
        # index, so striding keeps every subtask's watermark advancing
        # together — a contiguous split would hand each subtask a
        # disjoint event-time range and stall the combined watermark at
        # subtask 0's range until end of input. Position reset so a
        # re-executed graph replays the stream (restore_position runs
        # after open on recovery).
        self._stride = max(parallelism, 1)
        self._offset = subtask_index
        self._emitted = 0

    def _uniform(self, idx: np.ndarray, salt: int) -> np.ndarray:
        from flink_tpu.connectors.sources import _splitmix64

        u = _splitmix64(idx, self.seed * 4 + salt)
        return (u >> np.uint64(11)).astype(np.float64) / (1 << 53)

    def poll_batch(self, max_records):
        own = (self.total - self._offset + self._stride - 1) \
            // self._stride
        if self._emitted >= own:
            return None
        n = min(max_records, own - self._emitted)
        first = self._emitted * self._stride + self._offset
        # native single-pass generator when available (the measured path
        # runs on ONE host core here — generator cost is engine cost);
        # bit-identical to the numpy fallback below, so checkpoints replay
        # across either
        from flink_tpu.native import load_datagen

        lib = load_datagen()
        if lib is not None:
            import ctypes

            auctions = np.empty(n, dtype=np.int64)
            bidders = np.empty(n, dtype=np.int64)
            prices = np.empty(n, dtype=np.float32)
            ts = np.empty(n, dtype=np.int64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.ngen_bids(
                n, first, self._stride, self.seed * 4 + 1,
                self.num_auctions, self.num_bidders,
                int(self.hot_ratio * 1024), max(self.rate, 1),
                auctions.ctypes.data_as(i64p),
                bidders.ctypes.data_as(i64p),
                prices.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ts.ctypes.data_as(i64p))
            self._emitted += n
            return RecordBatch.from_pydict(
                {"auction": auctions, "bidder": bidders, "price": prices},
                timestamps=ts)
        idx = (np.arange(self._emitted, self._emitted + n,
                         dtype=np.int64) * self._stride + self._offset)
        self._emitted += n
        # ONE hash per record; all four fields are sliced from its 64
        # bits (hot flag 10, auction uniform 22, bidder 16, price 16).
        # Same distributions as the previous four-hash version at a
        # quarter of the generator cost — the generator must not shadow
        # the engine in the measured path.
        from flink_tpu.connectors.sources import _splitmix64

        u64 = _splitmix64(idx, self.seed * 4 + 1)
        hot = (u64 & np.uint64(0x3FF)).astype(np.int64) < int(
            self.hot_ratio * 1024)
        u_auction = ((u64 >> np.uint64(10)) & np.uint64(0x3FFFFF)
                     ).astype(np.float64) / (1 << 22)
        auctions = np.where(
            hot,
            (u_auction * max(self.num_auctions // 100, 1)),
            (u_auction * self.num_auctions)).astype(np.int64)
        bidders = (((u64 >> np.uint64(32)) & np.uint64(0xFFFF)
                    ).astype(np.int64) * self.num_bidders) >> 16
        # Pareto(a=3) via inverse transform of the uniform hash — the
        # same price distribution the Nexmark-style generator used
        u_price = np.maximum(
            ((u64 >> np.uint64(48)).astype(np.float64) / (1 << 16)),
            1e-12)
        prices = ((np.power(u_price, -1.0 / 3.0) - 1.0) * 100 + 1
                  ).astype(np.float32)
        ts = (idx * 1000) // max(self.rate, 1)
        return RecordBatch.from_pydict(
            {"auction": auctions, "bidder": bidders, "price": prices},
            timestamps=ts)

    def snapshot_position(self):
        return {"emitted": self._emitted}

    def restore_position(self, pos):
        self._emitted = pos["emitted"]


def _window_argmax(field: str):
    """Fired window batches hold one whole window — per-window arg-max is a
    vectorized scan of the batch."""

    def fn(batch: RecordBatch):
        counts = batch[field]
        best = counts.max()
        return batch.filter(counts == best)

    return fn


def build_q5(env, source: BidSource, size_ms: int = 10_000,
             slide_ms: int = 2_000, device_top_k: int = 0):
    """Q5 hot items -> stream of (auction, count, window) winners.

    ``device_top_k`` > 0 fuses a top-k reduction into the window-fire
    kernel (flink_tpu.windowing.fire_projectors.TopKFireProjector): only k
    candidate rows cross HBM->host instead of one row per live auction, and
    the arg-max map scans those k. Exact as long as ties for the max count
    fit in k; 0 disables the fusion (tests with mass ties use 0).
    """
    from flink_tpu.windowing.aggregates import CountAggregate

    projector = None
    if device_top_k:
        from flink_tpu.windowing.fire_projectors import TopKFireProjector

        projector = TopKFireProjector("count", k=device_top_k)
    return (
        env.from_source(source,
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("auction")
        .window(SlidingEventTimeWindows.of(size_ms, slide_ms))
        .aggregate(CountAggregate(), fire_projector=projector)
        .map(_window_argmax("count"), name="hot_items_argmax")
    )


def build_q7(env, source: BidSource, size_ms: int = 10_000):
    """Q7 highest bid -> the bid rows achieving the per-window max price."""
    bids = env.from_source(
        source, WatermarkStrategy.for_bounded_out_of_orderness(0))
    bids = bids.map(lambda b: b.with_column(
        "g", np.zeros(len(b), dtype=np.int64)), name="const_key")
    maxes = (
        bids.key_by("g")
        .window(TumblingEventTimeWindows.of(size_ms))
        .max("price")
        .map(lambda b: b.drop("g"), name="drop_g")
    )
    joined = (
        bids.join(maxes).where("price").equal_to("max_price")
        .window(TumblingEventTimeWindows.of(size_ms))
        .apply(name="q7_join")
    )
    return joined


# ---------------------------------------------------------------------------
# Oracles (pure Python/NumPy, used by tests)
# ---------------------------------------------------------------------------


def oracle_q5(bids, size_ms, slide_ms):
    """bids: list of (auction, ts). Returns {window_end: (max_count, set of
    auctions with that count)} for complete windows."""
    import collections

    counts = collections.defaultdict(lambda: collections.defaultdict(int))
    for auction, ts in bids:
        first = ts - (ts % slide_ms) + slide_ms
        for w in range(first, ts + size_ms + 1, slide_ms):
            if w - size_ms <= ts < w:
                counts[w][auction] += 1
    out = {}
    for w, per_auction in counts.items():
        best = max(per_auction.values())
        out[w] = (best, {a for a, c in per_auction.items() if c == best})
    return out


def oracle_q7(bids, size_ms):
    """bids: list of (auction, bidder, price, ts). Returns
    {window_end: (max_price, [(auction, bidder)])}"""
    import collections

    per_w = collections.defaultdict(list)
    for auction, bidder, price, ts in bids:
        w = ts - (ts % size_ms) + size_ms
        per_w[w].append((auction, bidder, price))
    out = {}
    for w, rows in per_w.items():
        mx = max(r[2] for r in rows)
        out[w] = (mx, [(a, b) for a, b, p in rows if p == mx])
    return out
