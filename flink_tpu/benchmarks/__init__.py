from flink_tpu.benchmarks.nexmark import BidSource, build_q5, build_q7

__all__ = ["BidSource", "build_q5", "build_q7"]
