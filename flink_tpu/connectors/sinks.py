"""Sinks (reference: Sink V2, flink-core/.../api/connector/sink2/)."""

from __future__ import annotations

from typing import List, Optional

from flink_tpu.core.records import RecordBatch


from flink_tpu.core.annotations import public

@public
class Sink:
    def open(self, subtask_index: int = 0) -> None:
        pass

    def write(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


@public
class DiscardingSink(Sink):
    """Swallows output (reference: DiscardingSink test utility)."""

    def write(self, batch: RecordBatch) -> None:
        pass


@public
class CollectSink(Sink):
    """Collects all batches in memory (tests / execute_and_collect)."""

    def __init__(self):
        self.batches: List[RecordBatch] = []

    def write(self, batch):
        self.batches.append(batch)

    def result(self) -> RecordBatch:
        return RecordBatch.concat(self.batches)

    def rows(self):
        return self.result().to_rows()


@public
class PrintSink(Sink):
    def __init__(self, label: str = "", max_rows_per_batch: Optional[int] = 20):
        self.label = label
        self.max_rows = max_rows_per_batch

    def write(self, batch):
        rows = batch.to_rows()
        shown = rows if self.max_rows is None else rows[: self.max_rows]
        for r in shown:
            print(f"{self.label}> {r}")
        if self.max_rows is not None and len(rows) > self.max_rows:
            print(f"{self.label}> ... {len(rows) - self.max_rows} more")


@public
class JsonLinesFileSink(Sink):
    """Append rows as JSON lines to a file.

    reference: filesystem connector / FileSink (flink-connectors). Append
    mode survives job restarts — downstream consumers dedupe on key columns
    for effectively-once results (the reference's at-least-once file sink
    without the two-phase-commit part; see checkpoint docs for the exactly-
    once variant design).
    """

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def open(self, subtask_index: int = 0) -> None:
        from flink_tpu.core.fs import get_filesystem

        fs, local = get_filesystem(self.path)
        self._fh = fs.open(local, "ab")

    def write(self, batch: RecordBatch) -> None:
        import json

        if self._fh is None:  # deserialized on a worker without open()
            self.open()
        for row in batch.to_rows():
            self._fh.write((json.dumps(row, default=str) + "\n").encode())
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __getstate__(self):
        # the sink travels to workers via cloudpickle; the handle does not
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._fh = None

    @staticmethod
    def read_rows(path: str):
        import io
        import json

        from flink_tpu.core.fs import get_filesystem

        fs, local = get_filesystem(path)
        if not fs.exists(local):
            return []
        with fs.open(local, "rb") as fh:
            text = io.TextIOWrapper(fh, encoding="utf-8")
            return [json.loads(line) for line in text if line.strip()]


class BinaryFileSink(Sink):
    """Length-prefixed binary batches in the framework's columnar wire
    format (core/serializers.py RowBatchSerializer) — the compact,
    schema-carrying counterpart of JsonLinesFileSink. The serializer
    snapshot is embedded in every file header, so a reader can restore the
    exact row type (and resolve compatibility) without out-of-band schema.
    """

    MAGIC = b"FTFS"

    def __init__(self, path: str):
        self.path = path
        self._fh = None
        self._ser = None

    def open(self, subtask_index: int = 0) -> None:
        from flink_tpu.core.fs import get_filesystem

        fs, local = get_filesystem(self.path)
        self._fh = fs.open(local, "wb")

    def write(self, batch: RecordBatch) -> None:
        import json
        import struct

        if self._fh is None:
            self.open()
        if self._ser is None:
            from flink_tpu.core.types import RowTypeInfo

            self._ser = RowTypeInfo.from_batch(batch).create_serializer()
            header = json.dumps(self._ser.snapshot().to_json()).encode()
            self._fh.write(self.MAGIC + struct.pack("<I", len(header))
                           + header)
        payload = self._ser.serialize(batch)
        self._fh.write(struct.pack("<Q", len(payload)) + payload)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._fh = None
        self._ser = None
