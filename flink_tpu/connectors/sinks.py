"""Sinks (reference: Sink V2, flink-core/.../api/connector/sink2/)."""

from __future__ import annotations

from typing import List, Optional

from flink_tpu.core.records import RecordBatch


class Sink:
    def open(self, subtask_index: int = 0) -> None:
        pass

    def write(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CollectSink(Sink):
    """Collects all batches in memory (tests / execute_and_collect)."""

    def __init__(self):
        self.batches: List[RecordBatch] = []

    def write(self, batch):
        self.batches.append(batch)

    def result(self) -> RecordBatch:
        return RecordBatch.concat(self.batches)

    def rows(self):
        return self.result().to_rows()


class PrintSink(Sink):
    def __init__(self, label: str = "", max_rows_per_batch: Optional[int] = 20):
        self.label = label
        self.max_rows = max_rows_per_batch

    def write(self, batch):
        rows = batch.to_rows()
        shown = rows if self.max_rows is None else rows[: self.max_rows]
        for r in shown:
            print(f"{self.label}> {r}")
        if self.max_rows is not None and len(rows) > self.max_rows:
            print(f"{self.label}> ... {len(rows) - self.max_rows} more")
