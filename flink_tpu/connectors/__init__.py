from flink_tpu.connectors.sources import (
    Source,
    CollectionSource,
    DataGenSource,
    SocketSource,
)
from flink_tpu.connectors.sinks import Sink, CollectSink, PrintSink

__all__ = [
    "Source",
    "CollectionSource",
    "DataGenSource",
    "SocketSource",
    "Sink",
    "CollectSink",
    "PrintSink",
]
