"""Sources.

reference model: FLIP-27 split-based sources (flink-runtime/.../source/
coordinator/SourceCoordinator.java + streaming/api/operators/SourceOperator.java).
Batched re-design: a source yields RecordBatches from ``poll_batch``; splits
exist so a source can be sharded across subtasks/hosts. Checkpointable via
``snapshot_position``/``restore_position``.
"""

from __future__ import annotations

import socket as _socket
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch


from flink_tpu.core.annotations import public

@public
class Source:
    """A bounded or unbounded batch source."""

    bounded: bool = True

    def estimate_records(self) -> Optional[int]:
        """Best-effort size estimate for adaptive batch parallelism
        (reference: the adaptive batch scheduler sizes parallelism from
        produced data volume). None = unknown."""
        return None

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        pass

    def poll_batch(self, max_records: int) -> Optional[RecordBatch]:
        """Next batch, or None when (currently) exhausted."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    def snapshot_position(self) -> Dict[str, Any]:
        return {}

    def restore_position(self, pos: Dict[str, Any]) -> None:
        pass


class CollectionSource(Source):
    """In-memory batches (tests / examples), like the reference's
    fromCollection/fromData (StreamExecutionEnvironment.java)."""

    def __init__(self, batches: Sequence[RecordBatch]):
        self.batches = list(batches)
        self._i = 0

    def estimate_records(self) -> Optional[int]:
        return sum(len(b) for b in self.batches)

    @staticmethod
    def of_rows(rows: Iterable[dict], batch_size: int = 8192) -> "CollectionSource":
        rows = list(rows)
        batches = [RecordBatch.from_rows(rows[i:i + batch_size])
                   for i in range(0, len(rows), batch_size)]
        return CollectionSource(batches)

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        # each execution re-reads the collection from the start (the
        # reference's fromCollection deploys a fresh source per job;
        # restore_position runs AFTER open, so recovery still wins)
        self._i = 0

    def poll_batch(self, max_records):
        if self._i >= len(self.batches):
            return None
        b = self.batches[self._i]
        self._i += 1
        return b

    def snapshot_position(self):
        return {"i": self._i}

    def restore_position(self, pos):
        self._i = pos["i"]


def _splitmix64(idx: np.ndarray, salt: int) -> np.ndarray:
    """Vectorized counter-based hash (splitmix64): record content derives
    from the GLOBAL record index, so the stream is identical under any
    batch size or source parallelism — subtasks own disjoint index ranges
    of one well-defined stream (the reference's datagen splits the same
    way: a partitioned sequence, not N independent generators)."""
    with np.errstate(over="ignore"):
        z = idx.astype(np.uint64) + np.uint64(
            (salt * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF)
        z = (z + np.uint64(0x9E3779B97F4A7C15))
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


class DataGenSource(Source):
    """Deterministic synthetic event generator (keys, values, event time),
    the analog of the reference's datagen connector
    (docs/content/docs/connectors/datastream/datagen.md) but batch-granular
    and seedable for benchmarks. Content is a pure function of the global
    record index (counter-based hashing), so re-reads, re-batching, and
    parallel splits all observe the same logical stream."""

    def __init__(self, total_records: int, num_keys: int,
                 events_per_second_of_eventtime: int = 10000,
                 key_field: str = "key", value_field: str = "value",
                 seed: int = 7, start_ts: int = 0,
                 key_dtype=np.int64, skew: float = 0.0):
        self.total = int(total_records)
        self.num_keys = int(num_keys)
        self.rate = int(events_per_second_of_eventtime)
        self.key_field = key_field
        self.value_field = value_field
        self.seed = seed
        self.start_ts = start_ts
        self.skew = skew
        self._emitted = 0  # within this subtask's range
        self._start = 0
        self._end = self.total

    def estimate_records(self) -> Optional[int]:
        return self.total

    def open(self, subtask_index=0, parallelism=1):
        # contiguous split of the global index space; position reset so a
        # re-executed graph re-generates the same stream (restore_position
        # runs after open on recovery)
        per = -(-self.total // max(parallelism, 1))
        self._start = min(subtask_index * per, self.total)
        self._end = min(self._start + per, self.total)
        self._emitted = 0

    def _generate(self, idx: np.ndarray) -> RecordBatch:
        u_key = _splitmix64(idx, self.seed * 2 + 1)
        if self.skew > 0.0:
            # zipf-ish skew via inverse power transform of the uniform
            # hash (hot-key benchmarks, Nexmark Q5 style)
            u = (u_key >> np.uint64(11)).astype(np.float64) / (1 << 53)
            raw = np.maximum(
                1.0, np.power(np.maximum(u, 1e-12), -1.0 / self.skew))
            raw = np.minimum(raw, 1e18)
            keys = (raw.astype(np.int64) % self.num_keys)
        else:
            keys = (u_key % np.uint64(self.num_keys)).astype(np.int64)
        u_val = _splitmix64(idx, self.seed * 2 + 2)
        values = ((u_val >> np.uint64(11)).astype(np.float64)
                  / (1 << 53)).astype(np.float32)
        # event time advances deterministically with the GLOBAL index
        ts = self.start_ts + (idx * 1000) // max(self.rate, 1)
        return RecordBatch.from_pydict(
            {self.key_field: keys, self.value_field: values}, timestamps=ts)

    def poll_batch(self, max_records):
        own = self._end - self._start
        if self._emitted >= own:
            return None
        n = min(max_records, own - self._emitted)
        idx = np.arange(self._start + self._emitted,
                        self._start + self._emitted + n, dtype=np.int64)
        self._emitted += n
        return self._generate(idx)

    def snapshot_position(self):
        return {"emitted": self._emitted}

    def restore_position(self, pos):
        self._emitted = pos["emitted"]


class SocketSource(Source):
    """Line-oriented TCP socket source (the WordCount baseline's source;
    reference: streaming/api/functions/source/SocketTextStreamFunction.java).
    Each line becomes one record in column ``line``; timestamps are arrival
    time unless a later operator assigns event time."""

    bounded = False

    def __init__(self, host: str, port: int, field: str = "line",
                 connect_timeout: float = 10.0):
        self.host = host
        self.port = port
        self.field = field
        self.connect_timeout = connect_timeout
        self._sock: Optional[_socket.socket] = None
        self._buf = b""
        self._eof = False

    def open(self, subtask_index=0, parallelism=1):
        self._sock = _socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        self._sock.settimeout(0.05)

    def poll_batch(self, max_records):
        import time as _time

        if self._eof:
            return None
        lines: List[str] = []
        try:
            data = self._sock.recv(1 << 16)
            if not data:
                self._eof = True
            self._buf += data
        except (TimeoutError, _socket.timeout):
            pass
        while b"\n" in self._buf and len(lines) < max_records:
            line, self._buf = self._buf.split(b"\n", 1)
            lines.append(line.decode("utf-8", errors="replace"))
        if not lines:
            return None if self._eof else RecordBatch({})
        now = int(_time.time() * 1000)
        return RecordBatch.from_pydict(
            {self.field: np.array(lines, dtype=object)},
            timestamps=np.full(len(lines), now, dtype=np.int64))

    def close(self):
        if self._sock is not None:
            self._sock.close()


class BinaryFileSource(Source):
    """Reads files written by BinaryFileSink. The embedded serializer
    snapshot restores the writer's exact row type; if a ``row_type`` is
    given, compatibility is resolved first and batches are migrated when
    the schema evolved (reference: serializer snapshot compatibility on
    state restore — flink-core/.../typeutils/TypeSerializerSnapshot.java).
    """

    def __init__(self, path: str, row_type=None):
        self.path = path
        self.row_type = row_type
        self._fh = None
        self._ser = None
        self._snap = None
        self._migrating = False
        self._pos = 0

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        import json
        import struct

        from flink_tpu.core.serializers import (
            Compatibility,
            SerializerSnapshot,
        )

        from flink_tpu.core.fs import get_filesystem

        fs, local = get_filesystem(self.path)
        self._fh = fs.open(local, "rb")
        magic = self._fh.read(4)
        if magic != b"FTFS":
            raise ValueError(f"{self.path}: not a binary batch file")
        (hlen,) = struct.unpack("<I", self._fh.read(4))
        self._snap = SerializerSnapshot.from_json(
            json.loads(self._fh.read(hlen).decode()))
        if self.row_type is not None:
            new_ser = self.row_type.create_serializer()
            compat = self._snap.resolve_compatibility(new_ser)
            if compat is Compatibility.INCOMPATIBLE:
                raise ValueError(
                    f"{self.path}: written schema is incompatible with the "
                    f"requested row type")
            self._ser = new_ser
            self._migrating = compat is Compatibility.COMPATIBLE_AFTER_MIGRATION
        else:
            self._ser = self._snap.restore_serializer()
        if self._pos:
            self._fh.seek(self._pos)

    def poll_batch(self, max_records):
        import struct

        head = self._fh.read(8)
        if len(head) < 8:
            return None
        (plen,) = struct.unpack("<Q", head)
        payload = self._fh.read(plen)
        self._pos = self._fh.tell()
        if self._migrating:
            return self._ser.migrate(payload, self._snap)
        return self._ser.deserialize(payload)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def snapshot_position(self):
        return {"pos": self._pos}

    def restore_position(self, pos):
        self._pos = pos["pos"]
        if self._fh is not None and self._pos:
            # restore after open (the framework-wide ordering): seek the
            # live handle; restore before open still works via the seek
            # open() performs. pos 0 = never polled — the handle already
            # sits just past the header, which byte 0 is not.
            self._fh.seek(self._pos)
