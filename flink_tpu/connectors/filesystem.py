"""FileSink — bucketed, rolling, exactly-once file output.

reference: flink-connectors/flink-connector-files — FileSink with
BucketAssigner (file/src/main/java/.../sink/filesystem/BucketAssigner.java,
DateTimeBucketAssigner), RollingPolicy (DefaultRollingPolicy: part size /
rollover interval), and the pending -> finished part-file lifecycle
committed through Sink V2's two-phase protocol (SupportsCommitter).

Columnar re-design: bucket assignment is VECTORIZED — one call maps a
whole RecordBatch to bucket ids and the batch splits into per-bucket
sub-batches with one lexsort, so a million rows crossing a day boundary
cost two gathers, not a per-record router. Row encoding goes through
the SerializationSchema seam (connectors/formats.py), so every
registered format — jsonl, csv, avro — writes files.

Lifecycle (exactly the reference's):
- rows append to a bucket's ``.inprogress`` part file;
- the rolling policy closes parts (size/records), making them PENDING;
- ``prepare_commit`` (checkpoint) seals all open parts -> pending, and
  the pending list rides the checkpoint as committables;
- ``commit`` atomically renames pending parts to their final names
  (idempotent: already-renamed parts are skipped);
- a crash discards unsealed ``.inprogress`` files on restore.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import TIMESTAMP_FIELD, RecordBatch
from flink_tpu.connectors.two_phase import TwoPhaseCommitSink


class BucketAssigner:
    """batch -> one bucket id per row (vectorized; reference:
    sink/filesystem/BucketAssigner.java getBucketId per record)."""

    def bucket_ids(self, batch: RecordBatch) -> np.ndarray:
        raise NotImplementedError


class BasePathBucketAssigner(BucketAssigner):
    """Everything in one bucket (reference: BasePathBucketAssigner)."""

    def bucket_ids(self, batch: RecordBatch) -> np.ndarray:
        return np.full(len(batch), "", dtype=object)


class DateTimeBucketAssigner(BucketAssigner):
    """Buckets by the rows' EVENT TIME formatted with ``fmt``
    (reference: DateTimeBucketAssigner, default yyyy-MM-dd--HH) —
    vectorized through a per-batch unique on the truncated epoch."""

    def __init__(self, fmt: str = "%Y-%m-%d--%H"):
        self.fmt = fmt
        # truncation granularity: finest field present in the format
        self._step_ms = (1000 if "%S" in fmt else
                         60_000 if "%M" in fmt else
                         3_600_000 if "%H" in fmt else 86_400_000)

    def bucket_ids(self, batch: RecordBatch) -> np.ndarray:
        if not batch.has_timestamps:
            raise ValueError(
                "DateTimeBucketAssigner needs event-time rows (assign a "
                "watermark strategy)")
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        trunc = ts // self._step_ms
        uniq, inverse = np.unique(trunc, return_inverse=True)
        names = np.array([
            time.strftime(self.fmt, time.gmtime(u * self._step_ms / 1000))
            for u in uniq.tolist()], dtype=object)
        return names[inverse]


class ColumnBucketAssigner(BucketAssigner):
    """Buckets by a column's value (partitioned output directories)."""

    def __init__(self, column: str):
        self.column = column

    def bucket_ids(self, batch: RecordBatch) -> np.ndarray:
        return np.asarray(
            [str(v) for v in batch[self.column].tolist()], dtype=object)


class RollingPolicy:
    """When does an in-progress part close? (reference:
    DefaultRollingPolicy: shouldRollOnEvent by size,
    shouldRollOnProcessingTime by interval; checkpoints always roll
    here — parts seal at prepare_commit like the bulk-format sink)."""

    def __init__(self, max_part_bytes: int = 128 << 20,
                 max_part_records: int = 0,
                 rollover_interval_ms: int = 0):
        self.max_part_bytes = int(max_part_bytes)
        self.max_part_records = int(max_part_records)
        self.rollover_interval_ms = int(rollover_interval_ms)

    def should_roll(self, part: "_Part", now_ms: int) -> bool:
        if self.max_part_bytes and part.bytes >= self.max_part_bytes:
            return True
        if self.max_part_records and part.records >= self.max_part_records:
            return True
        if self.rollover_interval_ms and \
                now_ms - part.opened_ms >= self.rollover_interval_ms:
            return True
        return False


class _Part:
    """``binary`` framing: text rows are newline-delimited (jsonl/csv
    files readable by anything); binary rows (avro) are u32-length-
    prefixed — a record's payload may contain any byte, including
    0x0A (reference: the bulk formats' own container framing)."""

    def __init__(self, directory: str, name: str, binary: bool = False):
        self.final_path = os.path.join(directory, name)
        self.inprogress = self.final_path + ".inprogress"
        os.makedirs(directory, exist_ok=True)
        self.fh = open(self.inprogress, "wb")
        self.binary = binary
        self.bytes = 0
        self.records = 0
        self.opened_ms = int(time.time() * 1000)

    def append(self, rows: List[bytes]) -> None:
        import struct

        for r in rows:
            if self.binary:
                self.fh.write(struct.pack("<I", len(r)))
                self.bytes += 4
            elif b"\n" in r:
                # newline framing cannot represent this record — failing
                # loudly beats committing a file that splits mid-record
                # on read (csv quoting keeps raw 0x0A inside fields)
                raise ValueError(
                    "record contains a raw newline, which the text "
                    "framing cannot represent — use 'format'='json' "
                    "(escapes control characters) or a binary format "
                    "(length-prefixed)")
            self.fh.write(r)
            self.bytes += len(r)
            if not self.binary:
                self.fh.write(b"\n")
                self.bytes += 1
        self.records += len(rows)

    def seal(self) -> Dict[str, str]:
        self.fh.close()
        return {"inprogress": self.inprogress, "final": self.final_path}


class FileSink(TwoPhaseCommitSink):
    """Bucketed rolling exactly-once file sink (reference: FileSink).

    ``fmt`` is a format name resolved through the DDL format seam
    ('json', 'csv', 'avro', ...) or a SerializationSchema instance.
    """

    def __init__(self, base_path: str, columns: Sequence[str],
                 fmt: Any = "json",
                 bucket_assigner: Optional[BucketAssigner] = None,
                 rolling_policy: Optional[RollingPolicy] = None,
                 types: Optional[Sequence[str]] = None,
                 format_options: Optional[dict] = None):
        self.base_path = base_path
        self.columns = list(columns)
        if isinstance(fmt, str):
            from flink_tpu.connectors.formats import resolve_format

            _, self._ser = resolve_format(
                fmt, self.columns, list(types or [None] * len(columns)),
                format_options)
        else:
            self._ser = fmt
        self.assigner = bucket_assigner or BasePathBucketAssigner()
        self.policy = rolling_policy or RollingPolicy()
        self._subtask = 0
        self._open_parts: Dict[str, _Part] = {}
        self._pending: List[Dict[str, str]] = []
        self._seq = 0

    def open(self, subtask_index: int = 0) -> None:
        self._subtask = subtask_index
        self._ser.open()
        os.makedirs(self.base_path, exist_ok=True)

    # ------------------------------------------------------------- write

    def _part_for(self, bucket: str) -> _Part:
        part = self._open_parts.get(bucket)
        if part is None:
            directory = (os.path.join(self.base_path, bucket)
                         if bucket else self.base_path)
            name = (f"part-{self._subtask}-{self._seq}-"
                    f"{uuid.uuid4().hex[:8]}")
            self._seq += 1
            part = _Part(directory, name,
                         binary=getattr(self._ser, "binary", False))
            self._open_parts[bucket] = part
        return part

    def write(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        buckets = self.assigner.bucket_ids(batch)
        uniq, inverse = np.unique(buckets, return_inverse=True)
        now = int(time.time() * 1000)
        for i, bucket in enumerate(uniq.tolist()):
            sub = batch.filter(inverse == i) if len(uniq) > 1 else batch
            rows = self._ser.serialize_batch(sub)
            part = self._part_for(bucket)
            part.append(rows)
            if self.policy.should_roll(part, now):
                # rolled parts are PENDING: published at the NEXT
                # checkpoint (reference: rolling closes the part file
                # but visibility still waits for the committer)
                self._pending.append(part.seal())
                del self._open_parts[bucket]

    # -------------------------------------------------------- two-phase

    def prepare_commit(self) -> List[Any]:
        for bucket in list(self._open_parts):
            part = self._open_parts.pop(bucket)
            if part.records:
                self._pending.append(part.seal())
            else:
                part.fh.close()
                os.unlink(part.inprogress)
        out, self._pending = self._pending, []
        return out

    def commit(self, committables: List[Any]) -> None:
        for c in committables:
            if os.path.exists(c["inprogress"]):
                os.replace(c["inprogress"], c["final"])
            elif not os.path.exists(c["final"]):
                raise RuntimeError(
                    f"committable lost: neither {c['inprogress']} nor "
                    f"{c['final']} exists — data loss would be silent")

    def abort_current(self) -> None:
        for part in self._open_parts.values():
            part.fh.close()
            if os.path.exists(part.inprogress):
                os.unlink(part.inprogress)
        self._open_parts = {}
        self._pending = []

    def abort_uncommitted(self, exclude: List[Any]) -> None:
        # only THIS subtask's parts: parallel sinks share base_path, and
        # restore-time cleanup racing a peer's open/committable part
        # would delete live data (part names embed the subtask index)
        keep = {c["inprogress"] for c in exclude}
        own = f"part-{self._subtask}-"
        for root, _, files in os.walk(self.base_path):
            for f in files:
                p = os.path.join(root, f)
                if (f.startswith(own) and p.endswith(".inprogress")
                        and p not in keep):
                    os.unlink(p)

    def close(self) -> None:
        # seal + publish the tail transaction (end of input is a natural
        # commit point — reference: final checkpoint on finished sources)
        self.commit(self.prepare_commit())

    # committables travel inside checkpoints; file handles do not
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_open_parts"] = {}
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


from flink_tpu.connectors.sources import Source


def _walk_committed(base_path: str) -> List[str]:
    """All committed part files under ``base_path``, in bucket/file
    order (readers must never see ``.inprogress`` data)."""
    out = []
    for root, _dirs, files in sorted(
            (r, d, f) for r, d, f in os.walk(base_path)):
        for f in sorted(files):
            if not f.endswith(".inprogress"):
                out.append(os.path.join(root, f))
    return out


def _decode_file_rows(path: str, binary: bool) -> List[bytes]:
    """One part file -> raw rows, undoing the framing _Part.append
    wrote (newline-delimited text / u32-length-prefixed binary). THE
    single copy of the read-side framing rule."""
    import struct

    with open(path, "rb") as fh:
        data = fh.read()
    if binary:
        rows, off = [], 0
        while off < len(data):
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            rows.append(data[off:off + n])
            off += n
        return rows
    return [line for line in data.split(b"\n") if line]


class FileSource(Source):
    """Bounded scan over COMMITTED part files (reference:
    flink-connector-files FileSource / the filesystem table source).
    Readers never observe ``.inprogress`` data — the other half of the
    FileSink's exactly-once contract. Rows decode through the
    DeserializationSchema seam in file order; buckets are directories,
    so a partitioned layout reads back transparently.

    Exactly-once on the reader side: the checkpoint carries the
    REMAINING FILE PATHS and the row offset inside the current file
    (reference: FileSource snapshots its splits) — never an index into
    a list re-discovered from a directory that may have changed."""

    def __init__(self, path: str, deserializer,
                 timestamp_field: Optional[str] = None):
        self.path = path
        self._deser = deserializer
        self.timestamp_field = timestamp_field
        self._files: List[str] = []
        self._next_file = 0
        self._row = 0            # rows of the CURRENT file already emitted
        self._cur_rows: Optional[List[bytes]] = None
        self._restored = False

    def estimate_records(self) -> Optional[int]:
        return None  # unknowable without reading; batch mode meters

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        self._deser.open()
        if self._restored:
            return  # the checkpointed file list IS the split
        files = _walk_committed(self.path)
        per = -(-len(files) // max(parallelism, 1))
        self._files = files[subtask_index * per:(subtask_index + 1) * per]
        self._next_file = 0
        self._row = 0

    def poll_batch(self, max_records: int):
        binary = getattr(self._deser, "binary", False)
        while self._next_file < len(self._files):
            if self._cur_rows is None:
                self._cur_rows = _decode_file_rows(
                    self._files[self._next_file], binary)
            if self._row >= len(self._cur_rows):
                self._cur_rows = None
                self._next_file += 1
                self._row = 0
                continue
            chunk = self._cur_rows[self._row:self._row + max_records]
            self._row += len(chunk)
            batch = self._deser.deserialize_batch(chunk)
            if self.timestamp_field and \
                    self.timestamp_field in batch.columns:
                batch = batch.with_column(
                    TIMESTAMP_FIELD,
                    np.asarray(batch[self.timestamp_field],
                               dtype=np.int64))
            return batch
        return None

    def close(self) -> None:
        pass

    def snapshot_position(self) -> Dict[str, Any]:
        return {"files": list(self._files[self._next_file:]),
                "row": self._row}

    def restore_position(self, pos) -> None:
        if "files" in pos:
            self._files = list(pos["files"])
            self._next_file = 0
            self._row = int(pos.get("row", 0))
            self._cur_rows = None
            self._restored = True


def read_committed_rows(base_path: str,
                        binary: bool = False) -> List[bytes]:
    """All rows of committed part files under ``base_path``, in
    bucket/file order (test/validation helper — readers must never see
    ``.inprogress`` data). ``binary`` selects the length-prefixed
    framing binary formats (avro) write."""
    rows: List[bytes] = []
    for path in _walk_committed(base_path):
        rows.extend(_decode_file_rows(path, binary))
    return rows
