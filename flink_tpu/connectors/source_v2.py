"""Split-based source framework — the FLIP-27 model.

reference: runtime/source/coordinator/SourceCoordinator.java (enumerator on
the JobMaster, split assignment via RPC events, watermark alignment params
at :106), streaming/api/operators/SourceOperator.java (reader on the task),
flink-connector-base split-reader infra, and the continuous file discovery
of the FileSource connector.

Re-design for the batched engine:

- A *split* is a unit of parallelizable input (one file, one partition).
- The *enumerator* discovers splits (incrementally for unbounded sources —
  continuous directory monitoring).
- A *split reader* IS a plain ``Source`` (open/poll_batch/snapshot_position)
  created per split by a factory — reusing the one source contract end to
  end instead of a second reader SPI.
- The *coordinator* owns the enumerator and deals splits to parallel
  subtasks round-robin; each ``SplitSource`` instance (one per subtask)
  reads only its assigned splits.
- *Watermark alignment*: a split whose local max timestamp runs more than
  ``alignment_max_drift_ms`` ahead of the slowest unfinished split is
  paused (its poll is skipped) until the others catch up — the reference
  pauses SourceReader splits the same way (SourceCoordinator.java:106
  watermarkAlignmentParams + pauseOrResumeSplits).
- *Idleness*: a split with no data for ``idle_timeout_ms`` (wall clock) is
  excluded from the source watermark so it cannot hold back event time
  (reference: WatermarkStrategy.withIdleness).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.connectors.sources import Source
from flink_tpu.runtime.elements import MIN_WATERMARK
from flink_tpu.runtime.watermarks import WatermarkGenerator, WatermarkStrategy


@dataclasses.dataclass(frozen=True)
class SourceSplit:
    split_id: str
    payload: Any = None


class SplitEnumerator:
    """Discovers splits. ``discover()`` returns only NEW splits since the
    previous call (the reference's enumerator sends incremental
    assignments). ``bounded`` declares whether discovery ever finishes.
    ``reset()`` forgets the discovery state so a RE-opened source replays
    the whole stream (part of the contract: SplitSource.open calls it on
    re-execution; restore_state then wins on recovery)."""

    bounded: bool = True

    def discover(self) -> List[SourceSplit]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} must implement reset() so a "
            "re-executed graph replays its splits")

    def snapshot_state(self) -> Dict[str, Any]:
        return {}

    def restore_state(self, state: Dict[str, Any]) -> None:
        pass


class FileSplitEnumerator(SplitEnumerator):
    """One split per file matching a glob pattern; unbounded mode keeps
    discovering files that appear later (reference: FileSource continuous
    monitoring mode)."""

    def __init__(self, pattern: str, bounded: bool = True):
        self.pattern = pattern
        self.bounded = bounded
        self._seen: set = set()

    def discover(self) -> List[SourceSplit]:
        new = []
        for path in sorted(_glob.glob(self.pattern)):
            if path not in self._seen:
                self._seen.add(path)
                new.append(SourceSplit(split_id=path, payload=path))
        return new

    def reset(self) -> None:
        self._seen.clear()

    def snapshot_state(self):
        return {"seen": sorted(self._seen)}

    def restore_state(self, state):
        self._seen = set(state["seen"])


class SourceCoordinator:
    """Assigns splits to parallel subtasks round-robin, sticky per split
    (reference: SourceCoordinator split assignment; sticky so a restore
    re-reads a split on the same subtask)."""

    def __init__(self, parallelism: int):
        self.parallelism = max(int(parallelism), 1)
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def reset(self, parallelism: int) -> None:
        """Re-open of the owning source: forget sticky assignments and
        adopt the NEW parallelism so splits rebalance — in place, so an
        injected custom coordinator keeps its construction-time
        configuration."""
        self.parallelism = max(int(parallelism), 1)
        self._assignment.clear()
        self._next = 0

    def assign(self, splits: Sequence[SourceSplit]) -> Dict[str, int]:
        for s in splits:
            if s.split_id not in self._assignment:
                self._assignment[s.split_id] = self._next % self.parallelism
                self._next += 1
        return dict(self._assignment)

    def splits_for(self, subtask: int,
                   splits: Sequence[SourceSplit]) -> List[SourceSplit]:
        self.assign(splits)
        return [s for s in splits if self._assignment[s.split_id] == subtask]

    def snapshot_state(self):
        return {"assignment": dict(self._assignment), "next": self._next}

    def restore_state(self, state):
        self._assignment = dict(state["assignment"])
        self._next = state["next"]


class _SplitState:
    __slots__ = ("split", "reader", "finished", "max_ts", "last_data_wall",
                 "idle", "records")

    def __init__(self, split: SourceSplit, reader: Source):
        self.split = split
        self.reader = reader
        self.finished = False
        self.max_ts = MIN_WATERMARK
        self.last_data_wall = _time.monotonic()
        self.idle = False
        self.records = 0


class SplitSource(Source):
    """Adapter: (enumerator, reader_factory) -> the framework's Source
    contract, with alignment/idleness/checkpointing.

    ``reader_factory(split)`` returns a Source reading that one split.
    """

    def __init__(self, enumerator: SplitEnumerator,
                 reader_factory: Callable[[SourceSplit], Source],
                 timestamp_field: Optional[str] = None,
                 alignment_max_drift_ms: Optional[int] = None,
                 idle_timeout_ms: Optional[int] = None,
                 coordinator: Optional[SourceCoordinator] = None,
                 clock: Callable[[], float] = _time.monotonic):
        self.enumerator = enumerator
        self.reader_factory = reader_factory
        self.timestamp_field = timestamp_field
        self.max_drift = alignment_max_drift_ms
        self.idle_timeout = idle_timeout_ms
        self.coordinator = coordinator
        self.clock = clock
        self.bounded = enumerator.bounded
        self._states: Dict[str, _SplitState] = {}
        self._order: List[str] = []
        self._rr = 0
        self._subtask = 0
        self._parallelism = 1
        self._opened = False
        self._parked_restore: Optional[Dict[str, Any]] = None

    # -- lifecycle -----------------------------------------------------------

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        self._subtask = subtask_index
        self._parallelism = parallelism
        if self.coordinator is None:
            self.coordinator = SourceCoordinator(parallelism)
        if self._opened:
            # RE-execution of the same graph (a registered table view
            # queried twice, a restarted job): the framework-wide
            # contract is that open() resets position so the stream
            # replays (see connectors/sources.py) — the enumerator and
            # per-split readers must start over, not resume the previous
            # run's consumed state (restore_position, applied below,
            # then wins on recovery). The coordinator is rebuilt at the
            # NEW parallelism so splits rebalance (its own documented
            # contract), and the previous run's unfinished readers close
            # first (same discipline as _apply_restore).
            self.enumerator.reset()
            for st in self._states.values():
                if st.reader is not None and not st.finished:
                    st.reader.close()
            self._states.clear()
            self._order.clear()
            self._rr = 0
            self.coordinator.reset(parallelism)
        self._opened = True
        if self._parked_restore is not None:
            self._apply_restore(self._parked_restore)
            self._parked_restore = None
        else:
            self._discover()

    def _add_split(self, split: SourceSplit,
                   reader_pos: Optional[Dict[str, Any]] = None,
                   finished: bool = False,
                   max_ts: int = MIN_WATERMARK) -> None:
        if finished:
            st = _SplitState(split, reader=None)
            st.finished = True
        else:
            reader = self.reader_factory(split)
            # open BEFORE restore: the framework-wide ordering contract is
            # open() (re)initializes position, restore_position() then
            # wins on recovery (sources reset in open so re-executed
            # graphs replay — see connectors/sources.py)
            reader.open(self._subtask, self._parallelism)
            if reader_pos is not None:
                reader.restore_position(reader_pos)
            st = _SplitState(split, reader)
        st.last_data_wall = self.clock()
        st.max_ts = max_ts
        self._states[split.split_id] = st
        self._order.append(split.split_id)

    def _discover(self) -> None:
        new = self.enumerator.discover()
        if not new:
            return
        for split in self.coordinator.splits_for(self._subtask, new):
            self._add_split(split)

    # -- alignment / idleness -----------------------------------------------

    def _unfinished(self) -> List[_SplitState]:
        return [s for s in self._states.values() if not s.finished]

    def _paused_by_alignment(self, st: _SplitState) -> bool:
        if self.max_drift is None:
            return False
        others = [s.max_ts for s in self._unfinished()
                  if s is not st and not s.idle]
        if not others:
            return False
        slowest = min(others)
        if slowest == MIN_WATERMARK:
            # peers that have produced nothing yet can't define drift
            return False
        return st.max_ts > slowest + self.max_drift

    def _update_idleness(self) -> None:
        if self.idle_timeout is None:
            return
        now = self.clock()
        for st in self._unfinished():
            st.idle = (now - st.last_data_wall) * 1000.0 >= self.idle_timeout

    # -- polling -------------------------------------------------------------

    def poll_batch(self, max_records: int) -> Optional[RecordBatch]:
        self._update_idleness()
        n = len(self._order)
        for attempt in range(max(n, 1)):
            if not self._order:
                break
            sid = self._order[self._rr % len(self._order)]
            self._rr += 1
            st = self._states[sid]
            if st.finished or self._paused_by_alignment(st):
                continue
            batch = st.reader.poll_batch(max_records)
            if batch is None:
                if self.enumerator.bounded or getattr(
                        st.reader, "bounded", True):
                    st.finished = True
                    st.reader.close()
                continue
            if len(batch) == 0:
                continue
            st.last_data_wall = self.clock()
            st.idle = False
            st.records += len(batch)
            if self.timestamp_field is not None:
                batch = batch.with_timestamps(
                    np.asarray(batch[self.timestamp_field], dtype=np.int64))
            if batch.has_timestamps:
                st.max_ts = max(st.max_ts, int(batch.timestamps.max()))
            return batch
        # nothing produced this round: rediscover (unbounded), maybe done
        if not self.enumerator.bounded:
            self._discover()
            return RecordBatch({})  # unbounded: never signal end-of-input
        if all(s.finished for s in self._states.values()):
            self._discover()  # late files between discover and finish
            if all(s.finished for s in self._states.values()):
                return None
        return RecordBatch({})

    def close(self) -> None:
        for st in self._states.values():
            if not st.finished:
                st.reader.close()

    # -- per-split watermark -------------------------------------------------

    def current_watermark(self, out_of_orderness_ms: int = 0) -> Optional[int]:
        """Min over unfinished, non-idle splits of (max_ts - delay) — the
        per-split min-merge the reference does inside SourceOperator."""
        active = [s for s in self._unfinished() if not s.idle]
        if not active:
            # all finished or idle: the max over everything seen
            seen = [s.max_ts for s in self._states.values()]
            return (max(seen) - out_of_orderness_ms - 1) if seen else None
        m = min(s.max_ts for s in active)
        if m == MIN_WATERMARK:
            return None
        return m - out_of_orderness_ms - 1

    def watermark_strategy(self, out_of_orderness_ms: int = 0,
                           ) -> WatermarkStrategy:
        """A WatermarkStrategy wired to per-split progress."""
        source = self

        class _SplitAware(WatermarkGenerator):
            def on_batch(self, batch):
                return source.current_watermark(out_of_orderness_ms)

        return WatermarkStrategy(_SplitAware,
                                 timestamp_field=None)

    # -- checkpoint ----------------------------------------------------------

    def snapshot_position(self) -> Dict[str, Any]:
        """The snapshot carries the split payloads themselves, so restore can
        rebuild readers without re-running discovery (sticky assignment
        preserved via the coordinator state)."""
        return {
            "enumerator": self.enumerator.snapshot_state(),
            "coordinator": self.coordinator.snapshot_state()
            if self.coordinator else {},
            "splits": {
                sid: {"payload": st.split.payload,
                      "finished": st.finished, "max_ts": st.max_ts,
                      "reader": (st.reader.snapshot_position()
                                 if st.reader is not None else {})}
                for sid, st in self._states.items()
            },
        }

    def restore_position(self, pos: Dict[str, Any]) -> None:
        if self._opened:
            self._apply_restore(pos)
        else:
            self._parked_restore = pos

    def _apply_restore(self, pos: Dict[str, Any]) -> None:
        for st in self._states.values():
            if st.reader is not None and not st.finished:
                st.reader.close()
        self._states.clear()
        self._order.clear()
        self._rr = 0
        self.enumerator.restore_state(pos["enumerator"])
        if self.coordinator is not None and pos.get("coordinator"):
            self.coordinator.restore_state(pos["coordinator"])
        for sid, s in pos["splits"].items():
            self._add_split(SourceSplit(sid, s["payload"]),
                            reader_pos=s["reader"] or None,
                            finished=s["finished"], max_ts=s["max_ts"])
        self._discover()  # splits that appeared after the snapshot


def file_source(pattern: str, bounded: bool = True,
                reader_factory: Optional[Callable] = None,
                timestamp_field: Optional[str] = None,
                **kwargs) -> SplitSource:
    """Directory/glob source over binary batch files (default) or a custom
    per-file reader (reference: FileSource builder)."""
    if reader_factory is None:
        from flink_tpu.connectors.sources import BinaryFileSource

        reader_factory = lambda split: BinaryFileSource(split.payload)  # noqa: E731
    return SplitSource(FileSplitEnumerator(pattern, bounded=bounded),
                       reader_factory, timestamp_field=timestamp_field,
                       **kwargs)
