"""(De)serialization formats — the seam that makes a connector real.

reference: DeserializationSchema / SerializationSchema
(flink-core/src/main/java/org/apache/flink/api/common/serialization/
DeserializationSchema.java) and the JSON format
(flink-formats/flink-json/src/main/java/org/apache/flink/formats/json/
JsonRowDataDeserializationSchema.java:1), discovered from DDL via
``'format' = 'json'`` (DeserializationFormatFactory SPI).

Re-design: schemas are BATCH-granular — ``deserialize_batch`` turns a
sequence of raw byte records into one columnar RecordBatch (typed by the
DDL column list), ``serialize_batch`` the reverse — so the per-record
work happens once per micro-batch at the connector boundary and
everything inside the framework stays columnar.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.records import ROWKIND_FIELD, RecordBatch

_FORMATS: Dict[str, Callable] = {}


def register_format(name: str, factory: Callable) -> None:
    """``factory(columns, types, options) -> (DeserializationSchema,
    SerializationSchema)`` — the DeserializationFormatFactory /
    SerializationFormatFactory SPI pair."""
    _FORMATS[name.lower()] = factory


def resolve_format(name: str, columns: Sequence[str],
                   types: Sequence[Optional[str]],
                   options: Optional[dict] = None
                   ) -> Tuple["DeserializationSchema",
                              "SerializationSchema"]:
    if name.lower() == "avro" and "avro" not in _FORMATS:
        # self-registers on import; 'format' = 'avro' in DDL must not
        # require a user-level import (same pattern as shuffle.service)
        import flink_tpu.connectors.avro  # noqa: F401
    factory = _FORMATS.get(name.lower())
    if factory is None:
        raise ValueError(
            f"unknown format {name!r} (registered: {sorted(_FORMATS)}); "
            "add one with "
            "flink_tpu.connectors.formats.register_format")
    return factory(list(columns), list(types), options or {})


class DeserializationSchema:
    """raw byte records -> one typed columnar batch.

    After ``deserialize_batch``, ``last_surviving`` holds the RAW
    indices of the records that made it into the batch (None = all of
    them) — what lets per-record metadata attached to the raw stream
    (broker timestamps) stay aligned when ignore-parse-errors skips
    records."""

    last_surviving: Optional[List[int]] = None

    #: True when records are arbitrary binary (may contain newlines) —
    #: file sources must undo u32-length-prefix framing instead of
    #: newline-splitting (the read-side mirror of
    #: SerializationSchema.binary; the two MUST agree per format)
    binary = False

    def open(self) -> None:
        pass

    def deserialize_batch(self, raw: Sequence[bytes]) -> RecordBatch:
        raise NotImplementedError


class SerializationSchema:
    """one columnar batch -> raw byte records."""

    #: True when records are arbitrary binary (may contain newlines) —
    #: file sinks must length-prefix instead of newline-framing them
    binary = False

    def open(self) -> None:
        pass

    def serialize_batch(self, batch: RecordBatch) -> List[bytes]:
        raise NotImplementedError


def _coerce(v, dt):
    """One field to its declared dtype; raises ValueError/TypeError on a
    lossy/unparseable value (callers decide skip-vs-fail per record)."""
    if dt is np.int64:
        return 0 if v is None else int(v)
    if dt is np.float64:
        return np.nan if v is None else float(v)
    if dt is np.bool_:
        return (v.lower() in ("true", "1")
                if isinstance(v, str) else bool(v))
    if dt is object:
        return "" if v is None else str(v)
    return v


def _columns_from_rows(rows: List[tuple], columns: Sequence[str],
                       dts) -> Dict[str, np.ndarray]:
    cols: Dict[str, np.ndarray] = {}
    for j, (name, dt) in enumerate(zip(columns, dts)):
        vals = [r[j] for r in rows]
        if dt is object:
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
            cols[name] = arr
        elif dt is None:
            # untyped: numeric values infer their dtype; text stays an
            # OBJECT array (a '<U' array would change equality and
            # fill semantics batch-to-batch)
            arr = np.asarray(vals)
            if arr.dtype.kind in ("U", "S"):
                obj = np.empty(len(vals), dtype=object)
                obj[:] = vals
                arr = obj
            cols[name] = arr
        else:
            cols[name] = np.asarray(vals, dtype=dt)
    return cols


def _np_dtype(sql_type: Optional[str]):
    t = (sql_type or "").upper().split("(")[0].strip()
    if t in ("BIGINT", "INT", "INTEGER", "SMALLINT", "TINYINT"):
        return np.int64
    if t in ("DOUBLE", "FLOAT", "DECIMAL", "NUMERIC", "REAL"):
        return np.float64
    if t in ("BOOLEAN",):
        return np.bool_
    if t in ("STRING", "VARCHAR", "CHAR"):
        return object
    return None  # untyped: infer from the values


class JsonRowDeserializationSchema(DeserializationSchema):
    """One JSON object per record, projected onto the DDL columns with
    dtype coercion (reference: JsonRowDataDeserializationSchema;
    ``json.ignore-parse-errors`` maps the reference option)."""

    def __init__(self, columns: Sequence[str],
                 types: Optional[Sequence[Optional[str]]] = None,
                 ignore_parse_errors: bool = False):
        self.columns = list(columns)
        self.types = list(types) if types is not None \
            else [None] * len(self.columns)
        self.ignore_parse_errors = ignore_parse_errors

    def deserialize_batch(self, raw: Sequence[bytes]) -> RecordBatch:
        dts = [_np_dtype(t) for t in self.types]
        rows: List[tuple] = []
        surviving: List[int] = []
        for i, rec in enumerate(raw):
            if isinstance(rec, (bytes, bytearray)):
                rec = rec.decode("utf-8", errors="replace")
            # parse AND type-coerce inside the guarded path: the
            # reference's ignore-parse-errors covers conversion failures
            # too, so one bad-typed field skips ONE record, never the
            # batch
            try:
                obj = json.loads(rec)
                if not isinstance(obj, dict):
                    raise ValueError("JSON record is not an object")
                rows.append(tuple(
                    _coerce(obj.get(name), dt)
                    for name, dt in zip(self.columns, dts)))
                surviving.append(i)
            except (ValueError, TypeError) as e:
                if self.ignore_parse_errors:
                    continue
                raise RuntimeError(
                    f"failed to deserialize JSON record {rec!r}: {e} "
                    "(set 'json.ignore-parse-errors'='true' to skip "
                    "corrupt records)") from e
        self.last_surviving = (None if len(surviving) == len(raw)
                               else surviving)
        return RecordBatch.from_pydict(
            _columns_from_rows(rows, self.columns, dts))


class JsonRowSerializationSchema(SerializationSchema):
    """One JSON object per row over the declared columns (reference:
    JsonRowDataSerializationSchema). A changelog row keeps its kind
    under ``"op"`` (+I/+U/-U/-D — the reference's debezium-ish op
    field), so upsert topics stay interpretable."""

    _OPS = {0: "+I", 1: "-U", 2: "+U", 3: "-D"}

    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)

    def serialize_batch(self, batch: RecordBatch) -> List[bytes]:
        out: List[bytes] = []
        names = [c for c in self.columns if c in batch.columns]
        cols = {c: batch[c] for c in names}
        kinds = (np.asarray(batch[ROWKIND_FIELD])
                 if ROWKIND_FIELD in batch.columns else None)
        for i in range(len(batch)):
            obj = {}
            for c in names:
                v = cols[c][i]
                if isinstance(v, (np.integer,)):
                    v = int(v)
                elif isinstance(v, (np.floating,)):
                    v = float(v)
                elif isinstance(v, (np.bool_,)):
                    v = bool(v)
                else:
                    v = v if isinstance(v, (int, float, bool, str,
                                            type(None))) else str(v)
                obj[c] = v
            if kinds is not None:
                obj["op"] = self._OPS.get(int(kinds[i]), "+I")
            out.append(json.dumps(obj).encode("utf-8"))
        return out


def _json_factory(columns, types, options):
    return (JsonRowDeserializationSchema(
                columns, types,
                ignore_parse_errors=str(options.get(
                    "json.ignore-parse-errors", "false")).lower()
                in ("true", "1", "yes")),
            JsonRowSerializationSchema(columns))


register_format("json", _json_factory)


class CsvRowDeserializationSchema(DeserializationSchema):
    """Positional CSV (reference: flink-formats/flink-csv)."""

    def __init__(self, columns, types=None, delimiter: str = ",",
                 ignore_parse_errors: bool = False):
        self.columns = list(columns)
        self.types = list(types) if types is not None \
            else [None] * len(self.columns)
        self.delimiter = delimiter
        self.ignore_parse_errors = ignore_parse_errors

    def deserialize_batch(self, raw: Sequence[bytes]) -> RecordBatch:
        import csv as _csv

        dts = [_np_dtype(t) for t in self.types]
        rows: List[tuple] = []
        surviving: List[int] = []
        for i, rec in enumerate(raw):
            if isinstance(rec, (bytes, bytearray)):
                rec = rec.decode("utf-8", errors="replace")
            # RFC-4180 parsing (quoted fields may hold the delimiter,
            # quotes, newlines) — symmetric with the serializer; type
            # coercion happens here too so a bad field skips ONE record.
            # Untyped columns keep their raw field text verbatim.
            try:
                parts = next(_csv.reader([rec.rstrip("\r\n")],
                                         delimiter=self.delimiter), [])
                if len(parts) != len(self.columns):
                    raise ValueError(
                        f"CSV record has {len(parts)} fields, expected "
                        f"{len(self.columns)}")
                rows.append(tuple(
                    p if dt is None
                    else _coerce(int(float(p)) if dt is np.int64 and p
                                 else (p or None), dt)
                    for p, dt in zip(parts, dts)))
                surviving.append(i)
            except (ValueError, TypeError) as e:
                if self.ignore_parse_errors:
                    continue
                raise RuntimeError(
                    f"failed to deserialize CSV record {rec!r}: {e} "
                    "(set 'csv.ignore-parse-errors'='true' to skip "
                    "corrupt records)") from e
        self.last_surviving = (None if len(surviving) == len(raw)
                               else surviving)
        return RecordBatch.from_pydict(
            _columns_from_rows(rows, self.columns, dts))


class CsvRowSerializationSchema(SerializationSchema):
    def __init__(self, columns, delimiter: str = ","):
        self.columns = list(columns)
        self.delimiter = delimiter

    def serialize_batch(self, batch: RecordBatch) -> List[bytes]:
        import csv as _csv
        import io as _io

        names = [c for c in self.columns if c in batch.columns]
        cols = {c: batch[c] for c in names}
        out: List[bytes] = []
        buf = _io.StringIO()
        writer = _csv.writer(buf, delimiter=self.delimiter,
                             lineterminator="")
        for i in range(len(batch)):
            buf.seek(0)
            buf.truncate()
            writer.writerow([str(cols[c][i]) for c in names])
            out.append(buf.getvalue().encode("utf-8"))
        return out


def _csv_factory(columns, types, options):
    delim = options.get("csv.field-delimiter", ",")
    ignore = str(options.get("csv.ignore-parse-errors",
                             "false")).lower() in ("true", "1", "yes")
    return (CsvRowDeserializationSchema(columns, types, delimiter=delim,
                                        ignore_parse_errors=ignore),
            CsvRowSerializationSchema(columns, delimiter=delim))


register_format("csv", _csv_factory)
