"""Avro binary format: registry-less encode/decode with schema resolution.

reference: flink-formats/flink-avro/src/main/java/org/apache/flink/formats/
avro/AvroRowDataDeserializationSchema.java:1 (record bytes -> rows under a
reader schema), AvroRowDataSerializationSchema.java, and the schema-
resolution rules of the Avro spec the reference delegates to the Avro
runtime (matching fields by name, defaults for added fields, numeric
promotions, union resolution).

Re-design notes: this is a from-scratch Avro *binary encoding* core (no
avro/fastavro dependency — neither is in the image), scoped to the part the
reference's format actually uses: single-record binary payloads (Kafka
value bytes), NOT the object-container file layout. The batch-granular
seam (formats.DeserializationSchema) turns the decoded rows into one
columnar RecordBatch, so row-oriented Avro stays at the connector boundary
and everything inside the engine remains columnar.

Supported schema forms: null, boolean, int, long, float, double, bytes,
string, fixed, enum, array, map, union, record (nested records included).
Resolution: field match by name or aliases, reader defaults for missing
fields, promotions int->long->float->double and string<->bytes, writer
union branch resolved against the reader schema.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.connectors.formats import (
    DeserializationSchema,
    SerializationSchema,
    _columns_from_rows,
    _np_dtype,
    register_format,
)
from flink_tpu.core.records import RecordBatch

_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double",
               "bytes", "string"}


def parse_schema(schema) -> Any:
    """JSON text / dict / list -> normalized schema tree (dicts with
    'type'; primitives stay strings; named types resolvable by name)."""
    if isinstance(schema, str) and schema.lstrip()[:1] in "[{\"":
        schema = json.loads(schema)
    names: Dict[str, Any] = {}

    def norm(s):
        if isinstance(s, str):
            if s in _PRIMITIVES:
                return s
            if s in names:
                return names[s]
            raise ValueError(f"unknown Avro type {s!r}")
        if isinstance(s, list):
            return {"type": "union", "branches": [norm(b) for b in s]}
        t = s["type"]
        if t in _PRIMITIVES:
            # annotated primitive ({"type": "bytes", "logicalType":
            # "decimal", ...}): logical-type annotations read as their
            # underlying primitive (the spec's required fallback)
            return t
        if t == "record":
            out = {"type": "record", "name": s["name"],
                   "aliases": s.get("aliases", []), "fields": []}
            names[s["name"]] = out
            for f in s["fields"]:
                fld = {"name": f["name"],
                       "aliases": f.get("aliases", []),
                       "schema": norm(f["type"])}
                if "default" in f:
                    fld["default"] = f["default"]
                out["fields"].append(fld)
            return out
        if t == "enum":
            out = {"type": "enum", "name": s["name"],
                   "symbols": list(s["symbols"]),
                   "default": s.get("default")}
            names[s["name"]] = out
            return out
        if t == "fixed":
            out = {"type": "fixed", "name": s["name"],
                   "size": int(s["size"])}
            names[s["name"]] = out
            return out
        if t == "array":
            return {"type": "array", "items": norm(s["items"])}
        if t == "map":
            return {"type": "map", "values": norm(s["values"])}
        if isinstance(t, (dict, list)):
            return norm(t)
        raise ValueError(f"unsupported Avro schema: {s!r}")

    return norm(schema)


def _type_name(s) -> str:
    return s if isinstance(s, str) else s["type"]


# --------------------------------------------------------------------------
# binary encoding (Avro spec: zigzag varints, length-prefixed payloads,
# block-encoded arrays/maps)
# --------------------------------------------------------------------------


class _Writer:
    __slots__ = ("parts",)

    def __init__(self):
        self.parts: List[bytes] = []

    def long(self, v: int) -> None:
        v = (v << 1) ^ (v >> 63)  # zigzag
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self.parts.append(bytes(out))

    def raw(self, b: bytes) -> None:
        self.parts.append(b)

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # un-zigzag

    def raw(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated Avro payload")
        self.pos += n
        return out


def _write(s, v, w: _Writer) -> None:
    t = _type_name(s)
    if t == "null":
        return
    if t == "boolean":
        w.raw(b"\x01" if v else b"\x00")
    elif t in ("int", "long"):
        w.long(int(v))
    elif t == "float":
        w.raw(struct.pack("<f", float(v)))
    elif t == "double":
        w.raw(struct.pack("<d", float(v)))
    elif t == "bytes":
        b = bytes(v)
        w.long(len(b))
        w.raw(b)
    elif t == "string":
        b = str(v).encode("utf-8")
        w.long(len(b))
        w.raw(b)
    elif t == "fixed":
        b = bytes(v)
        if len(b) != s["size"]:
            raise ValueError(f"fixed size {s['size']} != {len(b)}")
        w.raw(b)
    elif t == "enum":
        w.long(s["symbols"].index(v))
    elif t == "array":
        items = list(v)
        if items:
            w.long(len(items))
            for it in items:
                _write(s["items"], it, w)
        w.long(0)
    elif t == "map":
        if v:
            w.long(len(v))
            for k, mv in v.items():
                _write("string", k, w)
                _write(s["values"], mv, w)
        w.long(0)
    elif t == "union":
        for i, branch in enumerate(s["branches"]):
            if _union_accepts(branch, v):
                w.long(i)
                _write(branch, v, w)
                return
        raise ValueError(f"no union branch for {v!r}")
    elif t == "record":
        for f in s["fields"]:
            _write(f["schema"], v[f["name"]], w)
    else:
        raise ValueError(f"unsupported Avro type {t!r}")


def _union_accepts(branch, v) -> bool:
    t = _type_name(branch)
    if v is None:
        return t == "null"
    if isinstance(v, bool):
        return t == "boolean"
    if isinstance(v, (int, np.integer)):
        return t in ("int", "long", "float", "double")
    if isinstance(v, (float, np.floating)):
        return t in ("float", "double")
    if isinstance(v, str):
        return t in ("string", "enum")
    if isinstance(v, (bytes, bytearray)):
        return t in ("bytes", "fixed")
    if isinstance(v, dict):
        return t in ("record", "map")
    if isinstance(v, (list, tuple)):
        return t == "array"
    return False


_PROMOTIONS = {
    ("int", "long"), ("int", "float"), ("int", "double"),
    ("long", "float"), ("long", "double"), ("float", "double"),
    ("string", "bytes"), ("bytes", "string"),
}


def _read(writer_s, reader_s, r: _Reader):
    """Decode per the WRITER schema, resolving into the READER schema
    (Avro spec 'Schema Resolution')."""
    wt, rt = _type_name(writer_s), _type_name(reader_s)
    if wt == "union" and rt != "union":
        branch = writer_s["branches"][r.long()]
        return _read(branch, reader_s, r)
    if rt == "union":
        if wt == "union":
            branch = writer_s["branches"][r.long()]
        else:
            branch = writer_s
        bt = _type_name(branch)
        for rb in reader_s["branches"]:
            if _type_name(rb) == bt or (bt, _type_name(rb)) in _PROMOTIONS:
                return _read(branch, rb, r)
        raise ValueError(
            f"writer branch {bt!r} not in reader union")
    if wt != rt and (wt, rt) not in _PROMOTIONS:
        raise ValueError(f"cannot resolve writer {wt!r} as reader {rt!r}")
    if wt == "null":
        return None
    if wt == "boolean":
        return r.raw(1) == b"\x01"
    if wt in ("int", "long"):
        v = r.long()
        return float(v) if rt in ("float", "double") else v
    if wt == "float":
        return struct.unpack("<f", r.raw(4))[0]
    if wt == "double":
        return struct.unpack("<d", r.raw(8))[0]
    if wt == "bytes":
        b = r.raw(r.long())
        return b.decode("utf-8") if rt == "string" else b
    if wt == "string":
        b = r.raw(r.long())
        return b if rt == "bytes" else b.decode("utf-8")
    if wt == "fixed":
        return r.raw(writer_s["size"])
    if wt == "enum":
        sym = writer_s["symbols"][r.long()]
        if sym not in reader_s["symbols"]:
            if reader_s.get("default") is not None:
                return reader_s["default"]
            raise ValueError(f"enum symbol {sym!r} unknown to reader")
        return sym
    if wt == "array":
        out = []
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:  # block with byte size prefix
                n = -n
                r.long()
            for _ in range(n):
                out.append(_read(writer_s["items"], reader_s["items"], r))
        return out
    if wt == "map":
        out = {}
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                n = -n
                r.long()
            for _ in range(n):
                k = r.raw(r.long()).decode("utf-8")
                out[k] = _read(writer_s["values"], reader_s["values"], r)
        return out
    if wt == "record":
        reader_fields = {}
        for f in reader_s["fields"]:
            reader_fields[f["name"]] = f
            for a in f.get("aliases", []):
                reader_fields[a] = f
        out = {}
        seen = set()
        for wf in writer_s["fields"]:
            rf = reader_fields.get(wf["name"])
            if rf is None:
                _skip(wf["schema"], r)  # writer-only field
                continue
            out[rf["name"]] = _read(wf["schema"], rf["schema"], r)
            seen.add(rf["name"])
        for rf in reader_s["fields"]:
            if rf["name"] in seen:
                continue
            if "default" not in rf:
                raise ValueError(
                    f"reader field {rf['name']!r} missing from writer "
                    "data and has no default")
            out[rf["name"]] = rf["default"]
        return out
    raise ValueError(f"unsupported Avro type {wt!r}")


def _skip(s, r: _Reader) -> None:
    t = _type_name(s)
    if t == "null":
        return
    if t == "boolean":
        r.raw(1)
    elif t in ("int", "long", "enum"):
        r.long()
    elif t == "float":
        r.raw(4)
    elif t == "double":
        r.raw(8)
    elif t in ("bytes", "string"):
        r.raw(r.long())
    elif t == "fixed":
        r.raw(s["size"])
    elif t == "union":
        _skip(s["branches"][r.long()], r)
    elif t == "record":
        for f in s["fields"]:
            _skip(f["schema"], r)
    elif t == "array":
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                r.raw(r.long())
                continue
            for _ in range(n):
                _skip(s["items"], r)
    elif t == "map":
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                r.raw(r.long())
                continue
            for _ in range(n):
                r.raw(r.long())
                _skip(s["values"], r)


def encode_record(schema, datum: dict) -> bytes:
    w = _Writer()
    _write(schema, datum, w)
    return w.getvalue()


def decode_record(writer_schema, reader_schema, payload: bytes) -> dict:
    return _read(writer_schema, reader_schema, _Reader(payload))


# --------------------------------------------------------------------------
# DDL integration: 'format' = 'avro'
# --------------------------------------------------------------------------

_SQL_TO_AVRO = {
    "tinyint": "int", "smallint": "int", "int": "int", "integer": "int",
    "bigint": "long", "float": "float", "double": "double",
    "string": "string", "varchar": "string", "char": "string",
    "boolean": "boolean", "bytes": "bytes", "binary": "bytes",
    "timestamp": "long", "timestamp_ltz": "long", "date": "int",
}


def schema_from_ddl(name: str, columns: Sequence[str],
                    types: Sequence[Optional[str]]):
    """Derive a record schema from the DDL column list (the reference's
    AvroSchemaConverter.convertToSchema role)."""
    fields = []
    for c, t in zip(columns, types):
        base = (t or "string").lower().split("(")[0].strip()
        avro_t = _SQL_TO_AVRO.get(base, "string")
        fields.append({"name": c, "type": ["null", avro_t],
                       "default": None})
    return parse_schema({"type": "record", "name": name, "fields": fields})


class AvroRowDeserializationSchema(DeserializationSchema):
    """Single-record Avro binary payloads -> one typed columnar batch,
    decoding with the WRITER schema resolved into the READER schema."""

    #: varint payloads may contain any byte (0x0A included): file
    #: sources must undo the length-prefix framing the sink wrote —
    #: newline-splitting silently corrupts rows
    binary = True

    def __init__(self, columns: Sequence[str],
                 types: Sequence[Optional[str]],
                 reader_schema, writer_schema=None,
                 ignore_parse_errors: bool = False):
        self.columns = list(columns)
        self.dts = [_np_dtype(t) for t in types]
        self.reader = reader_schema
        self.writer = writer_schema or reader_schema
        self.ignore = ignore_parse_errors

    def deserialize_batch(self, raw: Sequence[bytes]) -> RecordBatch:
        rows: List[tuple] = []
        surviving: List[int] = []
        for i, payload in enumerate(raw):
            try:
                d = decode_record(self.writer, self.reader, payload)
                rows.append(tuple(d.get(c) for c in self.columns))
            except Exception:
                if not self.ignore:
                    raise
                continue
            surviving.append(i)
        self.last_surviving = surviving if len(surviving) != len(raw) \
            else None
        return RecordBatch(_columns_from_rows(rows, self.columns,
                                              self.dts))


class AvroRowSerializationSchema(SerializationSchema):
    binary = True  # varint-encoded payloads may contain any byte

    def __init__(self, columns: Sequence[str], schema):
        self.columns = list(columns)
        self.schema = schema

    def serialize_batch(self, batch: RecordBatch) -> List[bytes]:
        cols = [np.asarray(batch[c]) if c in batch.columns else None
                for c in self.columns]
        out = []
        for i in range(len(batch)):
            datum = {}
            for c, col in zip(self.columns, cols):
                v = None if col is None else col[i]
                if isinstance(v, np.generic):
                    v = v.item()
                datum[c] = v
            out.append(encode_record(self.schema, datum))
        return out


def _avro_factory(columns, types, options):
    reader_json = options.get("avro.schema")
    reader = parse_schema(reader_json) if reader_json else \
        schema_from_ddl("row", columns, types)
    writer_json = options.get("avro.writer-schema")
    writer = parse_schema(writer_json) if writer_json else None
    ignore = str(options.get("avro.ignore-parse-errors",
                             "false")).lower() == "true"
    return (AvroRowDeserializationSchema(columns, types, reader,
                                         writer_schema=writer,
                                         ignore_parse_errors=ignore),
            AvroRowSerializationSchema(columns, reader))


register_format("avro", _avro_factory)
