"""Lookup tables — point lookups against external systems at join time.

reference: LookupTableSource / LookupFunction
(flink-table/flink-table-common/src/main/java/org/apache/flink/table/
connector/source/LookupTableSource.java, .../functions/LookupFunction.java)
and the lookup join
(flink-table-runtime/.../operators/join/lookup/LookupJoinRunner.java) —
the dimension-table enrichment pattern: each stream row fetches the
external row for its key at processing time, with an optional cache
(FLIP-221 'lookup.cache').

Re-design: lookups are BATCHED — one ``lookup(keys)`` call per distinct
key set per micro-batch (the expensive boundary crossed once per batch,
like every other connector seam here), fronted by an LRU cache.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.operators import Operator


class LookupFunction:
    """The lookup seam: ``lookup(keys) -> {column: array}`` returning one
    row per FOUND key, keyed by the first output column matching the
    lookup key. Misses are simply absent. Implementations wrap real
    clients (JDBC, HBase, REST); tests use ``TableLookupFunction``."""

    #: the key column name in the returned rows
    key_column: str = "key"

    def open(self) -> None:
        pass

    def lookup(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class TableLookupFunction(LookupFunction):
    """In-memory dimension table (tests / static enrichment data)."""

    def __init__(self, rows: Sequence[dict], key_column: str):
        self.key_column = key_column
        self._by_key = {r[key_column]: r for r in rows}
        self._columns = list(rows[0].keys()) if rows else [key_column]

    def lookup(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        hits = [self._by_key[k] for k in keys.tolist()
                if k in self._by_key]
        if not hits:
            return {c: np.empty(0) for c in self._columns}
        return {c: np.asarray([r[c] for r in hits])
                for c in self._columns}


class LookupJoinOperator(Operator):
    """Enrich each row with its key's external row (INNER or LEFT).

    reference: LookupJoinRunner + the FLIP-221 caching layer. Per batch:
    distinct keys split into cache hits and misses, ONE lookup() fetches
    the misses, results join back positionally. A cached miss is cached
    too (negative caching, like the reference's missing-key cache).

    Caching is OPT-IN (``cache_size=0`` by default), matching FLIP-221
    where ``lookup.cache`` defaults to NONE — a dimension row updated
    after first access would otherwise never be observed while its key
    sits in the LRU. When enabled, ``cache_ttl_ms`` bounds staleness
    (the reference's partial-cache ``expireAfterWrite``); ``None``
    means entries never expire (static dimension data only)."""

    name = "lookup_join"

    def __init__(self, fn: LookupFunction, key_field: str,
                 right_columns: Optional[Sequence[str]] = None,
                 suffixes=("_l", "_r"), cache_size: int = 0,
                 cache_ttl_ms: Optional[int] = None,
                 left_outer: bool = False):
        self.fn = fn
        self.key_field = key_field
        #: the DECLARED dimension-table columns — always emitted, so
        #: every output batch shares one schema even when a batch's
        #: lookups all miss
        self.right_columns = list(right_columns) if right_columns \
            else None
        self.suffixes = suffixes
        self.cache_size = int(cache_size)
        self.cache_ttl_ms = cache_ttl_ms
        self.left_outer = left_outer
        #: key value -> (row dict or None, write-time ms) — None row is
        #: the negative cache
        self._cache: OrderedDict = OrderedDict()
        self.lookups = 0
        self.cache_hits = 0

    def open(self, ctx) -> None:
        self.fn.open()

    def _fetch(self, key_vals: np.ndarray) -> Dict[object, Optional[dict]]:
        now_ms = time.monotonic() * 1e3
        out: Dict[object, Optional[dict]] = {}
        misses: List[object] = []
        for k in dict.fromkeys(key_vals.tolist()):
            entry = self._cache.get(k) if self.cache_size else None
            if entry is not None and (
                    self.cache_ttl_ms is None
                    or now_ms - entry[1] < self.cache_ttl_ms):
                self._cache.move_to_end(k)
                out[k] = entry[0]
                self.cache_hits += 1
            else:
                if entry is not None:  # expired — refetch
                    del self._cache[k]
                misses.append(k)
        if misses:
            self.lookups += 1
            cols = self.fn.lookup(np.asarray(misses))
            kc = self.fn.key_column
            found = {}
            if cols and len(next(iter(cols.values()))):
                n = len(next(iter(cols.values())))
                for i in range(n):
                    row = {c: cols[c][i] for c in cols}
                    found[row[kc]] = row
            for k in misses:
                row = found.get(k)
                out[k] = row
                if self.cache_size:
                    self._cache[k] = (row, now_ms)
                    while len(self._cache) > self.cache_size:
                        self._cache.popitem(last=False)
        return out

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        n = len(batch)
        if n == 0:
            return []
        if self.key_field not in batch.columns:
            raise RuntimeError(
                f"lookup join key {self.key_field!r} missing from batch "
                f"columns {batch.names()}")
        key_vals = np.asarray(batch[self.key_field])
        rows = self._fetch(key_vals)
        hit = np.asarray([rows[k] is not None
                          for k in key_vals.tolist()], dtype=bool)
        if not self.left_outer:
            batch = batch.filter(hit)
            key_vals = key_vals[hit]
            if len(batch) == 0:
                return []
        kc = self.fn.key_column
        names = self.right_columns
        if names is None:
            # undeclared schema: derive from observed rows (programmatic
            # use); declared columns are preferred for a stable schema
            seen = {c for k in key_vals.tolist()
                    for c in (rows[k] or {})}
            names = sorted(seen) or [kc]
        # columnar assembly: per-UNIQUE-key right values, gathered back
        # to row positions with one inverse-index fancy index per column
        # (K distinct keys per batch, not N rows, touch Python)
        uniq, inv = np.unique(key_vals, return_inverse=True)
        vals: Dict[str, np.ndarray] = {}
        for c in names:
            per_key = [(rows[k] or {}).get(c, np.nan)
                       for k in uniq.tolist()]
            vals[c] = np.asarray(per_key)[inv]
        out = {}
        lcols = batch.columns
        for c, v in lcols.items():
            if c in names and c not in (TIMESTAMP_FIELD,):
                out[c + self.suffixes[0]] = v
            else:
                out[c] = v
        for c in names:
            name = c + self.suffixes[1] if c in lcols else c
            out[name] = vals[c]
        return [RecordBatch(out)]

    def close(self) -> List[RecordBatch]:
        self.fn.close()
        return []
