"""Two-phase-commit sinks: end-to-end exactly once.

reference: Sink V2 SupportsCommitter
(flink-core/.../api/connector/sink2/SupportsCommitter.java, Committer.java)
and the transactional file sink. Protocol (same as the reference):

1. writer.write(batch)          — records land in an uncommitted
                                  transaction (temp files)
2. checkpoint: prepare_commit() — the transaction is sealed; its
                                  committables travel INSIDE the checkpoint
3. checkpoint complete          — commit(committables): atomically publish
4. failover                     — restore re-commits the checkpoint's
                                  committables (idempotent), and anything
                                  written after the checkpoint was never
                                  sealed, so it is simply discarded

In the micro-batch engine "checkpoint complete" is the successful
atomic-rename of the snapshot directory, so commit follows immediately
after; the committables still ride in the checkpoint because a crash
BETWEEN write and commit must re-commit on restore.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional

from flink_tpu.core.records import RecordBatch


class TwoPhaseCommitSink:
    """SPI for exactly-once sinks (reference: SupportsCommitter)."""

    def open(self, subtask_index: int = 0) -> None:
        pass

    def write(self, batch: RecordBatch) -> None:
        """Write into the CURRENT (uncommitted) transaction."""
        raise NotImplementedError

    def prepare_commit(self) -> List[Any]:
        """Seal the current transaction; returns committables that will be
        stored in the checkpoint and later passed to ``commit``. Starts a
        fresh transaction."""
        raise NotImplementedError

    def commit(self, committables: List[Any]) -> None:
        """Publish sealed committables. MUST be idempotent: a failover
        between checkpoint-write and commit replays this call."""
        raise NotImplementedError

    def abort_uncommitted(self, exclude: List[Any]) -> None:
        """Discard transaction leftovers not reachable from ``exclude``
        (restore-time cleanup of post-checkpoint writes)."""

    def abort_current(self) -> None:
        """Abandon the CURRENT (uncommitted) transaction without publishing
        it. Called on failure-path dispose (reference:
        TwoPhaseCommitSinkFunction.close aborts the current transaction);
        the leftovers are cleaned by ``abort_uncommitted`` on restore."""

    def close(self) -> None:
        pass


class ExactlyOnceFileSink(TwoPhaseCommitSink):
    """Transactional jsonl file sink: each transaction is an
    ``.inprogress`` part file, committed by atomic rename to its final
    name (reference: FileSink's pending -> finished file lifecycle).

    Readers only ever see committed part files; a crash leaves
    ``.inprogress`` garbage that restore cleans up.
    """

    def __init__(self, directory: str):
        self.directory = directory
        self._current: Optional[str] = None  # inprogress path
        self._fh = None
        self._txn_seq = 0

    # -- lifecycle -----------------------------------------------------------

    def open(self, subtask_index: int = 0) -> None:
        os.makedirs(self.directory, exist_ok=True)

    def _ensure_txn(self) -> None:
        if self._fh is None:
            name = f"part-{uuid.uuid4().hex[:12]}-{self._txn_seq}"
            self._current = os.path.join(self.directory,
                                         name + ".inprogress")
            self._fh = open(self._current, "w", encoding="utf-8")

    def write(self, batch: RecordBatch) -> None:
        import json

        self._ensure_txn()
        for row in batch.to_rows():
            self._fh.write(json.dumps(row, default=str) + "\n")

    def prepare_commit(self) -> List[Any]:
        if self._fh is None:
            return []
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._fh = None
        pending = self._current
        self._current = None
        self._txn_seq += 1
        return [{"pending": pending,
                 "final": pending[: -len(".inprogress")]}]

    def commit(self, committables: List[Any]) -> None:
        for c in committables:
            pending, final = c["pending"], c["final"]
            if os.path.exists(pending):
                os.replace(pending, final)  # atomic publish
            elif not os.path.exists(final):
                raise IOError(
                    f"committable lost: neither {pending} nor {final} "
                    "exists")
            # else: already committed (idempotent re-commit after failover)

    def abort_current(self) -> None:
        # close the handle but do NOT seal or publish: the .inprogress file
        # stays on disk for restore-time abort_uncommitted cleanup
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
            self._current = None

    def abort_uncommitted(self, exclude: List[Any]) -> None:
        keep = {os.path.basename(c["pending"]) for c in exclude}
        for name in os.listdir(self.directory):
            if name.endswith(".inprogress") and name not in keep:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def close(self) -> None:
        # seal + publish the tail transaction (end of input is a natural
        # commit point — reference: final checkpoint on finished sources)
        self.commit(self.prepare_commit())

    def __getstate__(self):
        return {"directory": self.directory, "_txn_seq": self._txn_seq}

    def __setstate__(self, state):
        self.directory = state["directory"]
        self._txn_seq = state["_txn_seq"]
        self._current = None
        self._fh = None

    @staticmethod
    def read_committed_rows(directory: str) -> List[dict]:
        import json

        rows: List[dict] = []
        if not os.path.isdir(directory):
            return rows
        for name in sorted(os.listdir(directory)):
            if name.endswith(".inprogress"):
                continue
            with open(os.path.join(directory, name), encoding="utf-8") as f:
                rows.extend(json.loads(line) for line in f if line.strip())
        return rows


from flink_tpu.runtime.operators import Operator


class TwoPhaseSinkOperator(Operator):
    """Operator wrapper driving the 2PC protocol from the task loop
    (reference: SinkWriterOperator + CommitterOperator pair)."""

    name = "two_phase_sink"

    def __init__(self, sink: TwoPhaseCommitSink):
        self.sink = sink
        #: committables sealed at the last snapshot, awaiting
        #: checkpoint-complete
        self._pending_commit: List[Any] = []

    def open(self, ctx) -> None:
        self.sink.open(ctx.operator_index)

    def process_batch(self, batch, input_index: int = 0):
        self.sink.write(batch)
        return []

    def process_watermark(self, watermark, input_index: int = 0):
        return []

    def close(self):
        self.sink.close()
        return []

    def dispose(self) -> None:
        # failure path: NEVER commit here — windows fired after the last
        # checkpoint must not be published, or restore re-commits them and
        # produces duplicates. Abort the open transaction; restore's
        # abort_uncommitted cleans the leftovers.
        try:
            self.sink.abort_current()
        except Exception:
            pass

    # -- checkpoint protocol -------------------------------------------------

    def snapshot_state(self):
        # accumulate: a savepoint may seal a transaction without a
        # checkpoint-complete following it — those committables must stay
        # pending (and inside every later snapshot) until actually
        # committed, or their data would be stranded as .inprogress
        self._pending_commit.extend(self.sink.prepare_commit())
        return {"committables": list(self._pending_commit)}

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        if self._pending_commit:
            self.sink.commit(self._pending_commit)
            self._pending_commit = []

    def restore_state(self, state):
        committables = list(state.get("committables", []))
        # 2PC recovery: the checkpoint's sealed transactions are committed
        # (idempotent), everything newer was never sealed -> discard
        self.sink.commit(committables)
        self.sink.abort_uncommitted(exclude=[])
        self._pending_commit = []
