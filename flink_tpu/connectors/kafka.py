"""Kafka-shaped partitioned source/sink on the split framework.

reference: the split-reader connector stack —
flink-connector-base/.../source/reader/SourceReaderBase.java:1 (split
readers over fetchers), flink-connectors/flink-connector-kafka (partitions
as splits, offsets in checkpoint state, partition discovery). Re-design:
a partition IS a SourceSplit; the per-split reader is a plain Source whose
position is the partition offset, so offsets ride checkpoints through the
existing SplitSource snapshot contract with nothing Kafka-specific in the
checkpoint path.

The broker here is an in-process fake (``FakeBroker``) — topics of
append-only partitioned logs with offset-addressed fetch, the exact
surface the real client exposes. Wire a real cluster by implementing the
same four methods against it.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.source_v2 import (
    SourceCoordinator,
    SourceSplit,
    SplitEnumerator,
    SplitSource,
)
from flink_tpu.connectors.sources import Source
from flink_tpu.core.records import RecordBatch


class FakeBroker:
    """In-process broker: named topics of partitioned append-only logs.

    Offset-addressed fetch over columnar chunks; thread-safe (producers
    and the source's split readers run on different threads). Process-wide
    named registry so tests and SQL DDL reach the same instance."""

    _registry: Dict[str, "FakeBroker"] = {}
    _registry_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        #: topic -> partition -> list of (base_offset, RecordBatch)
        self._logs: Dict[str, List[List[Tuple[int, RecordBatch]]]] = {}

    @classmethod
    def get(cls, name: str = "default") -> "FakeBroker":
        with cls._registry_lock:
            b = cls._registry.get(name)
            if b is None:
                b = cls._registry[name] = FakeBroker()
            return b

    @classmethod
    def reset(cls, name: Optional[str] = None) -> None:
        with cls._registry_lock:
            if name is None:
                cls._registry.clear()
            else:
                cls._registry.pop(name, None)

    def create_topic(self, topic: str, partitions: int) -> None:
        with self._lock:
            log = self._logs.setdefault(topic, [])
            while len(log) < partitions:
                log.append([])

    def add_partitions(self, topic: str, new_total: int) -> None:
        """Partition expansion (triggers source re-discovery)."""
        self.create_topic(topic, new_total)

    def partitions(self, topic: str) -> int:
        with self._lock:
            return len(self._logs.get(topic, []))

    def append(self, topic: str, partition: int,
               batch: RecordBatch) -> int:
        """Append a batch to one partition; returns its base offset."""
        with self._lock:
            log = self._logs.setdefault(topic, [])
            while len(log) <= partition:
                log.append([])
            part = log[partition]
            base = (part[-1][0] + len(part[-1][1])) if part else 0
            part.append((base, batch))
            return base

    RAW_FIELD = "__raw__"

    def append_raw(self, topic: str, partition: int, records,
                   timestamps=None) -> int:
        """Append RAW byte records (what a real producer writes); a
        format's DeserializationSchema turns them into columns on the
        consumer side."""
        arr = np.empty(len(records), dtype=object)
        arr[:] = list(records)
        return self.append(topic, partition, RecordBatch.from_pydict(
            {self.RAW_FIELD: arr},
            timestamps=np.asarray(timestamps, dtype=np.int64)
            if timestamps is not None else None))

    def produce_rows(self, topic: str, rows, partition_by=None,
                     num_partitions: Optional[int] = None,
                     timestamp_field: Optional[str] = None) -> None:
        """Test/DDL convenience: route rows to partitions by a key field
        (hash) or round-robin, preserving order within a partition."""
        rows = list(rows)
        if not rows:
            return
        n_parts = num_partitions or max(self.partitions(topic), 1)
        # flint: disable=LCK03 -- topics only grow: create_topic is
        # idempotent-or-raise on a partition-count conflict, so a racing
        # creator changes nothing this routing read depends on
        self.create_topic(topic, n_parts)
        buckets: List[List[dict]] = [[] for _ in range(n_parts)]
        for i, r in enumerate(rows):
            p = (hash(r[partition_by]) % n_parts) if partition_by \
                else i % n_parts
            buckets[p].append(r)
        for p, rs in enumerate(buckets):
            if not rs:
                continue
            cols = {k: np.asarray([r[k] for r in rs]) for k in rs[0]}
            ts = (np.asarray(cols[timestamp_field], dtype=np.int64)
                  if timestamp_field else None)
            # flint: disable=LCK03 -- the partition count read above is
            # only a routing hint; append() self-extends the partition
            # list under its own hold, so a stale count cannot drop rows
            self.append(topic, p, RecordBatch.from_pydict(
                {k: v for k, v in cols.items()}, timestamps=ts))

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int) -> Tuple[Optional[RecordBatch], int]:
        """(batch, next_offset) from ``offset``; (None, offset) when the
        log has nothing past it."""
        with self._lock:
            log = self._logs.get(topic)
            if log is None or partition >= len(log):
                return None, offset
            part = log[partition]
        picked: List[RecordBatch] = []
        n = 0
        next_off = offset
        for base, chunk in part:
            end = base + len(chunk)
            if end <= offset:
                continue
            lo = max(offset, base) - base
            hi = min(len(chunk), lo + (max_records - n))
            if hi <= lo:
                break
            picked.append(chunk.slice(lo, hi))
            n += hi - lo
            next_off = base + hi
            if n >= max_records:
                break
        if not picked:
            return None, offset
        return (picked[0] if len(picked) == 1
                else RecordBatch.concat(picked)), next_off

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            log = self._logs.get(topic)
            if log is None or partition >= len(log):
                return 0
            part = log[partition]
            return (part[-1][0] + len(part[-1][1])) if part else 0


class KafkaClientBroker:
    """Adapter template for a REAL Kafka cluster behind the same
    four-method surface ``FakeBroker`` exposes — what the source/sink
    stack actually depends on (reference: the KafkaConsumer/KafkaProducer
    calls inside flink-connector-kafka's split reader and writer).

    Wire it with any client library (kafka-python, confluent-kafka):

    - ``partitions(topic)``      -> consumer.partitions_for_topic
    - ``fetch(topic, p, offset, max_records)``
                                 -> seek(TopicPartition(topic, p), offset)
                                    + poll(); return a columnar batch
                                    (apply the table's
                                    DeserializationSchema to the raw
                                    values) and the next offset
    - ``end_offset(topic, p)``   -> consumer.end_offsets
    - ``append(topic, p, batch)`` / ``append_raw`` -> producer.send per
                                    record (serialized values)

    Offsets stay in THIS framework's checkpoints (the split position),
    never in Kafka's consumer-group storage — the same
    exactly-once-ownership decision the reference makes. This class
    raises until a client is injected; it exists so the seam is explicit
    and testable, not discovered by reverse-engineering FakeBroker."""

    def __init__(self, client=None):
        if client is None:
            raise RuntimeError(
                "KafkaClientBroker needs a client object implementing "
                "partitions_for/seek/poll/end_offsets/send (no Kafka "
                "client library ships in this environment; FakeBroker "
                "provides the in-process surface)")
        self.client = client

    def create_topic(self, topic: str, partitions: int) -> None:
        raise NotImplementedError("topic administration is external")

    def partitions(self, topic: str) -> int:
        return len(self.client.partitions_for(topic))

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int):
        raise NotImplementedError(
            "implement against your client: seek + poll -> "
            "(RecordBatch, next_offset)")

    def end_offset(self, topic: str, partition: int) -> int:
        raise NotImplementedError(
            "implement against your client: end_offsets")

    def append(self, topic: str, partition: int, batch) -> int:
        raise NotImplementedError(
            "implement against your client: producer.send")

    def append_raw(self, topic: str, partition: int, records,
                   timestamps=None) -> int:
        raise NotImplementedError(
            "implement against your client: producer.send per raw "
            "serialized record (a sink with a value_format writes "
            "through THIS method)")


class KafkaPartitionReader(Source):
    """Reads ONE partition from an offset — the per-split reader. Its
    snapshot position is the committed offset (reference: KafkaSource
    stores per-split offsets in checkpoints, not in the broker)."""

    def __init__(self, broker: FakeBroker, topic: str, partition: int,
                 bounded: bool, start_offset: int = 0,
                 deserializer=None):
        self.broker = broker
        self.topic = topic
        self.partition = partition
        self.bounded = bounded
        #: DeserializationSchema applied to raw byte records (the
        #: format seam — flink_tpu/connectors/formats.py)
        self.deserializer = deserializer
        self._offset = int(start_offset)
        self._stop_at: Optional[int] = None

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        if self.bounded:
            # bounded scan reads up to the end offset AT OPEN (the
            # reference's setBounded(latest) stopping condition)
            self._stop_at = self.broker.end_offset(self.topic,
                                                   self.partition)

    def poll_batch(self, max_records: int) -> Optional[RecordBatch]:
        limit = max_records
        if self._stop_at is not None:
            if self._offset >= self._stop_at:
                return None
            limit = min(limit, self._stop_at - self._offset)
        batch, next_off = self.broker.fetch(
            self.topic, self.partition, self._offset, limit)
        if batch is None:
            # unbounded: stay live (new appends show up on a later poll)
            return None if self._stop_at is not None else RecordBatch({})
        self._offset = next_off
        if self.deserializer is not None \
                and FakeBroker.RAW_FIELD in batch.columns:
            # offsets count RAW records (committed above); parse errors
            # the schema skips do not affect the committed position
            raw_ts = batch.timestamps if batch.has_timestamps else None
            batch = self.deserializer.deserialize_batch(
                list(batch[FakeBroker.RAW_FIELD]))
            if raw_ts is not None:
                # broker (log-append) timestamps survive the format
                # seam; the schema reports which raw records survived
                # so skipped (corrupt) records keep the rest aligned
                surviving = getattr(self.deserializer,
                                    "last_surviving", None)
                if surviving is not None:
                    raw_ts = raw_ts[np.asarray(surviving, dtype=np.int64)]
                if len(batch) == len(raw_ts):
                    batch = batch.with_timestamps(raw_ts)
        return batch

    def snapshot_position(self) -> Dict[str, Any]:
        return {"offset": self._offset}

    def restore_position(self, pos: Dict[str, Any]) -> None:
        self._offset = int(pos["offset"])


class KafkaPartitionEnumerator(SplitEnumerator):
    """One split per partition; unbounded mode re-discovers so partition
    expansion is picked up (reference: KafkaSourceEnumerator periodic
    partition discovery)."""

    def __init__(self, broker: FakeBroker, topic: str, bounded: bool):
        self.broker = broker
        self.topic = topic
        self.bounded = bounded
        self._known = 0

    def discover(self) -> List[SourceSplit]:
        total = self.broker.partitions(self.topic)
        new = [SourceSplit(split_id=f"{self.topic}-{p}", payload=p)
               for p in range(self._known, total)]
        self._known = total
        return new

    def reset(self) -> None:
        # a RE-opened source replays from scratch (see SplitSource.open)
        self._known = 0

    def snapshot_state(self):
        return {"known": self._known}

    def restore_state(self, state):
        self._known = int(state.get("known", 0))


class KafkaPartitionCoordinator(SourceCoordinator):
    """Deterministic partition -> subtask assignment
    (partition % parallelism): reopening at a different parallelism
    REBALANCES partitions with no sticky state to migrate — the split id
    encodes the partition, offsets travel with the split in checkpoints
    (reference: KafkaSourceEnumerator uses the same stateless modulo)."""

    def assign(self, splits) -> Dict[str, int]:
        for s in splits:
            if s.split_id not in self._assignment:
                self._assignment[s.split_id] = \
                    int(s.payload) % self.parallelism
        return dict(self._assignment)

    def restore_state(self, state):
        # recompute instead of trusting a snapshot taken at a different
        # parallelism; assignment is a pure function of (partition, P)
        pass


class KafkaSource(SplitSource):
    """Partitioned, offset-committing, rebalancing source.

    reference surface: KafkaSource builder (topic, bounded/unbounded,
    starting offsets); checkpoints carry per-partition offsets through
    SplitSource.snapshot_position.
    """

    def __init__(self, topic: str, broker: Optional[FakeBroker] = None,
                 broker_name: str = "default", bounded: bool = True,
                 timestamp_field: Optional[str] = None,
                 start_offsets: Optional[Dict[int, int]] = None,
                 value_format=None, **kwargs):
        broker = broker or FakeBroker.get(broker_name)
        self.topic = topic
        self.broker = broker
        start_offsets = start_offsets or {}

        def reader_factory(split: SourceSplit) -> KafkaPartitionReader:
            return KafkaPartitionReader(
                broker, topic, int(split.payload), bounded,
                start_offset=start_offsets.get(int(split.payload), 0),
                deserializer=value_format)

        super().__init__(
            KafkaPartitionEnumerator(broker, topic, bounded),
            reader_factory, timestamp_field=timestamp_field, **kwargs)

    def open(self, subtask_index: int = 0, parallelism: int = 1) -> None:
        if self.coordinator is None:
            self.coordinator = KafkaPartitionCoordinator(parallelism)
        super().open(subtask_index, parallelism)


class KafkaSink:
    """Partitioned sink: rows route to partitions by a key field (hash)
    or round-robin (reference: KafkaSink with a key-hash partitioner).

    Delivery is AT-LEAST-ONCE: writes are not transactional, so batches
    appended after the last completed checkpoint are re-appended on
    crash-restore (the reference's KafkaSink defaults to the same
    guarantee; its EXACTLY_ONCE mode needs broker transactions, which
    the in-process FakeBroker does not model).

    ``upsert_keys`` switches the sink to UPSERT mode (reference:
    upsert-kafka): it accepts a changelog (rows keep their
    ``__rowkind__``), always partitions by the primary key so a key's
    updates stay ordered within one partition, and duplicates from
    at-least-once replay are idempotent after consumer-side last-wins
    compaction — the same effective-exactly-once argument upsert-kafka
    makes."""

    def __init__(self, topic: str, broker: Optional[FakeBroker] = None,
                 broker_name: str = "default",
                 partition_by: Optional[str] = None,
                 num_partitions: int = 1,
                 upsert_keys: Optional[list] = None,
                 value_format=None):
        self.broker = broker or FakeBroker.get(broker_name)
        self.topic = topic
        self.upsert_keys = list(upsert_keys) if upsert_keys else None
        if self.upsert_keys and not partition_by:
            # a key's upserts must stay ordered: route by the key
            partition_by = self.upsert_keys[0]
        self.partition_by = partition_by
        self.num_partitions = int(num_partitions)
        #: SerializationSchema — rows leave as raw encoded records
        self.value_format = value_format
        self._rr = 0

    @property
    def supports_changelog(self) -> bool:
        return self.upsert_keys is not None

    def open(self, subtask_index: int = 0) -> None:
        self.broker.create_topic(self.topic, self.num_partitions)

    def snapshot_state(self) -> dict:
        # round-robin rotation is deterministic across restore
        return {"rr": self._rr}

    def restore_state(self, state: dict) -> None:
        self._rr = int(state.get("rr", 0))

    def _append(self, partition: int, batch: RecordBatch) -> None:
        if self.value_format is not None:
            self.broker.append_raw(
                self.topic, partition,
                self.value_format.serialize_batch(batch),
                timestamps=batch.timestamps
                if batch.has_timestamps else None)
        else:
            self.broker.append(self.topic, partition, batch)

    def write(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        if self.partition_by and self.partition_by in batch.columns:
            from flink_tpu.state.keygroups import hash_keys_to_i64

            parts = (hash_keys_to_i64(batch[self.partition_by])
                     % self.num_partitions).astype(np.int64)
            for p in range(self.num_partitions):
                mask = parts == p
                if mask.any():
                    self._append(p, batch.filter(mask))
        else:
            self._append(self._rr % self.num_partitions, batch)
            self._rr += 1

    def close(self) -> None:
        pass
