"""Batched segment/scatter primitives for keyed state.

This is the TPU replacement for the reference's per-record state mutation hot
path (reference: flink-runtime/.../state/heap/HeapAggregatingState.java:94,101
``add -> stateTable.transform`` — one virtual call + hash probe per record).
Here an entire micro-batch of ``AggregateFunction.add`` calls collapses into
one XLA scatter onto a device-resident slot array:

    acc = acc.at[slot_ids].add(values)     # one fused kernel, N records

Conventions:
- Slot 0 is the *identity slot*: never allocated, always holds the identity
  element. Padded lanes point at slot 0 with identity values so fixed bucket
  shapes never change results.
- Batches are padded to power-of-two buckets (``pad_bucket_size``) so XLA
  compiles a small bounded set of program shapes.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp
import numpy as np

# scatter reduce -> jnp .at[] method name
SCATTER_METHOD: Dict[str, str] = {
    "sum": "add",
    "max": "max",
    "min": "min",
}

# merge across the slice axis when combining per-slice partial aggregates
# (the slice-sharing trick; reference:
# flink-table-runtime/.../window/tvf/slicing/SliceAssigners.java)
MERGE_FN: Dict[str, Callable] = {
    "sum": jnp.sum,
    "max": jnp.max,
    "min": jnp.min,
}

# host-side elementwise combine of two partial-aggregate arrays (the spill
# tier merges spilled slice values into device-fired results on host)
HOST_COMBINE: Dict[str, Callable] = {
    "sum": np.add,
    "max": np.maximum,
    "min": np.minimum,
}

_MIN_BUCKET = 256


def pad_bucket_size(n: int, minimum: int = _MIN_BUCKET) -> int:
    """Next power-of-two >= n (>= minimum). Bounds the set of XLA shapes."""
    if n <= minimum:
        return minimum
    return 1 << (int(n - 1).bit_length())


def sticky_bucket(n: int, cached: int, minimum: int = _MIN_BUCKET) -> int:
    """Bucket size reusing a previously-compiled bucket when reasonable.

    Reuses ``cached`` when it covers ``n`` and wastes at most 4x padding —
    avoiding the recompile ladder as batch sizes ramp up — but falls back to
    the exact bucket when a past spike would otherwise inflate every later
    call's padding permanently.
    """
    need = pad_bucket_size(n, minimum)
    if need <= cached <= 4 * need:
        return cached
    return need


def pad_i32(a: np.ndarray, size: int, fill: int = 0) -> np.ndarray:
    """Pad an int index array up to ``size`` with ``fill`` (slot 0 default)."""
    a = np.asarray(a, dtype=np.int32)
    if len(a) == size:
        return a
    out = np.full(size, fill, dtype=np.int32)
    out[: len(a)] = a
    return out


def pad_values(a: np.ndarray, size: int, fill) -> np.ndarray:
    a = np.asarray(a)
    if len(a) == size:
        return a
    out = np.full((size,) + a.shape[1:], fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


def identity_for(reduce: str, dtype) -> float:
    """Identity element of a scatter reduce for ``dtype``."""
    dtype = np.dtype(dtype)
    if reduce == "sum":
        return dtype.type(0)
    if reduce == "max":
        if np.issubdtype(dtype, np.floating):
            return dtype.type(-np.inf)
        return np.iinfo(dtype).min
    if reduce == "min":
        if np.issubdtype(dtype, np.floating):
            return dtype.type(np.inf)
        return np.iinfo(dtype).max
    raise ValueError(f"unknown reduce {reduce!r}")
