from flink_tpu.ops.segment_ops import (
    SCATTER_METHOD,
    MERGE_FN,
    pad_bucket_size,
    pad_i32,
)

__all__ = ["SCATTER_METHOD", "MERGE_FN", "pad_bucket_size", "pad_i32"]
