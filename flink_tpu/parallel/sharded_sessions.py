"""Mesh-sharded session windows.

The multi-device form of ``flink_tpu.windowing.sessions.SessionWindower``
(reference: WindowOperator.java:159-162 / MergingWindowSet): session interval
*metadata* stays global on the host (``SessionIntervalSet``, shared with the
single-device engine), while accumulator *state* lives in ``[P, capacity]``
device arrays sharded over the key-group mesh axis.

Why this shards cleanly: sessions are per-key, and keys are routed to exactly
one shard by the key-group formula (reference:
KeyGroupRangeAssignment.java:124-127) — so session merges NEVER cross shards.
Every device step (record scatter, session merge, fire, reset) is ONE jitted
``shard_map`` program over the whole mesh; the scatter/fire/reset programs are
the same ones the mesh window engine uses (``build_mesh_steps``), plus one
session-merge program (``acc[dst] op= acc[src]; acc[src] = identity``).

Snapshots use the same logical format as SessionWindower (key_id / namespace
/ key_group / leaf columns + interval metadata), so session checkpoints are
mutually restorable across engines and mesh sizes.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.chaos import injection as chaos
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.observe import flight_recorder as flight
from flink_tpu.ops.segment_ops import (
    SCATTER_METHOD,
    pad_bucket_size,
    sticky_bucket,
)
from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.parallel.sharded_windower import (
    MeshPagedSpillSupport,
    build_delta_fire_step,
    build_mesh_steps,
)
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
from flink_tpu.parallel.shuffle import (
    bucket_by_shard,
    build_exchange_scatter,
    shard_records,
    stage_device_exchange,
)
from flink_tpu.state.keygroups import _splitmix64, assign_key_groups
from flink_tpu.state.slot_table import resolve_slot_hints
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.session_meta import MergeGroup, make_session_meta
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD

#: hot-key splitting: upper bound on sub-keys per split key. Salted
#: sub-rows live in the SAME state plane as real sessions, addressed by
#: (salted key, salted namespace): ``ssid = -(sid * MAX_SALTS + salt
#: + 1)`` — globally unique NEGATIVE namespaces that can never collide
#: with real (non-negative) session ids, and decode back to (sid, salt).
MAX_SALTS = 64

_SALT_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _salted_keys(key_ids: np.ndarray, salts: np.ndarray) -> np.ndarray:
    """Deterministic synthetic key id for (key, salt) — splitmix64 over
    the XOR-folded pair, so every sub-key lands in its own key group
    (that is the point: the group spread is what moves the load)."""
    x = (np.asarray(key_ids, dtype=np.int64).astype(np.uint64)
         ^ ((np.asarray(salts, dtype=np.uint64) + np.uint64(1))
            * _SALT_GOLDEN))
    return _splitmix64(x).astype(np.int64)


def _salted_ns(sids: np.ndarray, salts: np.ndarray) -> np.ndarray:
    """(sid, salt) -> unique negative namespace (see MAX_SALTS)."""
    return -(np.asarray(sids, dtype=np.int64) * MAX_SALTS
             + np.asarray(salts, dtype=np.int64) + 1)


def build_session_merge_step(mesh: Mesh, agg: AggregateFunction):
    """One shard_map program: ``acc[p, dst] op= acc[p, src]`` for [P, M]
    index blocks, then reset the src slots to identity (the mesh form of
    sessions._merge_jit). Padded lanes use dst == src == 0 (reserved
    identity slot) and are pure no-ops."""
    key = (tuple(d.id for d in mesh.devices.flat), agg.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "session-merge", key, lambda: _build_session_merge_step(mesh, agg))


def _build_session_merge_step(mesh: Mesh, agg: AggregateFunction):
    methods = tuple(SCATTER_METHOD[l.reduce] for l in agg.leaves)
    idents = tuple(l.identity for l in agg.leaves)
    n_leaves = len(agg.leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def merge_step(accs, dst, src):
        def local(*args):
            accs_l = args[:n_leaves]
            d = args[n_leaves][0]
            s = args[n_leaves + 1][0]
            out = []
            for a, m, i in zip(accs_l, methods, idents):
                moved = a[0][s]
                a = getattr(a.at[0, d], m)(moved)
                a = a.at[0, s].set(jnp.asarray(i, dtype=a.dtype))
                out.append(a)
            return tuple(out)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 2),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, dst, src)

    return merge_step


class MeshSessionEngine(MeshPagedSpillSupport):
    """Keyed session windows sharded over a 1-D device mesh.

    Spill layout (mirrors ``SessionWindower``): sessions are one row per
    namespace (sid), so the default ``spill_layout="pages"`` moves
    eviction COHORTS per shard (slot-granular touch clocks,
    split-on-reload — see flink_tpu.state.paged_spill) and runs the host
    indexes registry-free. An explicit ``spill_layout="namespaces"``
    keeps the registry-driven per-namespace eviction."""

    def __init__(
        self,
        gap: int,
        agg: AggregateFunction,
        mesh: Mesh,
        capacity_per_shard: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        max_device_slots: int = 0,
        spill_dir: Optional[str] = None,
        spill_host_max_bytes: int = 0,
        key_group_range: Optional[Tuple[int, int]] = None,
        memory=None,
        spill_layout: str = "pages",
        max_dispatch_ahead: int = 2,
        shuffle_mode: str = "device",
        host_topology=None,
    ) -> None:
        self.gap = int(gap)
        self.agg = agg
        self.shuffle_mode = self._check_shuffle_mode(shuffle_mode)
        #: dispatch-ahead depth: how many batches' device work may be in
        #: flight while the host preps the next (double-buffered by
        #: default; see MeshSpillSupport._init_pipeline)
        self.max_dispatch_ahead = max(int(max_dispatch_ahead or 1), 1)
        if spill_layout not in ("namespaces", "pages"):
            raise ValueError(
                f"spill_layout must be 'namespaces' or 'pages', got "
                f"{spill_layout!r}")
        self.spill_layout = spill_layout
        #: registry-backed namespace bookkeeping only for the explicit
        #: "namespaces" layout; the paged layout frees by SLOT and the
        #: per-namespace registry would cost O(live sessions) Python
        #: per batch at one row per sid
        self._track_ns = spill_layout == "namespaces"
        #: (first, last) inclusive GLOBAL key groups this engine owns; the
        #: mesh shards within the range (mesh x stage — see shard_records)
        self.key_group_range = key_group_range
        #: (MemoryManager, owner) — managed [P, capacity] accounting
        self._memory = memory
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self._set_host_topology(host_topology)
        #: per-SHARD HBM slot budget; cold sessions spill per shard and
        #: reload on access (see MeshSpillSupport — the 10M-key session
        #: capacity of BASELINE row 5 cannot be device-resident)
        self.max_device_slots = int(max_device_slots or 0)
        self.capacity = max(int(capacity_per_shard), 1024)
        if self.max_device_slots:
            self.max_device_slots = max(self.max_device_slots, 1024)
            self.capacity = min(self.capacity, self.max_device_slots)
        self.max_parallelism = max_parallelism
        self.allowed_lateness = int(allowed_lateness)
        if max_parallelism < self.P:
            raise ValueError(
                f"max_parallelism {max_parallelism} < mesh size {self.P}")

        # growable per-shard indexes (see MeshWindowEngine: skew grows the
        # table instead of failing the job)
        self.indexes = self._make_shard_indexes()
        self._init_spill(spill_dir, spill_host_max_bytes)
        self._paged = (spill_layout == "pages"
                       and self.max_device_slots > 0)
        if self._paged:
            self._init_paged()
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._reserve_rows(self.P * self.capacity)
        self.accs: Tuple[jnp.ndarray, ...] = tuple(
            jax.device_put(
                jnp.full((self.P, self.capacity), leaf.identity,
                         dtype=leaf.dtype),
                self._sharding)
            for leaf in agg.leaves
        )
        self._build_steps()
        #: session-interval metadata: the native C sweep when compiled,
        #: else the pure-Python plane (bit-identical fires/snapshots)
        self.meta = make_session_meta(self.gap, self.allowed_lateness)
        self._dirty = np.zeros((self.P, self.capacity), dtype=bool)
        #: freed-session tombstone chunks (int64 arrays, deduped at
        #: snapshot time — per-fire tolist round-trips were measurable)
        self._freed_ns: List[np.ndarray] = []
        #: hot-key splitting (two-stage aggregation): key_id -> number of
        #: salts. Records for a hot key are salted into sub-keys whose
        #: partials live as ordinary (salted-key, negative-ns) rows in the
        #: SAME state plane — spill, checkpoint and reshard machinery see
        #: nothing special. Fires and queries fold the sub-rows back in a
        #: fixed order (main row, then salts ascending) on the host.
        self._hot_keys: Dict[int, int] = {}
        #: records diverted through the salting path (skew gauge)
        self._hot_salted_records = 0
        #: fires that folded at least one salted sub-row (skew gauge)
        self._hot_salted_fires = 0
        self._merge_bucket = 0
        self._fire_bucket = 0
        self._reset_bucket = 0
        self._gather_bucket = 0

    @property
    def late_records_dropped(self) -> int:
        return self.meta.late_records_dropped

    def _build_steps(self) -> None:
        (self._scatter_step, self._fire_step, self._reset_step,
         self._gather_step, self._put_step, self._merge_leaves_step,
         self._valued_scatter_step) = build_mesh_steps(self.mesh, self.agg)
        self._merge_step = build_session_merge_step(self.mesh, self.agg)
        # delta-harvest family: fire + reset fused into ONE dispatch —
        # a session fire pops only the sessions that close, merges and
        # finishes them, and resets their slots in a single program
        self._delta_fire_step = build_delta_fire_step(self.mesh, self.agg)
        # fused exchange+scatter (device shuffle mode) — built through
        # the shared program cache regardless of mode (cheap closure;
        # compiles lazily on first use)
        self._exchange_scatter_step = build_exchange_scatter(
            self.mesh, self.agg, valued=False)
        if self._two_level_active():
            from flink_tpu.parallel.exchange2 import (
                build_exchange2_steps,
            )

            self._exchange2_steps = build_exchange2_steps(
                self.mesh, self.host_topology, self.agg, valued=False)

    def _shard_index_grew(self, new_capacity: int) -> None:
        """Uniform-SPMD grow: widen [P, capacity] arrays to the largest
        shard index (same contract as MeshWindowEngine)."""
        if new_capacity <= self.capacity:
            return
        self._reserve_rows(self.P * (new_capacity - self.capacity))
        old = self.capacity
        self.capacity = new_capacity
        grown = []
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        for host, leaf in zip(accs_host, self.agg.leaves):
            padded = np.full((self.P, new_capacity), leaf.identity,
                             dtype=leaf.dtype)
            padded[:, :old] = host
            grown.append(jax.device_put(jnp.asarray(padded),
                                        self._sharding))
        self.accs = tuple(grown)
        dirty = np.zeros((self.P, new_capacity), dtype=bool)
        dirty[:, :old] = self._dirty
        self._dirty = dirty
        if self._paged:
            self._paged_grow(new_capacity)

    def _put_sharded(self, host_block: np.ndarray) -> jnp.ndarray:
        return jax.device_put(host_block, self._sharding)

    # ---------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        with self._flight_ingest():
            self._process_batch_inner(batch)

    def _process_batch_inner(self, batch: RecordBatch) -> None:
        n = len(batch)
        # batch boundary: the engine is consistent at a known source
        # position — the one point the watchdog may declare a shard dead
        self._wd_boundary()
        if self._paged:
            # page sweeps queued by fire-path extractions run HERE, on
            # the ingest step, so fires stay bounded deltas
            self._drain_deferred_sweeps()
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        keys = np.asarray(batch.key_ids, dtype=np.int64)
        if self._spill_active and n > 1:
            # bound one batch's PER-SHARD session working set by the
            # budget (the budget is per device): unique keys per shard
            # upper-bounds the touched sessions there; halving is safe
            # because absorb_batch is incremental. The bound used to
            # compare GLOBAL uniques against the per-shard budget,
            # splitting every batch whose key cardinality exceeded one
            # shard's slots even though each shard only sees ~1/P of
            # them — at the 10M-key bench shape that halved every batch
            # and doubled the per-batch host fixed costs (absorb, slot
            # resolution, dispatch).
            budget = max(self.max_device_slots // 2, 1024)
            if n > budget:
                # cheapest sufficient bound first: per-shard RECORD
                # counts dominate per-shard uniques (one hash pass, no
                # sort); only a shard actually over the record bound
                # pays the np.unique refinement
                rsm = getattr(self.meta, "rec_shard_max", None)
                if self._assignment is not None:
                    # the native sweep hard-codes the contiguous
                    # group->shard formula — a live rebalanced table
                    # must take the numpy path
                    rsm = None
                if rsm is not None:
                    rec_max = rsm(keys, self.P, self.max_parallelism,
                                  self.key_group_range)
                else:
                    rec_max = int(np.bincount(
                        self._route(keys),
                        minlength=self.P).max())
                if rec_max > budget:
                    uniq = np.unique(keys)
                    per_shard = np.bincount(
                        self._route(uniq),
                        minlength=self.P)
                    if int(per_shard.max()) > budget:
                        half = np.zeros(n, dtype=bool)
                        half[: n // 2] = True
                        # split ingest stays ONE failover boundary (the
                        # probe must not land between the halves)
                        self._ingest_subbatch(batch.filter(half))
                        self._ingest_subbatch(batch.filter(~half))
                        return

        from flink_tpu.windowing.session_meta import NativePlaneError

        with flight.span("prep.meta_sweep"):
            try:
                res = self.meta.absorb_batch_ex(keys, ts,
                                                want_fresh=self._paged)
            except NativePlaneError as e:
                # graceful degradation: the absorb is the batch's FIRST
                # mutation (no device state touched yet), so the batch
                # is re-runnable on the Python plane — once, loudly,
                # instead of crashing the job (interval extends are
                # idempotent, so the partially-swept metadata converges;
                # value scatter has not happened)
                self._meta_fallback(e)
                res = self.meta.absorb_batch_ex(keys, ts,
                                                want_fresh=self._paged)
        sess_key, sess_sid = res.sess_key, res.sess_sid
        rec_to_sess, order, groups = res.rec_to_sess, res.order, res.groups
        for g in groups:
            self._run_merge_group(g)

        live_sess = sess_sid >= 0
        if not live_sess.all():
            starts_pos = np.nonzero(
                np.diff(rec_to_sess, prepend=-1) > 0)[0]
            sess_counts = np.diff(np.append(starts_pos, n))
            self.meta.late_records_dropped += int(
                sess_counts[~live_sess].sum())

        # per-shard slot resolution for the live sessions: ONE stable
        # counting sort by shard replaces P boolean-mask scans — the
        # per-shard selections become contiguous slices of one index
        # array (within-shard session order unchanged: the sort is
        # stable over ascending session indices). The native metadata
        # plane runs the shard assignment + grouping + column gather as
        # one C sweep (sx_shard_group, same keygroups formula); the
        # Python plane takes the equivalent numpy path.
        m = len(sess_key)
        per_shard_sel = {}
        shard_slices = {}
        sg = getattr(self.meta, "shard_group", None)
        if self._assignment is not None:
            # sx_shard_group applies the contiguous formula in C; under
            # a rebalanced assignment the equivalent numpy path routes
            # through the table (meta.route_records below stays valid —
            # it consumes the sess_shard we hand it)
            sg = None
        if sg is not None:
            (sess_shard, counts, sorted_idx, key_sorted, sid_sorted,
             fresh_sorted, hint_sorted, row_sorted) = sg(
                res, self.P, self.max_parallelism, self.key_group_range)
            offs = np.concatenate(([0], np.cumsum(counts)))
            for p in np.nonzero(counts)[0].tolist():
                a, b = int(offs[p]), int(offs[p + 1])
                shard_slices[p] = (a, b)
                per_shard_sel[p] = sorted_idx[a:b]
        else:
            sess_shard = self._route(sess_key)
            live_idx = np.nonzero(live_sess)[0]
            sorted_idx = live_idx
            if len(live_idx):
                shards_live = sess_shard[live_idx]
                sorted_idx = live_idx[np.argsort(shards_live,
                                                 kind="stable")]
                counts = np.bincount(shards_live, minlength=self.P)
                offs = np.concatenate(([0], np.cumsum(counts)))
                for p in np.nonzero(counts)[0].tolist():
                    a, b = int(offs[p]), int(offs[p + 1])
                    shard_slices[p] = (a, b)
                    per_shard_sel[p] = sorted_idx[a:b]
            key_sorted = sess_key[sorted_idx]
            sid_sorted = sess_sid[sorted_idx]
            fresh_sorted = (None if res.fresh is None
                            else res.fresh[sorted_idx])
            hint_sorted = (None if res.slot_hint is None
                           else res.slot_hint[sorted_idx])
            row_sorted = (None if res.meta_row is None
                          else res.meta_row[sorted_idx])
        slot_of_sess = None
        if self._paged:
            # sessions CREATED by this absorb (res.fresh: allocated by
            # this absorb, minus merge destinations — see
            # SessionIntervalSet.absorb_batch_ex) cannot be resident or
            # paged: the resolve skips their index probe and page
            # query. Sessions carrying a FOLDED device slot from the
            # native metadata plane (res.slot_hint) skip the hash probe
            # after metadata verification — at high key cardinality the
            # state-plane hash is only probed for rows whose fold went
            # stale (eviction, restore, reshard). Per-shard columns are
            # gathered ONCE through the shard-sorted index and sliced
            # contiguously — no per-shard fancy indexing.
            resolved = self._resolve_slots_paged(
                {p: (key_sorted[a:b], sid_sorted[a:b])
                 for p, (a, b) in shard_slices.items()},
                fresh={p: fresh_sorted[a:b]
                       for p, (a, b) in shard_slices.items()},
                hints=(None if hint_sorted is None else
                       {p: hint_sorted[a:b]
                        for p, (a, b) in shard_slices.items()}))
            slot_sorted = np.zeros(len(sorted_idx), dtype=np.int32)
            for p, (a, b) in shard_slices.items():
                slot_sorted[a:b] = resolved[p]
                self._dirty[p, resolved[p]] = True
                self._rep_mark(p, resolved[p])
            # fold the resolved slots into the metadata rows so the
            # NEXT batch's resolve skips the probe (native plane only)
            self.meta.note_slots(key_sorted, sid_sorted, slot_sorted,
                                 rows=row_sorted)
        else:
            slot_of_sess = np.zeros(m, dtype=np.int32)
            if self._spill_active:
                touched = {p: np.unique(sess_sid[sel])
                           for p, sel in per_shard_sel.items()}
                self._ensure_resident(touched)
                for p, sids in touched.items():
                    self._touch(p, sids.tolist())
            for p, sel in per_shard_sel.items():
                self._reserve(p, sess_key[sel], sess_sid[sel])
                slots = self.indexes[p].lookup_or_insert(
                    sess_key[sel], sess_sid[sel])
                slot_of_sess[sel] = slots
                self._dirty[p, slots] = True
                self._rep_mark(p, slots)
            slot_sorted = slot_of_sess[sorted_idx]

        # route records: each record scatters into its session's slot on
        # its session's shard (stale records keep slot 0 = identity) —
        # one C pass on the native plane, numpy otherwise
        rt = getattr(self.meta, "route_records", None)
        if rt is not None:
            rec_slots, rec_shards = rt(n, order, rec_to_sess, m,
                                       sorted_idx, slot_sorted,
                                       sess_shard)
        else:
            if slot_of_sess is None:
                slot_of_sess = np.zeros(m, dtype=np.int32)
                slot_of_sess[sorted_idx] = slot_sorted
            rec_slots = np.empty(n, dtype=np.int32)
            rec_slots[order] = slot_of_sess[rec_to_sess]
            rec_shards = np.empty(n, dtype=sess_shard.dtype)
            rec_shards[order] = sess_shard[rec_to_sess]
        if self._hot_keys:
            rec_slots, rec_shards = self._salt_hot_records(
                keys, ts, sess_key, sess_sid, rec_to_sess, order,
                rec_slots, rec_shards)
        values = self.agg.map_input(batch)
        in_leaves = self.agg.input_leaves
        # pipelining: claim a dispatch slot BEFORE rewriting the pooled
        # staging buffers (their previous consumer must have finished),
        # then stage batch k+1 while the device still runs batch k
        self._await_dispatch_slot()
        self._shuffle_pool.flip()
        columns = [np.asarray(rec_slots, dtype=np.int32),
                   *[np.asarray(v, dtype=l.dtype)
                     for v, l in zip(values, in_leaves)]]
        fills = [0, *[l.identity for l in in_leaves]]
        if self._two_level_active():  # implies device shuffle mode
            # pod mesh: the two-level ICI/DCN exchange (see
            # parallel/exchange2.py) — bit-identical to the flat
            # program, two dispatches so ICI vs DCN time attributes
            # as distinct span kinds
            from flink_tpu.parallel.exchange2 import (
                stage_two_level_exchange,
            )

            with flight.span("prep.stage"):
                dst, staged, w1, w2 = stage_two_level_exchange(
                    rec_shards, self.host_topology, columns=columns,
                    fills=fills, pool=self._shuffle_pool,
                    traffic=self._exchange2_traffic)
            s1, s2 = self._exchange2_steps
            with self._device_span(), flight.span("exchange.stage1"):
                put = jax.device_put((dst, *staged), self._sharding)
                inter = s1(put[0], put[1], tuple(put[2:]), w1)
            with self._device_span(), flight.span("exchange.stage2"):
                self.accs = s2(self.accs, inter[0], inter[1],
                               tuple(inter[2:]), w2)
            chaos.fault_point("shuffle.device_exchange", records=n)
        elif self.shuffle_mode == "device":
            with flight.span("prep.stage"):
                dst, staged, width = stage_device_exchange(
                    rec_shards, self.P, columns=columns, fills=fills,
                    pool=self._shuffle_pool)
            with self._device_span():
                # ONE host->device hop: all flat columns in a single
                # device_put, then the fused exchange+scatter program
                put = jax.device_put((dst, *staged), self._sharding)
                self.accs = self._exchange_scatter_step(
                    self.accs, put[0], put[1], tuple(put[2:]), width)
            # "crash mid-batch after the fused dispatch" — the scatter
            # is on the device queue, the host dies before the fence
            chaos.fault_point("shuffle.device_exchange", records=n)
        else:
            with flight.span("prep.stage"):
                counts, blocked = bucket_by_shard(
                    rec_shards, self.P, columns=columns, fills=fills,
                    pool=self._shuffle_pool)
            slot_block = blocked[0]
            value_blocks = blocked[1:]
            with self._device_span():
                self.accs = self._scatter_step(
                    self.accs,
                    self._put_sharded(slot_block),
                    tuple(self._put_sharded(v) for v in value_blocks),
                )
        self._push_dispatch_fence()

    def _run_merge_group(self, g: MergeGroup) -> None:
        gk = np.asarray(g.keys_dst, dtype=np.int64)
        ds = np.asarray(g.sids_dst, dtype=np.int64)
        ss = np.asarray(g.sids_src, dtype=np.int64)
        if self._hot_keys:
            gk, ds, ss = self._expand_hot_merges(gk, ds, ss)
        shards = self._route(gk)
        # combined dst+src pairs per shard (dst and src share the key,
        # hence the shard): with a spill tier, both sides must be
        # device-resident simultaneously for the merge kernel
        pairs: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for p in range(self.P):
            sel = shards == p
            if sel.any():
                pairs[p] = (np.concatenate([gk[sel], gk[sel]]),
                            np.concatenate([ds[sel], ss[sel]]))
        resolved: Dict[int, np.ndarray] = {}
        if self._paged:
            resolved = self._resolve_slots_paged(pairs)
        else:
            if self._spill_active:
                touched = {p: np.unique(sids2)
                           for p, (_, sids2) in pairs.items()}
                self._ensure_resident(touched)
                for p, sids in touched.items():
                    self._touch(p, sids.tolist())
            for p, (keys2, sids2) in pairs.items():
                self._reserve(p, keys2, sids2)
                resolved[p] = self.indexes[p].lookup_or_insert(
                    keys2, sids2)
        m_max = 0
        per_shard: List[Tuple[np.ndarray, np.ndarray]] = []
        for p in range(self.P):
            if p not in pairs:
                per_shard.append((np.empty(0, np.int32),
                                  np.empty(0, np.int32)))
                continue
            both = resolved[p]
            c = len(both) // 2
            d_slots, s_slots = both[:c], both[c:]
            self._dirty[p, d_slots] = True
            self._rep_mark(p, d_slots)
            per_shard.append((d_slots.astype(np.int32),
                              s_slots.astype(np.int32)))
            m_max = max(m_max, c)
        if m_max == 0:
            return
        M = sticky_bucket(m_max, self._merge_bucket)
        self._merge_bucket = M
        dst_block = np.zeros((self.P, M), dtype=np.int32)
        src_block = np.zeros((self.P, M), dtype=np.int32)
        for p, (d_slots, s_slots) in enumerate(per_shard):
            dst_block[p, : len(d_slots)] = d_slots
            src_block[p, : len(s_slots)] = s_slots
        with self._device_span():
            self.accs = self._merge_step(
                self.accs, self._put_sharded(dst_block),
                self._put_sharded(src_block))
        if self._paged:
            # fold the merge DESTINATIONS' resolved slots into their
            # metadata rows (native plane) — the dst sessions live on
            # and would otherwise pay a probe next batch
            fk, fs, fl = [], [], []
            for p, (d_slots, _) in enumerate(per_shard):
                if p not in pairs or not len(d_slots):
                    continue
                c = len(d_slots)
                pk2, ps2 = pairs[p][0][:c], pairs[p][1][:c]
                if self._hot_keys:
                    # salted sub-rows (negative sids) have no metadata
                    # row to fold a slot into
                    keep2 = ps2 >= 0
                    pk2, ps2 = pk2[keep2], ps2[keep2]
                    d_slots = d_slots[keep2]
                if len(pk2):
                    fk.append(pk2)
                    fs.append(ps2)
                    fl.append(d_slots)
            if fk:
                self.meta.note_slots(np.concatenate(fk),
                                     np.concatenate(fs),
                                     np.concatenate(fl))
        # absorbed host slots reusable now that the kernel moved the values;
        # record tombstones so delta snapshots drop the absorbed rows
        self._freed_ns.append(
            np.asarray(g.absorbed_sids, dtype=np.int64))
        if self._track_ns:
            self._drop_spilled(g.absorbed_sids)
            for p in range(self.P):
                self.indexes[p].free_namespaces(g.absorbed_sids)
        else:
            # registry-free: the absorbed rows' slots are in hand (the
            # src half of each shard's combined lookup)
            for p, (_, s_slots) in enumerate(per_shard):
                if p not in pairs:
                    continue
                src_sids = pairs[p][1][len(s_slots):]
                if self._paged:
                    self._free_rows_paged(p, s_slots, src_sids)
                else:
                    self.indexes[p].free_slots(s_slots)
                    self._dirty[p, s_slots] = False

    # ---------------------------------------------------- hot-key splitting

    def register_hot_key(self, key_id: int, salts: int = 8,
                         allow_inexact: bool = False) -> int:
        """Two-stage aggregation for one dominating key: salt its
        records into ``salts`` sub-keys, pre-aggregated on their OWN
        shards as ordinary (salted-key, negative-namespace) rows, and
        folded back into the main row's result at fire / query time in
        a fixed order (main row, then salts ascending — the same fold
        discipline the exchange applies within a shard).

        Exactness: min/max and integer sums commute freely, so salting
        is bit-identical to the unsalted oracle. Floating-point sums
        reassociate; pass ``allow_inexact=True`` to accept that —
        streams whose values are integer-valued floats (e.g. counters
        held in float32, exact below 2**24) remain bit-identical in
        practice. Requires the paged spill layout (the split rows ride
        the registry-free slot machinery). Returns the clamped salt
        count actually applied."""
        if not self._paged:
            raise ValueError(
                "hot-key splitting requires the paged spill layout "
                "(spill_layout='pages' with max_device_slots > 0)")
        salts = max(2, min(int(salts), MAX_SALTS))
        exact = all(
            l.reduce in ("min", "max") or np.dtype(l.dtype).kind in "iub"
            for l in self.agg.leaves)
        if not exact and not allow_inexact:
            raise ValueError(
                "splitting a float sum reassociates the fold; pass "
                "allow_inexact=True if the stream tolerates it (exact "
                "for integer-valued floats below the mantissa limit)")
        self._hot_keys[int(key_id)] = salts
        # the serving shadow must re-route the split key through the
        # live combined fold (one lookup answers main + salts)
        self._rep_rebuild = True
        return salts

    def hot_key_stats(self) -> Dict[str, object]:
        return {
            "keys": dict(self._hot_keys),
            "salted_records": int(self._hot_salted_records),
            "salted_fires": int(self._hot_salted_fires),
        }

    def _hot_key_array(self) -> np.ndarray:
        return np.fromiter(self._hot_keys, dtype=np.int64,
                           count=len(self._hot_keys))

    def _salt_hot_records(self, keys, ts, sess_key, sess_sid,
                          rec_to_sess, order, rec_slots, rec_shards):
        """Ingest diversion: re-point hot keys' records at their salted
        sub-rows. The salt is derived from the record TIMESTAMP
        (splitmix64 mod n_salts) so a replay salts identically — no
        RNG, no per-batch state."""
        hot = self._hot_key_array()
        hot_sess = np.isin(sess_key, hot) & (sess_sid >= 0)
        j = np.nonzero(hot_sess[rec_to_sess])[0]
        if not len(j):
            return rec_slots, rec_shards
        ridx = order[j]  # original record positions (session-sorted -> raw)
        rk = keys[ridx]
        rs = sess_sid[rec_to_sess[j]]
        nsalts = np.zeros(len(ridx), dtype=np.uint64)
        for hk, hv in self._hot_keys.items():
            nsalts[rk == hk] = np.uint64(hv)
        salt = (_splitmix64(ts[ridx].astype(np.uint64))
                % nsalts).astype(np.int64)
        skey = _salted_keys(rk, salt)
        sns = _salted_ns(rs, salt)
        # sids are globally unique, so the salted namespace alone
        # identifies the (session, salt) pair: resolve each unique
        # sub-row once, scatter the slot to every diverted record
        uns, inv = np.unique(sns, return_inverse=True)
        first_pos = np.zeros(len(uns), dtype=np.int64)
        first_pos[inv[::-1]] = np.arange(len(sns) - 1, -1, -1)
        ukey = skey[first_pos]
        shards_u = self._route(ukey)
        per = {}
        for p in np.unique(shards_u).tolist():
            selp = np.nonzero(shards_u == p)[0]
            per[p] = (ukey[selp], uns[selp])
        resolved = self._resolve_slots_paged(per)
        slots_u = np.zeros(len(uns), dtype=np.int32)
        for p in per:
            selp = np.nonzero(shards_u == p)[0]
            slots_u[selp] = resolved[p]
            self._dirty[p, resolved[p]] = True
        if not rec_slots.flags.writeable:
            rec_slots = rec_slots.copy()
        if not rec_shards.flags.writeable:
            rec_shards = rec_shards.copy()
        rec_slots[ridx] = slots_u[inv]
        rec_shards[ridx] = shards_u[inv]
        self._hot_salted_records += len(ridx)
        return rec_slots, rec_shards

    def _expand_hot_merges(self, gk, ds, ss):
        """Session merges of a split key carry their salted sub-rows
        along: (skey(k,t), ssid(src,t)) folds into (skey(k,t),
        ssid(dst,t)) — same salted key, hence the same shard, so the
        merge kernel's no-cross-shard invariant holds. Missing sub-rows
        resolve to identity (a no-op merge)."""
        sel = np.nonzero(np.isin(gk, self._hot_key_array()))[0]
        if not len(sel):
            return gk, ds, ss
        ek, ed, es = [gk], [ds], [ss]
        freed = []
        for i in sel.tolist():
            k = int(gk[i])
            n = self._hot_keys[k]
            salts = np.arange(n, dtype=np.int64)
            kk = np.full(n, k, dtype=np.int64)
            ek.append(_salted_keys(kk, salts))
            ed.append(_salted_ns(np.full(n, int(ds[i]),
                                         dtype=np.int64), salts))
            sns = _salted_ns(np.full(n, int(ss[i]),
                                     dtype=np.int64), salts)
            es.append(sns)
            freed.append(sns)
        # absorbed sub-rows die with their session: tombstones so delta
        # snapshots drop them (mirrors g.absorbed_sids for main rows)
        self._freed_ns.append(np.concatenate(freed))
        return (np.concatenate(ek), np.concatenate(ed),
                np.concatenate(es))

    def _fire_hot_fold(self, hk, hs) -> List[np.ndarray]:
        """RAW folded leaves for hot fired sessions. The device delta
        fire FINISHES on device (nonlinear), so a split session cannot
        fire there — its sub-rows must fold BEFORE the finish. Resident
        physical rows come back through one gather + one reset (slots
        return to identity before reuse); paged rows extract from the
        page tier (tombstoning them); absent rows are identity. The
        fold runs per leaf with the exchange's combine op in array
        order: main row first, then salts ascending."""
        from flink_tpu.ops.segment_ops import HOST_COMBINE
        from flink_tpu.state.paged_spill import (
            reload_rows_for,
            sorted_match,
        )

        leaves = self.agg.leaves
        leaf_dtypes = [l.dtype for l in leaves]
        nh = len(hk)
        pks, pns, gids = [], [], []
        for i in range(nh):
            k, s = int(hk[i]), int(hs[i])
            n = self._hot_keys[k]
            salts = np.arange(n, dtype=np.int64)
            pks.append(np.concatenate((
                np.asarray([k], dtype=np.int64),
                _salted_keys(np.full(n, k, dtype=np.int64), salts))))
            pns.append(np.concatenate((
                np.asarray([s], dtype=np.int64),
                _salted_ns(np.full(n, s, dtype=np.int64), salts))))
            gids.append(np.full(n + 1, i, dtype=np.int64))
        pk = np.concatenate(pks)
        pn = np.concatenate(pns)
        gid = np.concatenate(gids)
        vals = [np.full(len(pk), l.identity, dtype=l.dtype)
                for l in leaves]
        shards = self._route(pk)
        lanes: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        g_max = 0
        for p in range(self.P):
            selp = np.nonzero(shards == p)[0]
            if not len(selp):
                continue
            idx = self.indexes[p]
            ks, ns = pk[selp], pn[selp]
            slots = idx.lookup(ks, ns)
            hit = slots >= 0
            if hit.any():
                rslots = slots[hit].astype(np.int32)
                lanes[p] = (selp[hit], rslots)
                g_max = max(g_max, len(rslots))
                idx.free_slots(rslots, keys=ks[hit], nss=ns[hit])
                self._dirty[p, rslots] = False
            miss = ~hit
            if miss.any() and len(self._pmaps[p]):
                rl = reload_rows_for(self.spills[p], self._pmaps[p],
                                     ns[miss], leaf_dtypes)
                if rl is not None:
                    _, rns, _, rvals = rl
                    ro = np.argsort(rns)
                    found, pos = sorted_match(rns[ro], ns[miss])
                    src = ro[pos[found]]
                    dstp = selp[miss][found]
                    for i in range(len(leaves)):
                        vals[i][dstp] = rvals[i][src]
        if g_max:
            G = pad_bucket_size(g_max, minimum=64)
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, (_, rslots) in lanes.items():
                block[p, : len(rslots)] = rslots
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            g_host = self._harvest_get(list(gathered), "hot_fire")
            # freed slots must hold identity before reuse (padded
            # lanes target reserved slot 0: harmless)
            self.accs = self._reset_step(self.accs,
                                         self._put_sharded(block))
            for p, (selp_hit, rslots) in lanes.items():
                for i in range(len(leaves)):
                    vals[i][selp_hit] = g_host[i][p][: len(rslots)]
        # salted namespaces die with the fire: delta tombstones
        self._freed_ns.append(pn[pn < 0])
        out = [np.full(nh, l.identity, dtype=l.dtype) for l in leaves]
        for i, l in enumerate(leaves):
            # np.ufunc.at is unbuffered: repeated gids fold in ARRAY
            # order — main first, salts ascending (the documented order)
            HOST_COMBINE[l.reduce].at(out[i], gid, vals[i])
        return out

    def _expand_hot_query(self, keys_r, sids):
        """Physical-row expansion for point lookups: every logical row
        of a split key reads main + all salted sub-rows, folded back by
        ``gid`` (index into the logical rows)."""
        pk: List[int] = []
        pn: List[int] = []
        gid: List[int] = []
        for j in range(len(keys_r)):
            k, s = int(keys_r[j]), int(sids[j])
            pk.append(k)
            pn.append(s)
            gid.append(j)
            n = self._hot_keys.get(k)
            if n and s >= 0:
                salts = np.arange(n, dtype=np.int64)
                pk.extend(_salted_keys(
                    np.full(n, k, dtype=np.int64), salts).tolist())
                pn.extend(_salted_ns(
                    np.full(n, s, dtype=np.int64), salts).tolist())
                gid.extend([j] * n)
        return (np.asarray(pk, dtype=np.int64),
                np.asarray(pn, dtype=np.int64),
                np.asarray(gid, dtype=np.int64))

    def _rep_publish_split(self, p, keys, nss):
        """Serving-plane filter: salted sub-rows never publish (their
        partials are meaningless alone); a hot key's MAIN rows publish
        as COLD entries so replica lookups route through the live
        engine's combined fold — a split key still answers ONE lookup."""
        if not self._hot_keys:
            return None
        nss = np.asarray(nss, dtype=np.int64)
        drop = nss < 0
        coldm = np.isin(np.asarray(keys, dtype=np.int64),
                        self._hot_key_array()) & ~drop
        return drop, coldm

    # ------------------------------------------------------------------ fire

    #: fires may be dispatched async (on_watermark(async_ok=True)
    #: returns PendingFire handles) — the pipelined driver overlaps the
    #: device fire + D2H copy with the next batches' host bucketing and
    #: harvests coalesced, in dispatch order (no reordering)
    supports_async_fires = True

    def on_watermark(self, watermark: int,
                     async_ok: bool = False) -> List[RecordBatch]:
        self._wd_boundary()
        with flight.fire_span(watermark):
            out = self._on_watermark_inner(watermark, async_ok)
        # replica publish AFTER this boundary's fires/frees (outside
        # the fire span — serving-plane work, budgeted under its own
        # serving.replica_publish span)
        self._publish_replica(watermark)
        return out

    # -------------------------------------------------- replica hooks

    def _rep_extra(self, p: int, keys: np.ndarray, nss: np.ndarray):
        """The session END per published (key, sid) row — the result
        key of the serving composition ({session_end -> columns}).
        One interval-list scan per KEY (not per row): this runs on the
        task thread inside the boundary publish, where the fire-
        deadline budget lives."""
        out = np.zeros(len(keys), dtype=np.int64)
        sessions = self.meta.sessions
        by_key: Dict[int, List[int]] = {}
        for j in range(len(keys)):
            by_key.setdefault(int(keys[j]), []).append(j)
        for key, idxs in by_key.items():
            ivs = sessions.get(key, ())
            if not ivs:
                continue
            end_of = {int(iv[2]): int(iv[1]) for iv in ivs}
            for j in idxs:
                out[j] = end_of.get(int(nss[j]), 0)
        return out

    def _rep_probe_cold(self, p: int, keys: np.ndarray,
                        nss: np.ndarray) -> np.ndarray:
        """A session that left the resident set is COLD iff its sid is
        still mapped in the shard's page tier (paged layout) or its
        namespace is spilled (registry layout); otherwise it fired/
        merged away and the index entry drops."""
        if self._paged:
            return self._pmaps[p].spilled_mask(
                np.asarray(nss, dtype=np.int64))
        return super()._rep_probe_cold(p, keys, nss)

    def _on_watermark_inner(self, watermark: int,
                            async_ok: bool = False) -> List[RecordBatch]:
        pop = self.meta.pop_fired_ex(watermark)
        keys, starts, ends, sids = pop.keys, pop.starts, pop.ends, pop.sids
        hint = pop.slot_hint
        if not len(keys):
            return []
        if self._spill_active:
            # a catch-up fire can exceed the device budget; chunking keeps
            # each fire's working set under it — fired slots free
            # immediately, so chunks reuse the space. The hybrid (paged)
            # fire touches the device only for already-RESIDENT rows, so
            # its device working set is bounded by the table itself and
            # the chunk merely bounds host-side assembly — chunking
            # per half-budget there would re-read the same pages once
            # per chunk for nothing.
            chunk = max(self.max_device_slots // 2, 1024)
            if self._paged:
                chunk = max(chunk, 1 << 20)
            if len(keys) > chunk:
                out: List[RecordBatch] = []
                for a in range(0, len(keys), chunk):
                    out.extend(self._fire_sessions(
                        keys[a:a + chunk], starts[a:a + chunk],
                        ends[a:a + chunk], sids[a:a + chunk],
                        async_ok=async_ok,
                        slot_hint=(None if hint is None
                                   else hint[a:a + chunk])))
                return out
        return self._fire_sessions(keys, starts, ends, sids,
                                   async_ok=async_ok, slot_hint=hint)

    def _fire_sessions(self, keys, starts, ends, sids,
                       async_ok: bool = False,
                       slot_hint=None) -> List[RecordBatch]:
        chaos.fault_point("mesh.session_fire", sessions=len(keys))
        k_arr = np.asarray(keys, dtype=np.int64)
        sid_arr = np.asarray(sids, dtype=np.int64)
        shards = self._route(k_arr)
        per_shard_sel: List[np.ndarray] = [
            np.nonzero(shards == p)[0] for p in range(self.P)]
        if self._paged:
            return self._fire_sessions_hybrid(
                k_arr, np.asarray(starts, dtype=np.int64),
                np.asarray(ends, dtype=np.int64), sid_arr,
                per_shard_sel, async_ok, slot_hint)
        resolved: Dict[int, np.ndarray] = {}
        if self._spill_active:
            touched = {p: np.unique(sid_arr[sel])
                       for p, sel in enumerate(per_shard_sel)
                       if len(sel)}
            self._ensure_resident(touched)
            for p in touched:
                sel = per_shard_sel[p]
                self._reserve(p, k_arr[sel], sid_arr[sel])
        for p, sel in enumerate(per_shard_sel):
            if len(sel):
                resolved[p] = self.indexes[p].lookup_or_insert(
                    k_arr[sel], sid_arr[sel])
        w_max = 0
        per_shard_slots: List[np.ndarray] = []
        for p, sel in enumerate(per_shard_sel):
            if len(sel) == 0:
                per_shard_slots.append(np.empty(0, np.int32))
                continue
            per_shard_slots.append(resolved[p].astype(np.int32))
            w_max = max(w_max, len(sel))
        W = sticky_bucket(w_max, self._fire_bucket, minimum=64)
        self._fire_bucket = W
        sm = np.zeros((self.P, W, 1), dtype=np.int32)
        rb = np.zeros((self.P, W), dtype=np.int32)
        for p, slots in enumerate(per_shard_slots):
            sm[p, : len(slots), 0] = slots
            rb[p, : len(slots)] = slots
        # delta harvest: fire + reset of exactly the closing sessions'
        # slots in ONE fused program (build_delta_fire_step); the fire
        # outputs are fresh buffers, so a deferred (async) host read
        # never races the donated reset
        self.accs, fire_out = self._delta_fire_step(
            self.accs, self._put_sharded(sm), self._put_sharded(rb))
        # free the fired slots' index entries (host bookkeeping)
        self._freed_ns.append(sid_arr)
        for p, slots in enumerate(per_shard_slots):
            if len(slots):
                self._dirty[p, slots] = False
            if self._track_ns:
                self.indexes[p].free_namespaces(
                    [int(sid_arr[i]) for i in per_shard_sel[p]])
            elif len(slots):
                # registry-free: slot-addressed free (the fire resolved
                # the rows, so no registry walk is needed)
                if self._paged:
                    self._free_rows_paged(p, slots,
                                          sid_arr[per_shard_sel[p]])
                else:
                    self.indexes[p].free_slots(slots)
        # assemble the output batch in shard order
        st_arr = np.asarray(starts, dtype=np.int64)
        en_arr = np.asarray(ends, dtype=np.int64)
        out_idx = np.concatenate([s for s in per_shard_sel if len(s)])
        cols = {
            KEY_ID_FIELD: k_arr[out_idx],
            WINDOW_START_FIELD: st_arr[out_idx],
            WINDOW_END_FIELD: en_arr[out_idx],
            TIMESTAMP_FIELD: en_arr[out_idx] - 1,
        }
        per_shard_counts = [len(s) for s in per_shard_sel]
        names = sorted(fire_out.keys())

        def build(host: List[np.ndarray]) -> RecordBatch:
            full = dict(cols)
            for name, arr in zip(names, host):
                chunks = [arr[p][:m]
                          for p, m in enumerate(per_shard_counts) if m]
                full[name] = np.concatenate(chunks)
            return RecordBatch(full)

        if async_ok:
            from flink_tpu.runtime.pending import PendingFire

            return [PendingFire([fire_out[n] for n in names], build,
                                watchdog=self._watchdog)]
        # sync path still batches all columns into ONE device_get
        return [build(self._harvest_get(
            [fire_out[n] for n in names]))]

    def _fire_sessions_hybrid(self, k_arr, st_arr, en_arr, sid_arr,
                              per_shard_sel, async_ok: bool,
                              slot_hint=None) -> List[RecordBatch]:
        """Paged-layout fire: RESIDENT sessions merge+finish on device
        (one fire kernel over the whole mesh), COLD sessions fire
        straight from page storage — their accumulators are already on
        the host, and a fired session frees immediately, so reloading
        it into the device table (the old path) bought nothing and cost
        everything: at the thrashing benchmark shape ~90% of fires were
        cold, and every reload evicted resident rows that later fired
        cold themselves (reload->evict churn: rows_evicted tracked
        rows_reloaded 1:1). Extraction tombstones the page rows (see
        paged_spill.reload_rows_for) — no device traffic at all."""
        from flink_tpu.state.paged_spill import (
            reload_rows_for,
            sorted_match,
        )

        leaves = self.agg.leaves
        n = len(k_arr)
        self._freed_ns.append(sid_arr)
        leaf_dtypes = [l.dtype for l in leaves]
        # hot (split) sessions cannot finish on device — fold their
        # physical rows on the host first and route the folded values
        # through the cold host-finish below. The ORIGINAL per-shard
        # selection keeps the output ordering; the loop skips hot rows.
        per_shard_out = per_shard_sel
        hot_pos = None
        hot_vals = None
        if self._hot_keys:
            hmask = np.isin(k_arr, self._hot_key_array())
            if hmask.any():
                hot_pos = np.nonzero(hmask)[0]
                hot_vals = self._fire_hot_fold(k_arr[hot_pos],
                                               sid_arr[hot_pos])
                self._hot_salted_fires += len(hot_pos)
                per_shard_sel = [s[~hmask[s]] for s in per_shard_sel]
        res_pos: List[np.ndarray] = []   # positions fired on device
        res_slots: List[np.ndarray] = []
        cold_chunks: List[np.ndarray] = []  # positions fired from pages
        cold_vals: List[List[np.ndarray]] = [[] for _ in leaves]
        w_max = 0
        for p, sel in enumerate(per_shard_sel):
            if len(sel) == 0:
                res_pos.append(np.empty(0, dtype=np.int64))
                res_slots.append(np.empty(0, dtype=np.int32))
                continue
            # per-shard attribution: this shard's fire-path host work
            # (slot resolve + cold page extraction) lands on its own
            # Perfetto track — "shard 3 is slow" reads off the trace
            _t_shard = time.perf_counter()
            idx = self.indexes[p]
            ks, ss = k_arr[sel], sid_arr[sel]
            if slot_hint is not None:
                # the pop carried each fired session's FOLDED device
                # slot out of its metadata row — verified folds replace
                # the per-fire hash probe; only stale folds (evicted
                # since the fold) pay the read-only lookup
                slots = resolve_slot_hints(idx, ks, ss, slot_hint[sel])
            else:
                slots = idx.lookup(ks, ss)  # read-only: no insert/evict
            hit = slots >= 0
            rslots = slots[hit].astype(np.int32)
            res_pos.append(sel[hit])
            res_slots.append(rslots)
            w_max = max(w_max, len(rslots))
            cold = ~hit
            if cold.any():
                cpos = sel[cold]
                # identity where no state exists (matching the old
                # path's fire of a freshly-inserted identity row)
                vals_p = [np.full(len(cpos), l.identity, dtype=l.dtype)
                          for l in leaves]
                rl = reload_rows_for(self.spills[p], self._pmaps[p],
                                     ss[cold], leaf_dtypes) \
                    if len(self._pmaps[p]) else None
                if rl is not None:
                    _, rns, _, rvals = rl
                    # align extracted rows (unordered) to their fired
                    # positions; sids are unique, misses keep identity
                    order = np.argsort(rns)
                    found, pos = sorted_match(rns[order], ss[cold])
                    src = order[pos[found]]
                    for i in range(len(leaves)):
                        vals_p[i][found] = rvals[i][src]
                cold_chunks.append(cpos)
                for i in range(len(leaves)):
                    cold_vals[i].append(vals_p[i])
            # slot-addressed free of the resident fired rows (their
            # cold siblings were unmapped by the extraction above); the
            # pair columns are in hand from the pop, so the free skips
            # the per-slot metadata gathers
            if len(rslots):
                idx.free_slots(rslots, keys=ks[hit], nss=ss[hit])
                self._dirty[p, rslots] = False
            flight.instant("fire.shard", shard=p,
                           duration_s=time.perf_counter() - _t_shard)
        # device part: fire + reset over resident rows only, fused into
        # ONE delta-harvest program (the fire outputs are fresh buffers,
        # so async reads never race the donated reset)
        fire_out = None
        if w_max:
            W = sticky_bucket(w_max, self._fire_bucket, minimum=64)
            self._fire_bucket = W
            sm = np.zeros((self.P, W, 1), dtype=np.int32)
            rb = np.zeros((self.P, W), dtype=np.int32)
            for p, rslots in enumerate(res_slots):
                m = len(rslots)
                sm[p, :m, 0] = rslots
                rb[p, :m] = rslots
            self.accs, fire_out = self._delta_fire_step(
                self.accs, self._put_sharded(sm), self._put_sharded(rb))
        # host finish over the COLD positions only (the resident
        # majority's finish already ran inside the device fire kernel)
        names = sorted(self.agg.output_names)
        if hot_pos is not None:
            # folded hot sessions finish with the cold rows (identical
            # host finish; their values scatter back by position)
            cold_chunks.append(hot_pos)
            for i in range(len(leaves)):
                cold_vals[i].append(hot_vals[i])
        if cold_chunks:
            cold_pos = np.concatenate(cold_chunks)
            finished = self.agg.finish(tuple(
                np.concatenate(c) for c in cold_vals))
            cold_out = {name: np.asarray(col)
                        for name, col in finished.items()}
        else:
            cold_pos = None
            cold_out = {}
        out_idx = np.concatenate([s for s in per_shard_out if len(s)])
        cols = {
            KEY_ID_FIELD: k_arr[out_idx],
            WINDOW_START_FIELD: st_arr[out_idx],
            WINDOW_END_FIELD: en_arr[out_idx],
            TIMESTAMP_FIELD: en_arr[out_idx] - 1,
        }

        def build(host: List[np.ndarray]) -> RecordBatch:
            full = dict(cols)
            for i, name in enumerate(names):
                if cold_pos is not None:
                    vals = np.empty(n, dtype=cold_out[name].dtype)
                    vals[cold_pos] = cold_out[name]
                else:
                    vals = np.empty(n, dtype=host[i].dtype)
                if host:
                    arr = host[i]
                    for p, rpos in enumerate(res_pos):
                        m = len(rpos)
                        if m:
                            vals[rpos] = arr[p][:m]
                full[name] = vals[out_idx]
            return RecordBatch(full)

        arrays = [fire_out[nm] for nm in names] if fire_out else []
        if async_ok:
            from flink_tpu.runtime.pending import PendingFire

            return [PendingFire(arrays, build,
                                watchdog=self._watchdog)]
        # sync path still batches all columns into ONE device_get
        return [build(self._harvest_get(arrays))]

    # ---------------------------------------------------------- point query

    def query_sessions(self, key_id: int) -> Dict[int, Dict[str, float]]:
        """{session_end -> result columns} for a key's live sessions —
        a batch of one (all reads route through :meth:`query_batch`)."""
        return self.query_batch(
            np.asarray([key_id], dtype=np.int64))[0]

    def query_batch(self, key_ids) -> List[Dict[int, Dict[str, float]]]:
        """Batched point lookup: one ``{session_end -> result columns}``
        dict per requested key, request order. The keys' live sessions
        come from the global host metadata; ALL resident accumulators of
        the batch come back through ONE gather program + ONE batched
        device read (the serving-plane cost model — a per-key fire paid
        one dispatch + one D2H per request), cold sessions answer from
        their shards' page tiers. Read-only — no residency change."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        results: List[Dict[int, Dict[str, float]]] = [
            {} for _ in range(n)]
        if n == 0:
            return results
        rows: List[Tuple[int, int, int]] = []  # (request row, sid, end)
        for r in range(n):
            for iv in self.meta.sessions.get(int(key_ids[r]), ()):
                rows.append((r, int(iv[2]), int(iv[1])))
        if not rows:
            return results
        m = len(rows)
        rr = np.asarray([t[0] for t in rows], dtype=np.int64)
        sids = np.asarray([t[1] for t in rows], dtype=np.int64)
        keys_r = key_ids[rr]
        if self._hot_keys:
            # split keys read main + all salted sub-rows; gid folds the
            # physical rows back to their logical row below
            pk, pn, gid = self._expand_hot_query(keys_r, sids)
        else:
            pk, pn, gid = keys_r, sids, None
        mp = len(pk)
        shards = self._route(pk)
        leaves = self.agg.leaves
        leaf_rows = [np.full(mp, l.identity, dtype=l.dtype)
                     for l in leaves]
        have = np.zeros(mp, dtype=bool)
        lanes: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        g_max = 0
        cold: Dict[int, np.ndarray] = {}
        for p in range(self.P):
            sel = np.nonzero(shards == p)[0]
            if not len(sel):
                continue
            slots = self.indexes[p].lookup(pk[sel], pn[sel])
            hit = slots >= 0
            if hit.any():
                lanes[p] = (sel[hit], slots[hit].astype(np.int32))
                g_max = max(g_max, int(hit.sum()))
            if (~hit).any() and self._spill_active:
                cold[p] = sel[~hit]
        if g_max:
            G = pad_bucket_size(g_max, minimum=64)
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, (_, hs) in lanes.items():
                block[p, : len(hs)] = hs
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            # ONE batched D2H
            g_host = self._harvest_get(gathered, "serving_lookup")
            for p, (sel_hit, hs) in lanes.items():
                for i in range(len(leaves)):
                    leaf_rows[i][sel_hit] = g_host[i][p][: len(hs)]
                have[sel_hit] = True
        # cold sessions: read their rows out of the page tier (host-only,
        # one peek per touched page — see read_spilled_rows)
        from flink_tpu.state.paged_spill import read_spilled_rows

        def _take_row(j, entry, src):
            for i, l in enumerate(leaves):
                leaf_rows[i][j] = np.asarray(
                    entry[f"leaf_{i}"], dtype=l.dtype)[src]
            have[j] = True

        for p, sel_cold in cold.items():
            read_spilled_rows(
                self.spills[p],
                self._pmaps[p] if self._paged else None, self._paged,
                [(j, int(pk[j]), int(pn[j]))
                 for j in sel_cold.tolist()],
                _take_row)
        if gid is not None:
            from flink_tpu.ops.segment_ops import HOST_COMBINE

            # fold physical rows into their logical row — array order
            # is main first, salts ascending (the documented order);
            # not-found rows hold identity and fold as no-ops
            folded = [np.full(m, l.identity, dtype=l.dtype)
                      for l in leaves]
            for i, l in enumerate(leaves):
                HOST_COMBINE[l.reduce].at(folded[i], gid, leaf_rows[i])
            hv = np.zeros(m, dtype=bool)
            np.logical_or.at(hv, gid, have)
            leaf_rows, have = folded, hv
        # one host finish over every found row at once
        finished = self.agg.finish(tuple(leaf_rows))
        cols = {name: np.asarray(col) for name, col in finished.items()}
        for j, (r, _sid, end) in enumerate(rows):
            if have[j]:
                results[r][end] = {name: col[j].item()
                                   for name, col in cols.items()}
        return results

    # -------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        """Same logical format as SessionWindower.snapshot — restorable
        across engines and mesh sizes (re-sharded by key group)."""
        if mode == "delta":
            out = {"table": self._snapshot_delta(),
                   **self.meta.snapshot()}
            if self._hot_keys:
                out["hot_keys"] = {int(k): int(v)
                                   for k, v in self._hot_keys.items()}
            return out
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        parts = []
        for p in range(self.P):
            idx = self.indexes[p]
            used = idx.used_slots()
            key_ids = idx.slot_key[used]
            parts.append({
                "key_id": key_ids,
                "namespace": idx.slot_ns[used],
                "key_group": assign_key_groups(key_ids,
                                               self.max_parallelism),
                **{f"leaf_{i}": accs_host[i][p][used]
                   for i in range(len(self.accs))},
            })
        # spilled sessions are part of the logical state
        parts.extend(self._spill_snapshot_parts())
        merged = {
            k: np.concatenate([pt[k] for pt in parts]) for k in parts[0]
        } if parts else {}
        if mode != "savepoint":
            self._dirty[:] = False
            self._freed_ns.clear()
            for sp in self.spills:
                sp.clear_dirty()
        out = {"table": merged, **self.meta.snapshot()}
        if self._hot_keys:
            # the salted rows above are physical state; the registry
            # travels with them so a restore folds them correctly
            out["hot_keys"] = {int(k): int(v)
                               for k, v in self._hot_keys.items()}
        return out

    def _snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Dirty rows + freed-session tombstones (same format as
        SlotTable.snapshot_delta / MeshWindowEngine._snapshot_delta)."""
        per_shard = []
        g_max = 0
        for p in range(self.P):
            used = self.indexes[p].slot_used
            dirty = np.nonzero(self._dirty[p][:len(used)]
                               & used)[0].astype(np.int32)
            per_shard.append(dirty)
            g_max = max(g_max, len(dirty))
        freed = (np.unique(np.concatenate(self._freed_ns))
                 if self._freed_ns else np.empty(0, dtype=np.int64))
        if g_max == 0:
            out = {
                "__delta__": np.asarray(True),
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "key_group": np.empty(0, dtype=np.int32),
                "freed_namespaces": freed,
                **{f"leaf_{i}": np.empty(0, dtype=l.dtype)
                   for i, l in enumerate(self.agg.leaves)},
            }
        else:
            G = sticky_bucket(g_max, self._gather_bucket)
            self._gather_bucket = G
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, dirty in enumerate(per_shard):
                block[p, :len(dirty)] = dirty
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            leaves_host = jax.device_get(list(gathered))  # ONE batched D2H
            key_cols, ns_cols = [], []
            leaf_cols = [[] for _ in leaves_host]
            for p, dirty in enumerate(per_shard):
                mm = len(dirty)
                if mm == 0:
                    continue
                idx = self.indexes[p]
                key_cols.append(idx.slot_key[dirty])
                ns_cols.append(idx.slot_ns[dirty])
                for i, lh in enumerate(leaves_host):
                    leaf_cols[i].append(lh[p][:mm])
            key_ids = np.concatenate(key_cols)
            out = {
                "__delta__": np.asarray(True),
                "key_id": key_ids,
                "namespace": np.concatenate(ns_cols),
                "key_group": assign_key_groups(key_ids,
                                               self.max_parallelism),
                "freed_namespaces": freed,
                **{f"leaf_{i}": np.concatenate(cols)
                   for i, cols in enumerate(leaf_cols)},
            }
        self._spill_delta_append(out)
        self._dirty[:] = False
        self._freed_ns.clear()
        return out

    def restore(self, snap: Dict[str, object],
                key_group_filter=None) -> None:
        """Restore, re-sharding by key group — accepts single-device
        SessionWindower snapshots and mesh snapshots of any mesh size."""
        table = snap.get("table", {})
        hk = snap.get("hot_keys")
        if hk:
            # the snapshot carries salted physical rows — the registry
            # must be live BEFORE any fire/query folds them
            for k, v in hk.items():
                self._hot_keys[int(k)] = int(v)
        key_ids = np.asarray(table.get("key_id", []), dtype=np.int64)
        namespaces = np.asarray(table.get("namespace", []), dtype=np.int64)
        if len(key_ids):
            if key_group_filter is not None:
                groups = assign_key_groups(key_ids, self.max_parallelism)
                keep = np.isin(groups, np.asarray(sorted(key_group_filter)))
                key_ids, namespaces = key_ids[keep], namespaces[keep]
                leaves = [np.asarray(table[f"leaf_{i}"])[keep]
                          for i in range(len(self.agg.leaves))]
            else:
                leaves = [np.asarray(table[f"leaf_{i}"])
                          for i in range(len(self.agg.leaves))]
        if self._spill_active and len(key_ids):
            if self._paged:
                self._paged_restore_rows(key_ids, namespaces, leaves)
            else:
                self._spill_restore_rows(key_ids, namespaces, leaves)
        elif len(key_ids):
            shards = self._route(key_ids)
            # inserts first — growth must settle before the host copy
            # (same contract as MeshWindowEngine.restore)
            per_shard_slots: Dict[int, np.ndarray] = {}
            for p in range(self.P):
                mask = shards == p
                if mask.any():
                    per_shard_slots[p] = self.indexes[p].lookup_or_insert(
                        key_ids[mask], namespaces[mask])
            # one batched D2H read, then writable copies (restore
            # mutates them in place before re-uploading)
            accs_host = [np.array(a)
                         for a in jax.device_get(list(self.accs))]
            for p, slots in per_shard_slots.items():
                mask = shards == p
                for acc, vals in zip(accs_host, leaves):
                    acc[p][slots] = vals[mask]
            self.accs = tuple(
                jax.device_put(jnp.asarray(a), self._sharding)
                for a in accs_host)
        self._dirty[:] = False
        self._freed_ns.clear()
        for sp in self.spills:
            sp.clear_dirty()
        # restored values bypass the scatter sites — the replica shadow
        # is stale wholesale; republish at the next boundary
        self._rep_rebuild = True
        self.meta.restore(snap, key_group_filter=key_group_filter,
                          max_parallelism=self.max_parallelism)

    # ------------------------------------------------ partial-failover hooks

    def _drop_meta_key_groups(self, groups) -> None:
        # a lost shard's session intervals die with its state rows —
        # the checkpoint unit (restore_key_groups) brings both back
        self.meta.drop_key_groups(groups, self.max_parallelism)

    def _merge_restored_meta(self, snap, groups) -> None:
        self.meta.merge_restore(snap, groups, self.max_parallelism)

    def _filter_meta_snapshot(self, snap, groups):
        from flink_tpu.windowing.session_meta import SessionIntervalSet

        out = SessionIntervalSet.filter_snapshot(
            snap, groups, self.max_parallelism)
        hk = snap.get("hot_keys")
        if hk:
            # every unit carries the full split registry (tiny) — any
            # subset of units restores with the folds intact
            out["hot_keys"] = dict(hk)
        return out

    def _merge_meta_snapshots(self, units):
        _NEG = -(1 << 62)
        sessions: Dict[int, list] = {}
        hot: Dict[int, int] = {}
        for u in units:
            for k, ivs in u.get("sessions", {}).items():
                sessions[int(k)] = list(ivs)  # ranges are disjoint
            for k, v in (u.get("hot_keys") or {}).items():
                hot[int(k)] = max(hot.get(int(k), 0), int(v))
        out = {
            "sessions": sessions,
            "next_sid": max((int(u.get("next_sid", 1)) for u in units),
                            default=1),
            # the OLDEST unit's staleness horizon: its range's records
            # replay from its position and must not be judged stale
            "max_fired_watermark": min(
                (u.get("max_fired_watermark", _NEG) for u in units),
                default=_NEG),
        }
        if hot:
            out["hot_keys"] = hot
        return out

    # -------------------------------------------- native-plane degradation

    def _meta_fallback(self, err) -> None:
        """Swap the native metadata plane for the bit-identical Python
        plane after a runtime sweep failure — once, loudly (warning +
        ``flink_tpu.native.native_fallbacks()``), preserving the live
        interval state via the plane-independent snapshot format."""
        from flink_tpu.native import note_fallback
        from flink_tpu.windowing.session_meta import SessionIntervalSet

        note_fallback(
            f"native session sweep failed at runtime "
            f"({type(err).__name__}: {err}) — engine degraded to the "
            "Python metadata plane")
        py = SessionIntervalSet(self.gap, self.allowed_lateness)
        py.restore(self.meta.snapshot())
        py.late_records_dropped = self.meta.late_records_dropped
        py.native_sweep_s = self.meta.native_sweep_s
        self.meta = py
