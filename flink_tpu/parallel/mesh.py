"""Device mesh construction for keyed-state sharding.

The key-group axis (SURVEY.md §2.9 "keyed parallelism") is THE parallel axis
of a streaming dataflow: state, timers and window merges are all partitioned
by key group (reference: KeyGroupRangeAssignment.java). On TPU this axis maps
onto a 1-D ``jax.sharding.Mesh``; cross-shard exchange ("the shuffle",
reference: flink-runtime/.../io/network/) becomes host-side bucketing into a
[shards, ...] leading axis + ``shard_map`` collectives over ICI.

Pod scale (ROADMAP item 2): the same key-group axis can SPAN PROCESSES —
``make_mesh(span="process")`` builds the mesh over ``jax.devices()``
(global, process-major order), and a :class:`HostTopology` records the
``(hosts, local)`` factorization the two-level ICI/DCN exchange
(``parallel/exchange2.py``) programs against. On CPU the same shape runs
as N processes x M virtual devices (``jax.distributed.initialize`` + the
gloo cross-process collectives — :func:`initialize_distributed`), which
is how the multi-process smoke and chaos scenarios exercise the pod data
plane without a pod.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KEY_AXIS = "keygroups"
#: axis names of the 2-D (hosts, local) view the two-level exchange uses —
#: flattened host-major, the 2-D view IS the key-group axis (sharding
#: equivalence holds because the device order is identical)
HOST_AXIS = "hosts"
LOCAL_AXIS = "local"

try:  # jax >= 0.5 exposes shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map  # noqa: F401


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """The ``(hosts, local)`` factorization of the key-group axis.

    ``num_hosts`` is the DCN dimension (one entry per process / TPU
    host), ``local_devices`` the ICI dimension (devices per host). The
    flat shard index is host-major: shard ``p`` lives on host
    ``p // local_devices`` at local index ``p % local_devices`` — the
    same order ``jax.devices()`` enumerates a multi-process mesh, so
    the 2-D ``(hosts, local)`` mesh view and the flat key-group mesh
    address the same device the same way. A single-process test mesh
    can declare a VIRTUAL topology (e.g. 2x4 over 8 virtual CPU
    devices); the exchange programs only see the factorization.
    """

    num_hosts: int
    local_devices: int

    def __post_init__(self):
        if self.num_hosts < 1 or self.local_devices < 1:
            raise ValueError(
                f"topology must be positive, got "
                f"{self.num_hosts}x{self.local_devices}")

    @property
    def num_shards(self) -> int:
        return self.num_hosts * self.local_devices

    def host_of_shard(self, shard: int) -> int:
        return int(shard) // self.local_devices

    def shards_of_host(self, host: int) -> range:
        h = int(host)
        if not (0 <= h < self.num_hosts):
            raise ValueError(
                f"no host {h} in a {self.num_hosts}-host topology")
        return range(h * self.local_devices,
                     (h + 1) * self.local_devices)

    def check_covers(self, num_shards: int) -> None:
        """Raise unless this factorization describes exactly
        ``num_shards`` shards (the one validation every consumer —
        engines, watchdog, pod plane — applies)."""
        if self.num_shards != int(num_shards):
            raise ValueError(
                f"host topology {self.num_hosts}x"
                f"{self.local_devices} does not cover a "
                f"{int(num_shards)}-shard mesh")


def make_mesh(num_devices: Optional[int] = None, devices=None,
              span: str = "local") -> Mesh:
    """A 1-D mesh over the key-group axis.

    ``span="local"`` (the default) builds over this process's view —
    identical to the historical behavior on a single process.
    ``span="process"`` builds over ALL processes' devices
    (``jax.devices()`` is global once ``jax.distributed.initialize``
    ran), process-major — the pod mesh the two-level exchange spans.
    """
    if span not in ("local", "process"):
        raise ValueError(
            f"span must be 'local' or 'process', got {span!r}")
    if devices is None:
        if span == "process":
            devices = _global_devices_process_major()
        else:
            devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested a {num_devices}-device mesh but only "
                    f"{len(devices)} device(s) are available "
                    f"(span={span!r}) — a silently smaller mesh would "
                    "re-route key groups; shrink the request or add "
                    "devices")
            devices = devices[:num_devices]
    return Mesh(np.array(devices), (KEY_AXIS,))


def _global_devices_process_major() -> List:
    """``jax.devices()`` ordered (process, local) — the host-major flat
    order :class:`HostTopology` assumes. jax already enumerates by
    process; the explicit sort pins the contract."""
    return sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))


def process_topology() -> HostTopology:
    """The REAL process topology: one "host" per jax process, uniform
    local device count (jax requires it for collectives)."""
    return HostTopology(jax.process_count(), jax.local_device_count())


def pod_mesh_view(mesh: Mesh, topology: HostTopology) -> Mesh:
    """The 2-D ``(hosts, local)`` view of a flat key-group mesh: SAME
    devices, same order, reshaped — ``NamedSharding(view, P((HOST_AXIS,
    LOCAL_AXIS)))`` is equivalent to the flat ``P(KEY_AXIS)`` sharding,
    so arrays flow between flat and two-level programs without a copy."""
    devs = list(mesh.devices.flat)
    if topology.num_shards != len(devs):
        raise ValueError(
            f"topology {topology.num_hosts}x{topology.local_devices} "
            f"does not cover a {len(devs)}-device mesh")
    return Mesh(
        np.array(devs).reshape(topology.num_hosts,
                               topology.local_devices),
        (HOST_AXIS, LOCAL_AXIS))


def initialize_distributed(coordinator_address: str,
                           num_processes: int,
                           process_id: int) -> None:
    """Bring up the multi-process runtime for a CPU pod-shape run:
    enables the gloo cross-process CPU collectives (without which the
    CPU backend raises "Multiprocess computations aren't implemented")
    and calls ``jax.distributed.initialize``. Must run before the first
    backend touch; real TPU pods skip the gloo step (ICI/DCN collectives
    are native) but the call is harmless there."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jaxlib without gloo: initialize may
        pass           # still serve collective-free runs
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading axis across the key-group axis."""
    return NamedSharding(mesh, P(KEY_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
