"""Device mesh construction for keyed-state sharding.

The key-group axis (SURVEY.md §2.9 "keyed parallelism") is THE parallel axis
of a streaming dataflow: state, timers and window merges are all partitioned
by key group (reference: KeyGroupRangeAssignment.java). On TPU this axis maps
onto a 1-D ``jax.sharding.Mesh``; cross-shard exchange ("the shuffle",
reference: flink-runtime/.../io/network/) becomes host-side bucketing into a
[shards, ...] leading axis + ``shard_map`` collectives over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KEY_AXIS = "keygroups"

try:  # jax >= 0.5 exposes shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh over the key-group axis."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.array(devices), (KEY_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading axis across the key-group axis."""
    return NamedSharding(mesh, P(KEY_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
