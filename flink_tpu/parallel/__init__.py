from flink_tpu.parallel.mesh import make_mesh, KEY_AXIS

__all__ = ["make_mesh", "KEY_AXIS"]
