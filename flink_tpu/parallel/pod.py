"""The pod runtime: multi-process record routing over the DCN axis.

In pod mode every PROCESS owns a contiguous slice of the key-group
space (``host_key_group_ranges`` — the stable process -> range mapping)
and runs its own engine over its LOCAL devices; that engine's fused
device exchange IS the intra-host ICI stage. What a single process
cannot do is deliver a record whose key belongs to ANOTHER process:
that hop is this module — :class:`PodDataPlane` stages each process's
sub-batch onto its local devices, ``all_to_all``s the per-host buckets
over the ``hosts`` axis of the process-spanning mesh (the DCN stage —
the bytes move device-to-device, replacing the reference's Netty
shuffle for the inter-TaskManager hop), and hands each process exactly
the records its range owns, in GLOBAL STREAM ORDER (arrivals flatten
by (source host, source chunk, rank); chunks partition the stream
host-major) — so per-key processing order, and with it every float
fold downstream, matches a single-process run bit-for-bit.

Host-granular planes fall out of the process split: each process keeps
its own session-metadata plane, spill tier and per-range checkpoint
units (its engine's ``snapshot_sharded`` — PR 9's shard units), so a
lost process is "restore k units, replay one contiguous range"
(``tools/multiproc_smoke.py`` drives exactly that scenario).

CPU bring-up: ``mesh.initialize_distributed`` enables the gloo
cross-process collectives; N processes x M virtual devices then run
the same program a v5e pod would. The plane also runs degenerate in
ONE process over a virtual topology (every "host" addressable), which
is how tier-1 tests cover the routing program.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from flink_tpu.ops.segment_ops import pad_bucket_size, sticky_bucket
from flink_tpu.parallel.mesh import (
    HOST_AXIS,
    KEY_AXIS,
    HostTopology,
    make_mesh,
    pod_mesh_view,
    shard_map,
)
from flink_tpu.state.keygroups import host_key_group_ranges
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE


def build_pod_route(mesh, topology: HostTopology,
                    dtypes: Tuple[str, ...]):
    """The DCN routing program: each shard buckets its flat record
    chunk by destination HOST (one-hot-cumsum ranks — stream order per
    destination) and ``all_to_all``s the ``[H, W]`` buckets over the
    hosts axis. Returns the received buckets flattened ``[H * W]`` per
    shard, destination column first (its received values mark lane
    validity: a real lane carries the receiving host's own id, padding
    carries ``H``). Cached in the shared PROGRAM_CACHE."""
    key = (tuple(d.id for d in mesh.devices.flat), topology.num_hosts,
           topology.local_devices, tuple(dtypes))
    return PROGRAM_CACHE.get_or_build(
        "pod-route", key, lambda: _build_pod_route(mesh, topology,
                                                   dtypes))


def _build_pod_route(mesh, topology: HostTopology, dtypes):
    from functools import partial

    import jax.numpy as jnp

    H = topology.num_hosts
    mesh2 = pod_mesh_view(mesh, topology)

    def _xc(block):
        if H == 1:
            return block
        return jax.lax.all_to_all(block, HOST_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(2,))
    def route(dst, cols, w):
        W = int(w)

        def local(*args):
            d = args[0]          # [C] destination HOST (H = padding)
            vals = args[1:]
            oh = jax.nn.one_hot(d, H, dtype=jnp.int32)
            rank = jnp.cumsum(oh, axis=0) - oh
            rank_d = jnp.take_along_axis(
                rank, jnp.clip(d, 0, H - 1)[:, None], axis=1)[:, 0]
            ok = (d < H) & (rank_d < W)
            flat = jnp.where(ok, d * W + rank_d, H * W)
            outs = [_xc(
                jnp.full((H * W,), H, dtype=jnp.int32)
                .at[flat].set(d, mode="drop")
                .reshape(H, W)).reshape(-1)]
            for v, dt in zip(vals, dtypes):
                trail = v.shape[1:]  # 64-bit columns ride as [C, 2]
                outs.append(_xc(
                    jnp.zeros((H * W,) + trail, dtype=dt)
                    .at[flat].set(v, mode="drop")
                    .reshape((H, W) + trail))
                    .reshape((H * W,) + trail))
            return tuple(outs)

        from flink_tpu.parallel.mesh import LOCAL_AXIS

        spec = P((HOST_AXIS, LOCAL_AXIS))
        return shard_map(
            local, mesh=mesh2,
            in_specs=(spec,) * (1 + len(cols)),
            out_specs=(spec,) * (1 + len(cols)),
        )(dst, *cols)

    return route


def _build_agree(mesh):
    from functools import partial

    import jax.numpy as jnp

    rep = NamedSharding(mesh, P())

    @partial(jax.jit, out_shardings=rep)
    def agree(x):  # [P, 2] int32 sharded -> [2] replicated
        return jnp.max(x, axis=0)

    return agree


class PodDataPlane:
    """Routes raw record columns to their owning process over the DCN
    axis of a process-spanning mesh.

    ``dtypes``: the record columns every exchange call carries (e.g.
    key ids, timestamps, values). 64-bit columns (int64 key
    identities, timestamps) ride the x32 device plane as int32 LANE
    PAIRS (``[n, 2]`` views) and reassemble bit-exactly on harvest —
    the same reason the join side tables shadow int64 host-side; here
    the values only transit, so the pair split is enough.
    """

    def __init__(self, topology: HostTopology,
                 dtypes: Sequence, mesh=None,
                 max_parallelism: int = 128,
                 min_bucket: int = 256) -> None:
        self.topology = topology
        self.mesh = mesh if mesh is not None else make_mesh(
            span="process")
        if topology.num_shards != int(self.mesh.devices.size):
            raise ValueError(
                f"topology {topology.num_hosts}x"
                f"{topology.local_devices} does not cover the "
                f"{int(self.mesh.devices.size)}-device mesh")
        self.dtypes = tuple(np.dtype(d) for d in dtypes)
        #: device-side carrier dtype per column: 64-bit columns travel
        #: as int32 lane pairs (x32 plane), everything else unchanged
        self._wire = tuple(
            (np.dtype(np.int32) if d.itemsize == 8 else d)
            for d in self.dtypes)
        self._pair = tuple(d.itemsize == 8 for d in self.dtypes)
        self.max_parallelism = int(max_parallelism)
        self.min_bucket = int(min_bucket)
        self._sharding = NamedSharding(self.mesh, P(KEY_AXIS))
        self._route = build_pod_route(
            self.mesh, topology,
            tuple(d.str for d in self._wire))
        self._chunk_bucket = 0
        self._w_bucket = 0
        self.host_ranges = host_key_group_ranges(
            topology.num_hosts, topology.local_devices,
            self.max_parallelism)
        self.my_host = (jax.process_index()
                        if jax.process_count() > 1 else 0)
        self._agree_fn = PROGRAM_CACHE.get_or_build(
            "pod-agree",
            (tuple(d.id for d in self.mesh.devices.flat),),
            lambda: _build_agree(self.mesh))
        #: rows that genuinely crossed a process boundary vs stayed
        #: home (the smoke's vacuity guard)
        self.rows_cross_host = 0
        self.rows_intra_host = 0
        self.batches = 0

    # ------------------------------------------------------------ sizing

    def _agree(self, chunk_max: int, pair_max: int) -> Tuple[int, int]:
        """All processes must dispatch the SAME program shape (SPMD):
        agree on the global chunk length and bucket width. One tiny
        CACHED fixed-shape max-reduction per batch in multi-process
        mode (a fresh jit per call would trip the recompile sentinel);
        a no-op on one process."""
        if jax.process_count() > 1:
            L = self.topology.local_devices
            local = np.tile(
                np.array([[chunk_max, pair_max]], dtype=np.int32),
                (L, 1))
            arr = jax.make_array_from_process_local_data(
                self._sharding, local,
                (self.topology.num_shards, 2))
            both = np.asarray(jax.device_get(self._agree_fn(arr)))
            chunk_max = int(both[0])
            pair_max = int(both[1])
        C = sticky_bucket(chunk_max, self._chunk_bucket,
                          self.min_bucket)
        self._chunk_bucket = C
        W = sticky_bucket(min(pair_max, C), self._w_bucket,
                          self.min_bucket)
        self._w_bucket = min(W, C)
        return C, self._w_bucket

    # ---------------------------------------------------------- exchange

    def exchange(self, dst_host: np.ndarray,
                 columns: Sequence[np.ndarray],
                 chunk_bound: Optional[int] = None
                 ) -> Dict[int, List[np.ndarray]]:
        """Route this process's sub-batch: every record lands on its
        owning host, arrivals in GLOBAL stream order.

        Multi-process: each process passes ITS sub-batch (the global
        batch is the process-major concatenation) and receives
        ``{my_host: [col, ...]}``. Single-process (virtual topology):
        pass the WHOLE batch; every host's arrivals come back
        ``{host: [col, ...]}`` — the tier-1 test mode.

        ``chunk_bound``: a DETERMINISTIC upper bound on every process's
        per-chunk record count (e.g. ``ceil(max sub-batch / L)`` when
        the caller knows the global batch split). With it, no
        agreement collective runs — the bucket width is the chunk tier
        (a bounded overshoot); without it, one tiny cached max-
        reduction per batch agrees on exact shapes.
        """
        H = self.topology.num_hosts
        L = self.topology.local_devices
        dst_host = np.asarray(dst_host)
        n = len(dst_host)
        columns = [
            (np.ascontiguousarray(np.asarray(c, dtype=d))
             .view(np.int32).reshape(n, 2) if pair
             else np.asarray(c, dtype=d))
            for c, d, pair in zip(columns, self.dtypes, self._pair)]
        multi = jax.process_count() > 1
        local_chunks = L if multi else H * L
        per = -(-max(n, 1) // local_chunks)
        if chunk_bound is not None:
            # deterministic sizing: no collective, W = the chunk tier
            per = max(per, int(chunk_bound))
            C = sticky_bucket(per, self._chunk_bucket,
                              self.min_bucket)
            self._chunk_bucket = C
            self._w_bucket = W = C
        else:
            if n:
                chunk_of = np.minimum(
                    np.arange(n, dtype=np.int64) // per,
                    local_chunks - 1)
                pair_max = int(np.bincount(
                    chunk_of * (H + 1) + np.minimum(dst_host, H),
                    minlength=local_chunks * (H + 1))
                    .reshape(local_chunks, H + 1)[:, :H].max())
            else:
                pair_max = 0
            C, W = self._agree(per, pair_max)
        N_local = local_chunks * C
        dst_buf = np.full(N_local, H, dtype=np.int32)
        bufs = [np.zeros((N_local, 2) if pair else (N_local,),
                         dtype=w)
                for w, pair in zip(self._wire, self._pair)]
        if n:
            # re-chunk against the AGREED C: chunk j covers sub-batch
            # positions [j*C, (j+1)*C) — the contiguous split the
            # stream-order reconstruction assumes
            if per > C:
                raise AssertionError("agreed chunk below local need")
            for j in range(local_chunks):
                a, b = j * per, min((j + 1) * per, n)
                if a >= b:
                    break
                dst_buf[j * C:j * C + (b - a)] = dst_host[a:b]
                for buf, col in zip(bufs, columns):
                    buf[j * C:j * C + (b - a)] = col[a:b]
        src_host_of_chunk = (
            np.arange(local_chunks) // L if not multi
            else np.full(local_chunks, self.my_host))
        if n:
            cross = int((dst_host
                         != (src_host_of_chunk[np.minimum(
                             np.arange(n) // per,
                             local_chunks - 1)])).sum())
            self.rows_cross_host += cross
            self.rows_intra_host += n - cross
        self.batches += 1
        G = H * L * C
        if multi:
            arrs = [jax.make_array_from_process_local_data(
                self._sharding, b, (G,) + b.shape[1:])
                for b in [dst_buf] + bufs]
        else:
            arrs = [jax.device_put(b, self._sharding)
                    for b in [dst_buf] + bufs]
        out = self._route(arrs[0], tuple(arrs[1:]), W)
        # harvest THIS process's shards: ONE batched device_get of all
        # addressable pieces (the TRC01 discipline)
        shard_data: Dict[int, list] = {}
        for ci, o in enumerate(out):
            for s in o.addressable_shards:
                p = s.index[0].start // (H * W)
                shard_data.setdefault(p, [None] * len(out))[ci] = s.data
        flat_order = sorted(shard_data)
        fetched = jax.device_get(
            [shard_data[p] for p in flat_order])
        # reassemble in (source host, source chunk, rank) order =
        # global stream order restricted to each receiving host
        arrivals: Dict[int, List[np.ndarray]] = {}
        by_shard = dict(zip(flat_order, fetched))
        hosts = ({self.my_host} if multi
                 else set(range(H)))
        for h in sorted(hosts):
            parts: List[List[np.ndarray]] = [[] for _ in self.dtypes]
            for sh in range(H):
                for sl in range(L):
                    p = h * L + sl
                    cols_p = by_shard.get(p)
                    if cols_p is None:
                        continue
                    dcol = np.asarray(cols_p[0]).reshape(H, W)[sh]
                    valid = dcol < H
                    if not valid.any():
                        continue
                    m = int(valid.sum())  # ranks are contiguous
                    for ci, pair in enumerate(self._pair):
                        c = np.asarray(cols_p[ci + 1])
                        c = c.reshape((H, W) + c.shape[1:])[sh][:m]
                        parts[ci].append(c)
            cols_out: List[np.ndarray] = []
            for ps, d, pair in zip(parts, self.dtypes, self._pair):
                if not ps:
                    cols_out.append(np.empty(0, dtype=d))
                    continue
                c = np.ascontiguousarray(np.concatenate(ps))
                if pair:  # [m, 2] int32 lanes -> the 64-bit column
                    c = c.view(d).ravel()
                cols_out.append(c)
            arrivals[h] = cols_out
        return arrivals
