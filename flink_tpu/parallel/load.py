"""Per-shard / per-key-group load accounting — the *detect* stage of the
skew ladder (detect -> rebalance -> split).

The mesh already *records* the imbalance (``fire.shard`` flight spans,
``state`` resident-row gauges) but nothing turns those observations into
a per-key-group load estimate a rebalancer can act on. This module does
that differentiation:

- :meth:`ShardLoadAccountant.note_batch` folds routed key columns into
  per-group record counts and a Misra-Gries heavy-hitter sketch (the
  hot-KEY candidates the split stage needs);
- :meth:`ShardLoadAccountant.tick` differentiates the accumulated
  counts — plus externally-sampled per-shard busy seconds and resident
  rows — into EWMA-smoothed rates with an injectable clock (policy
  tests never sleep);
- :meth:`ShardLoadAccountant.shard_load` / :meth:`imbalance` project
  group loads through a :class:`~flink_tpu.state.KeyGroupAssignment`,
  so a proposed move can be scored *before* it happens.

Surfaced as the ``skew`` metric group (:meth:`register_metrics`).

Flight spans are expensive to decode (``snapshot()`` walks the whole
ring in Python), so the accountant never touches the recorder itself —
:func:`busy_from_flight` is the optional, explicitly-invoked bridge.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.state.keygroups import (
    KeyGroupAssignment,
    assign_key_groups,
)

__all__ = ["ShardLoadAccountant", "busy_from_flight"]


def busy_from_flight(recorder, num_shards: int,
                     kinds: Sequence[str] = ("fire.shard",)) -> np.ndarray:
    """Total busy seconds per shard from a flight recorder's ring.

    O(ring capacity) Python decode — sample this coarsely (once per
    policy tick at most), never per batch."""
    busy = np.zeros(int(num_shards), dtype=np.float64)
    want = frozenset(kinds)
    for rec in recorder.snapshot():
        if rec.kind in want and 0 <= int(rec.shard) < len(busy):
            busy[int(rec.shard)] += max(0.0, float(rec.duration_s))
    return busy


class ShardLoadAccountant:
    """EWMA per-key-group load estimates from routed batches + sampled
    shard gauges. All state is host-side numpy; nothing here touches a
    device."""

    def __init__(self, num_shards: int, max_parallelism: int,
                 key_group_range=None, ewma_alpha: float = 0.3,
                 top_k: int = 16,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if not (0.0 < float(ewma_alpha) <= 1.0):
            raise ValueError(f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.num_shards = int(num_shards)
        self.max_parallelism = int(max_parallelism)
        if key_group_range is None:
            self.first, self.span = 0, self.max_parallelism
        else:
            self.first = int(key_group_range[0])
            self.span = int(key_group_range[1]) - self.first + 1
        self.alpha = float(ewma_alpha)
        self.top_k = int(top_k)
        self.clock = clock if clock is not None else time.monotonic
        # accumulated since last tick
        self._group_counts = np.zeros(self.span, dtype=np.int64)
        self._records_pending = 0
        # EWMA state (None until the first differentiating tick)
        self._group_rate: Optional[np.ndarray] = None
        self._shard_busy_frac: Optional[np.ndarray] = None
        self._shard_resident: Optional[np.ndarray] = None
        self._last_tick: Optional[float] = None
        self.ticks = 0
        self.records_seen = 0
        # Misra-Gries heavy-hitter sketch over key ids
        self._mg: Dict[int, int] = {}

    # ------------------------------------------------------------ ingest

    def note_batch(self, key_ids: np.ndarray) -> None:
        """Fold one routed batch's key column into the running counts."""
        k = np.asarray(key_ids, dtype=np.int64)
        if len(k) == 0:
            return
        groups = assign_key_groups(k, self.max_parallelism)
        local = np.asarray(groups, dtype=np.int64) - self.first
        self._group_counts += np.bincount(local, minlength=self.span)
        self._records_pending += len(k)
        self.records_seen += len(k)
        # Misra-Gries: decrement-all on overflow keeps any key with
        # frequency > N/(top_k+1) in the sketch — enough for "one key
        # dominates its group" detection.
        uk, uc = np.unique(k, return_counts=True)
        mg = self._mg
        for key, cnt in zip(uk.tolist(), uc.tolist()):
            if key in mg:
                mg[key] += cnt
            elif len(mg) < self.top_k:
                mg[key] = cnt
            else:
                dec = min(cnt, min(mg.values()))
                for other in list(mg):
                    mg[other] -= dec
                    if mg[other] <= 0:
                        del mg[other]
                if cnt > dec:
                    mg[key] = cnt - dec

    # ------------------------------------------------------------ ticks

    def tick(self, shard_resident_rows: Sequence[float] = (),
             shard_busy_s: Sequence[float] = ()) -> None:
        """Differentiate accumulated counts into EWMA rates.

        ``shard_resident_rows``: the ``state`` gauge sample (rows per
        shard). ``shard_busy_s``: cumulative-or-sampled busy seconds per
        shard (e.g. from :func:`busy_from_flight`); normalized by the
        tick interval into a busy fraction."""
        now = float(self.clock())
        dt = None if self._last_tick is None else max(1e-9, now - self._last_tick)
        self._last_tick = now
        self.ticks += 1
        if dt is not None:
            rate = self._group_counts / dt
            if self._group_rate is None:
                self._group_rate = rate
            else:
                self._group_rate += self.alpha * (rate - self._group_rate)
            if len(shard_busy_s):
                frac = np.asarray(shard_busy_s, dtype=np.float64) / dt
                if self._shard_busy_frac is None or \
                        len(self._shard_busy_frac) != len(frac):
                    self._shard_busy_frac = frac
                else:
                    self._shard_busy_frac += self.alpha * (
                        frac - self._shard_busy_frac)
        self._group_counts[:] = 0
        self._records_pending = 0
        if len(shard_resident_rows):
            res = np.asarray(shard_resident_rows, dtype=np.float64)
            if self._shard_resident is None or \
                    len(self._shard_resident) != len(res):
                self._shard_resident = res
            else:
                self._shard_resident += self.alpha * (
                    res - self._shard_resident)

    # ------------------------------------------------------------ queries

    def group_load(self) -> np.ndarray:
        """EWMA records/sec per LOCAL key group (len == span). Before the
        first differentiating tick, falls back to the raw pending counts
        (so a single-batch smoke still sees shape)."""
        if self._group_rate is not None:
            return self._group_rate.copy()
        return self._group_counts.astype(np.float64)

    def shard_load(self, assignment: Optional[KeyGroupAssignment] = None
                   ) -> np.ndarray:
        """Group loads projected onto shards through ``assignment``
        (default: the contiguous layout)."""
        if assignment is None:
            assignment = KeyGroupAssignment.contiguous(
                self.num_shards, self.max_parallelism,
                None if (self.first == 0 and
                         self.span == self.max_parallelism)
                else (self.first, self.first + self.span - 1))
        shards = assignment.table
        return np.bincount(shards, weights=self.group_load(),
                           minlength=self.num_shards)

    def imbalance(self, assignment: Optional[KeyGroupAssignment] = None
                  ) -> float:
        """max-shard-load * P / total — same definition the autoscale
        skew guard pins (1.0 == perfectly balanced)."""
        loads = self.shard_load(assignment)
        total = float(loads.sum())
        if total <= 0.0:
            return 1.0
        return float(loads.max()) * len(loads) / total

    def hot_key_candidates(self) -> List[Tuple[int, int, float]]:
        """``(key_id, global_group, share_of_group)`` for sketched heavy
        hitters, hottest first. ``share_of_group`` ~ the fraction of the
        key's group's load this single key carries — the split-stage
        trigger signal."""
        if not self._mg:
            return []
        gl = self.group_load()
        out = []
        keys = np.fromiter(self._mg.keys(), dtype=np.int64,
                           count=len(self._mg))
        groups = assign_key_groups(keys, self.max_parallelism)
        total = max(1, self.records_seen)
        for key, grp in zip(keys.tolist(),
                            np.asarray(groups, dtype=np.int64).tolist()):
            cnt = self._mg[key]
            g_local = grp - self.first
            g_load = float(gl[g_local]) if 0 <= g_local < self.span else 0.0
            # MG counts are over the whole run; group rate is per-second.
            # Compare like with like: the key's share of ALL records vs
            # the group's share of the total rate.
            key_share = cnt / total
            g_total = float(gl.sum())
            g_share = g_load / g_total if g_total > 0 else 0.0
            share = key_share / g_share if g_share > 0 else 0.0
            out.append((int(key), int(grp), float(min(1.0, share))))
        out.sort(key=lambda t: -t[2])
        return out

    def hottest_group(self) -> int:
        """GLOBAL id of the currently hottest key group."""
        return int(np.argmax(self.group_load())) + self.first

    # ------------------------------------------------------------ metrics

    def register_metrics(self, group) -> None:
        g = group.add_group("skew")
        g.gauge("imbalance", lambda: self.imbalance())
        g.gauge("hottest_group", self.hottest_group)
        g.gauge("hottest_shard",
                lambda: int(np.argmax(self.shard_load())))
        g.gauge("records_seen", lambda: self.records_seen)
        g.gauge("ticks", lambda: self.ticks)
        g.gauge("hot_key_count", lambda: len(self._mg))
