"""The data plane: key-group repartitioning over the device mesh.

Replaces the reference's Netty shuffle (reference:
flink-runtime/.../io/network/ — RecordWriter.emit:105 -> KeyGroupStreamPartitioner
.selectChannel:55 -> PipelinedSubpartition -> Netty TCP with credit-based flow
control) with two TPU-native mechanisms:

1. **Host-side bucketing** for source->device ingestion: records are grouped
   by owning shard (key_group -> shard via the reference's operator-index
   formula) into a dense ``[num_shards, B]`` block that is laid out with the
   leading axis sharded over the mesh — the "shuffle" is then just a sharded
   device_put.
2. **``all_to_all`` over ICI** for device->device repartitioning between
   chained keyed stages (each shard holds records destined for every other
   shard; one collective delivers them), and **``psum``** for two-phase
   local/global aggregation (the MiniBatch local/global pattern, reference:
   flink-table-runtime/.../aggregate/MiniBatchLocalGroupAggFunction.java /
   MiniBatchGlobalGroupAggFunction.java).

Backpressure (credit-based flow control) maps to the bounded micro-batch
queue feeding the device — see flink_tpu.runtime.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.chaos import injection as chaos
from flink_tpu.ops.segment_ops import pad_bucket_size
from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.state.keygroups import (
    assign_key_groups,
    key_group_to_operator_index,
)


class ShuffleBufferPool:
    """Reused host-side staging buffers for the [num_shards, B] blocks.

    Allocating (and zero/identity-filling) fresh blocks per batch per
    column was a measurable slice of the mesh engines' host prep; the
    pool hands back the same arrays across batches instead. Buffers
    rotate through ``generations`` slots and a caller ``flip()``s once
    per batch, so with dispatch-ahead <= generations the async
    ``device_put`` that consumed a buffer has completed before the
    buffer is written again (the double-buffer contract — the engines
    fence their dispatch depth to guarantee it).
    """

    def __init__(self, generations: int = 2) -> None:
        self.generations = max(int(generations), 1)
        self._gen = 0
        self._bufs: Dict[tuple, np.ndarray] = {}

    def flip(self) -> None:
        """Advance to the next buffer generation (call once per batch)."""
        self._gen = (self._gen + 1) % self.generations

    def get(self, shape: tuple, dtype, fill, tag=None) -> np.ndarray:
        """A [shape] buffer pre-filled with ``fill`` (fast memset on
        reuse, one allocation on first use per shape/dtype/generation).
        ``tag`` disambiguates same-shaped buffers used concurrently
        within one generation (e.g. two value columns of one batch)."""
        dtype = np.dtype(dtype)
        key = (self._gen, shape, dtype.str, tag)
        buf = self._bufs.get(key)
        if buf is None:
            buf = np.empty(shape, dtype=dtype)
            self._bufs[key] = buf
        buf.fill(fill)
        return buf


def bucket_by_shard(
    shard_of_record: np.ndarray,
    num_shards: int,
    columns: Sequence[np.ndarray],
    fills: Sequence,
    min_bucket: int = 256,
    pool: Optional[ShuffleBufferPool] = None,
) -> Tuple[np.ndarray, List[np.ndarray], np.ndarray]:
    """Group records into a dense [num_shards, B] block (host side).

    Returns (counts[num_shards], blocked_columns each [num_shards, B],
    order) where order is the permutation applied to the input records
    (records of shard p occupy block[p, :counts[p]]).

    Fully vectorized: one argsort for the permutation, then ONE fancy
    scatter per column through a precomputed flat index (record i of the
    sorted stream lands at row shard, column i - offsets[shard]) — no
    per-shard Python loop. With ``pool`` set the destination blocks are
    reused (pinned) buffers instead of per-batch allocations.
    """
    shard_of_record = np.asarray(shard_of_record)
    n = len(shard_of_record)
    counts = np.bincount(shard_of_record, minlength=num_shards)
    # chaos (armed-only — the disarmed path pays one module check):
    # per-shard bucket faults model a lossy exchange. drop re-fills the
    # shard's rows (they then scatter identities into slot 0, i.e. the
    # records vanish in flight), duplicate replays them (B is padded to
    # hold the copy), delay/raise apply inside payload_action.
    mutations: Dict[int, str] = {}
    if chaos.armed():
        chaos.fault_point("shuffle.bucket_prep", num_shards=num_shards)
        for p in np.nonzero(counts)[0].tolist():
            rule = chaos.payload_action("shuffle.bucket_send", shard=p)
            if rule is not None and rule.kind in ("drop", "duplicate"):
                mutations[p] = rule.kind
    eff_counts = counts
    if mutations:
        eff_counts = counts.copy()
        for p, kind in mutations.items():
            if kind == "duplicate":
                eff_counts[p] = counts[p] * 2
    B = pad_bucket_size(int(eff_counts.max()) if n else 0,
                        minimum=min_bucket)
    order = np.argsort(shard_of_record, kind="stable")
    offsets = np.zeros(num_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    sorted_shard = shard_of_record[order]
    # flat destination of sorted record j: its shard's row, at column
    # j - offsets[shard] (its rank within the shard)
    flat_dst = (sorted_shard * B
                + np.arange(n, dtype=np.int64) - offsets[sorted_shard])
    blocked = []
    for ci, (col, fill) in enumerate(zip(columns, fills)):
        col = np.asarray(col)
        shape = (num_shards, B) + col.shape[1:]
        if pool is not None:
            block = pool.get(shape, col.dtype, fill, tag=("bucket", ci))
        else:
            block = np.full(shape, fill, dtype=col.dtype)
        block.reshape((num_shards * B,) + col.shape[1:])[flat_dst] = \
            col[order]
        blocked.append(block)
    if mutations:
        for p, kind in mutations.items():
            c = int(counts[p])
            for block, fill in zip(blocked, fills):
                if kind == "drop":
                    block[p, :c] = fill
                else:  # duplicate: replay the bucket's rows
                    block[p, c:2 * c] = block[p, :c]
            eff_counts[p] = 0 if kind == "drop" else 2 * c
        counts = eff_counts
    return counts, blocked, order


def shard_records(
    key_ids: np.ndarray,
    num_shards: int,
    max_parallelism: int,
    key_group_range=None,
) -> np.ndarray:
    """key id -> owning shard (the keyBy routing decision).

    reference: KeyGroupStreamPartitioner.java:55 selectChannel =
    operator index of the key's group.

    ``key_group_range`` = (first, last) inclusive global key groups this
    mesh owns (the mesh x stage composition: a keyed SUBTASK owns a range
    of the global key-group space and shards it across its private
    sub-mesh). The reference formula applied to the LOCAL group space —
    without the remap, a sub-range would collapse onto a couple of shards.
    """
    groups = assign_key_groups(key_ids, max_parallelism)
    if key_group_range is not None:
        first, last = key_group_range
        local = (np.asarray(groups, dtype=np.int64) - int(first))
        local_max = int(last) - int(first) + 1
        return ((local * num_shards) // local_max).astype(np.int64)
    return key_group_to_operator_index(groups, max_parallelism, num_shards)


# ---------------------------------------------------------------------------
# Device-side collectives (used inside shard_map-ped steps)
# ---------------------------------------------------------------------------


def make_all_to_all_repartition(mesh: Mesh):
    """[P, P, B] block (dim0 = source shard sharded, dim1 = dest shard) ->
    redistributed so each shard holds the rows destined for it.

    This is the ICI replacement for the reference's network exchange between
    two keyed stages.
    """

    @jax.jit
    def repartition(block):
        def local(x):  # x: [1, P, B, ...]; dim1 indexed by destination shard
            # exchange blocks: after this, dim1 is indexed by SOURCE shard
            return jax.lax.all_to_all(x, KEY_AXIS, split_axis=1, concat_axis=1)

        return shard_map(
            local, mesh=mesh,
            in_specs=P(KEY_AXIS), out_specs=P(KEY_AXIS))(block)

    return repartition


def make_global_combine(mesh: Mesh, reduce: str = "sum"):
    """Two-phase aggregation: per-shard partials [P, ...] -> full reduction
    replicated on every shard (psum/pmax/pmin over ICI)."""

    op = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}[reduce]

    local_reduce = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[reduce]

    @jax.jit
    def combine(partials):
        def local(x):  # [1, ...] per shard
            return op(local_reduce(x, axis=0), KEY_AXIS)

        return shard_map(
            local, mesh=mesh,
            in_specs=P(KEY_AXIS), out_specs=P())(partials)

    return combine
