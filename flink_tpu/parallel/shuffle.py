"""The data plane: key-group repartitioning over the device mesh.

Replaces the reference's Netty shuffle (reference:
flink-runtime/.../io/network/ — RecordWriter.emit:105 -> KeyGroupStreamPartitioner
.selectChannel:55 -> PipelinedSubpartition -> Netty TCP with credit-based flow
control) with two TPU-native mechanisms:

1. **The in-program device exchange** (``shuffle.mode=device``, the
   default): a batch goes host->device ONCE as flat padded columns (one
   ``device_put`` of the whole column pytree against the key-group
   sharding), and a single jitted shard_map program segment-sorts each
   shard's chunk into per-destination buckets, exchanges them with
   ``all_to_all`` over the mesh axis, and feeds the segment-reduce
   scatter in the SAME program — ``keyBy -> window -> aggregate`` is one
   XLA program end to end (``build_exchange_scatter``). The collective
   runs over ICI on real hardware; there is no host argsort and no
   ``[num_shards, B]`` staging block.
2. **Host-side bucketing** (``shuffle.mode=host``, the explicit
   fallback): records are grouped by owning shard (key_group -> shard
   via the reference's operator-index formula) into a dense
   ``[num_shards, B]`` block that is laid out with the leading axis
   sharded over the mesh — the "shuffle" is then just a sharded
   device_put (``bucket_by_shard``).

``all_to_all`` also repartitions between chained keyed stages
(``make_all_to_all_repartition``), and **``psum``** handles two-phase
local/global aggregation (the MiniBatch local/global pattern, reference:
flink-table-runtime/.../aggregate/MiniBatchLocalGroupAggFunction.java /
MiniBatchGlobalGroupAggFunction.java).

Backpressure (credit-based flow control) maps to the bounded micro-batch
queue feeding the device — see flink_tpu.runtime.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.chaos import injection as chaos
from flink_tpu.ops.segment_ops import SCATTER_METHOD, pad_bucket_size
from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.stateplane.backends import backend_of
from flink_tpu.stateplane.rank import exchange_rank_flat
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
from flink_tpu.state.keygroups import (
    assign_key_groups,
    key_group_to_operator_index,
)


class ShuffleBufferPool:
    """Reused host-side staging buffers for the [num_shards, B] blocks.

    Allocating (and zero/identity-filling) fresh blocks per batch per
    column was a measurable slice of the mesh engines' host prep; the
    pool hands back the same arrays across batches instead. Buffers
    rotate through ``generations`` slots and a caller ``flip()``s once
    per batch, so with dispatch-ahead <= generations the async
    ``device_put`` that consumed a buffer has completed before the
    buffer is written again (the double-buffer contract — the engines
    fence their dispatch depth to guarantee it).
    """

    def __init__(self, generations: int = 2) -> None:
        self.generations = max(int(generations), 1)
        self._gen = 0
        self._bufs: Dict[tuple, np.ndarray] = {}

    def flip(self) -> None:
        """Advance to the next buffer generation (call once per batch)."""
        self._gen = (self._gen + 1) % self.generations

    def get(self, shape: tuple, dtype, fill, tag=None) -> np.ndarray:
        """A [shape] buffer pre-filled with ``fill`` (fast memset on
        reuse, one allocation on first use per shape/dtype/generation).
        ``tag`` disambiguates same-shaped buffers used concurrently
        within one generation (e.g. two value columns of one batch)."""
        dtype = np.dtype(dtype)
        key = (self._gen, shape, dtype.str, tag)
        buf = self._bufs.get(key)
        if buf is None:
            buf = np.empty(shape, dtype=dtype)
            self._bufs[key] = buf
        buf.fill(fill)
        return buf


def bucket_by_shard(
    shard_of_record: np.ndarray,
    num_shards: int,
    columns: Sequence[np.ndarray],
    fills: Sequence,
    min_bucket: int = 256,
    pool: Optional[ShuffleBufferPool] = None,
    want_order: bool = False,
):
    """Group records into a dense [num_shards, B] block (host side).

    Returns ``(counts[num_shards], blocked_columns each [num_shards,
    B])`` — records of shard p occupy ``block[p, :counts[p]]`` in
    stream order. With ``want_order=True`` the applied permutation is
    returned as a third element; the engines pre-permute their columns
    and never need it, so the default return shape is explicit about
    that (no silently-discarded values at the call sites).

    Fully vectorized: one argsort for the permutation, then ONE fancy
    scatter per column through a precomputed flat index (record i of the
    sorted stream lands at row shard, column i - offsets[shard]) — no
    per-shard Python loop. With ``pool`` set the destination blocks are
    reused (pinned) buffers instead of per-batch allocations.
    """
    shard_of_record = np.asarray(shard_of_record)
    n = len(shard_of_record)
    counts = np.bincount(shard_of_record, minlength=num_shards)
    # chaos (armed-only — the disarmed path pays one module check):
    # per-shard bucket faults model a lossy exchange. drop re-fills the
    # shard's rows (they then scatter identities into slot 0, i.e. the
    # records vanish in flight), duplicate replays them (B is padded to
    # hold the copy), delay/raise apply inside payload_action.
    mutations: Dict[int, str] = {}
    if chaos.armed():
        chaos.fault_point("shuffle.bucket_prep", num_shards=num_shards)
        for p in np.nonzero(counts)[0].tolist():
            rule = chaos.payload_action("shuffle.bucket_send", shard=p)
            if rule is not None and rule.kind in ("drop", "duplicate"):
                mutations[p] = rule.kind
    eff_counts = counts
    if mutations:
        eff_counts = counts.copy()
        for p, kind in mutations.items():
            if kind == "duplicate":
                eff_counts[p] = counts[p] * 2
    B = pad_bucket_size(int(eff_counts.max()) if n else 0,
                        minimum=min_bucket)
    order = np.argsort(shard_of_record, kind="stable")
    offsets = np.zeros(num_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    sorted_shard = shard_of_record[order]
    # flat destination of sorted record j: its shard's row, at column
    # j - offsets[shard] (its rank within the shard)
    flat_dst = (sorted_shard * B
                + np.arange(n, dtype=np.int64) - offsets[sorted_shard])
    blocked = []
    for ci, (col, fill) in enumerate(zip(columns, fills)):
        col = np.asarray(col)
        shape = (num_shards, B) + col.shape[1:]
        if pool is not None:
            block = pool.get(shape, col.dtype, fill, tag=("bucket", ci))
        else:
            block = np.full(shape, fill, dtype=col.dtype)
        block.reshape((num_shards * B,) + col.shape[1:])[flat_dst] = \
            col[order]
        blocked.append(block)
    if mutations:
        for p, kind in mutations.items():
            c = int(counts[p])
            for block, fill in zip(blocked, fills):
                if kind == "drop":
                    block[p, :c] = fill
                else:  # duplicate: replay the bucket's rows
                    block[p, c:2 * c] = block[p, :c]
            eff_counts[p] = 0 if kind == "drop" else 2 * c
        counts = eff_counts
    if want_order:
        return counts, blocked, order
    return counts, blocked


def shard_records(
    key_ids: np.ndarray,
    num_shards: int,
    max_parallelism: int,
    key_group_range=None,
    assignment=None,
) -> np.ndarray:
    """key id -> owning shard (the keyBy routing decision).

    reference: KeyGroupStreamPartitioner.java:55 selectChannel =
    operator index of the key's group.

    ``key_group_range`` = (first, last) inclusive global key groups this
    mesh owns (the mesh x stage composition: a keyed SUBTASK owns a range
    of the global key-group space and shards it across its private
    sub-mesh). The reference formula applied to the LOCAL group space —
    without the remap, a sub-range would collapse onto a couple of shards.

    ``assignment``: a :class:`flink_tpu.state.KeyGroupAssignment` — the
    explicit table a rebalanced plane routes by instead of the
    contiguous formula. Subsumes ``key_group_range`` (an assignment
    carries its own first/span).
    """
    groups = assign_key_groups(key_ids, max_parallelism)
    if assignment is not None:
        return assignment.shard_of_groups(groups).astype(np.int64)
    if key_group_range is not None:
        first, last = key_group_range
        local = (np.asarray(groups, dtype=np.int64) - int(first))
        local_max = int(last) - int(first) + 1
        return ((local * num_shards) // local_max).astype(np.int64)
    return key_group_to_operator_index(groups, max_parallelism, num_shards)


# ---------------------------------------------------------------------------
# The in-program exchange (shuffle.mode=device)
# ---------------------------------------------------------------------------


def exchange_chunk_size(n: int, num_shards: int,
                        min_bucket: int = 256) -> int:
    """Per-shard flat-column chunk length for ``n`` records: the
    ``pad_bucket_size`` tier of ``ceil(n / num_shards)``, so the fused
    exchange program compiles once per tier (the same bounded shape set
    the host blocks use) and the staged length ``num_shards * C`` is
    always divisible by the mesh."""
    per = -(-max(int(n), 1) // num_shards)
    return pad_bucket_size(per, minimum=min_bucket)


def stage_device_exchange(
    shard_of_record: np.ndarray,
    num_shards: int,
    columns: Sequence[np.ndarray],
    fills: Sequence,
    min_bucket: int = 256,
    pool: Optional[ShuffleBufferPool] = None,
) -> Tuple[np.ndarray, List[np.ndarray], int]:
    """Stage flat record columns for the in-program exchange.

    Unlike :func:`bucket_by_shard` there is NO host argsort and NO
    [num_shards, B] scatter: each column is copied once into a padded
    flat buffer of length ``num_shards * C`` (``C`` =
    :func:`exchange_chunk_size` — a ``pad_bucket_size`` tier, so the
    fused program's shape set stays bounded) and the segment sort +
    exchange happen inside the compiled program. Padded lanes carry the
    out-of-range destination ``num_shards``; the program drops them
    before the collective.

    Returns ``(dst, staged_columns, bucket_width)``, columns all length
    ``num_shards * C``. ``bucket_width`` is the ``pad_bucket_size`` tier
    of the batch's densest (source chunk, destination) pair count — the
    static per-pair bucket capacity the fused program allocates. Sizing
    it to the worst case (``C``) would make every shard's received
    block ``num_shards`` times wider than the data; the O(n) host
    bincount buys the compiled program a ~P-fold smaller exchange
    payload at the cost of one more bounded shape dimension.

    The chaos payload point ``shuffle.device_exchange`` models a lossy
    exchange like ``shuffle.bucket_send`` does for the host path: drop
    re-routes one shard's records to the padding destination (they
    vanish before the collective), duplicate replays them.
    """
    shard_of_record = np.asarray(shard_of_record)
    n = len(shard_of_record)
    columns = [np.asarray(c) for c in columns]
    if chaos.armed():
        # payload kinds only — raise/delay fire at the engines'
        # post-dispatch fault point, so a "crash mid-batch" lands AFTER
        # the fused program was dispatched (the hardest restore case)
        mutations: Dict[int, str] = {}
        present = np.unique(shard_of_record) if n else ()
        for p in present:
            rule = chaos.payload_action(
                "shuffle.device_exchange",
                kinds=("drop", "duplicate", "delay"), shard=int(p))
            if rule is not None and rule.kind in ("drop", "duplicate"):
                mutations[int(p)] = rule.kind
        for p, kind in mutations.items():
            sel = shard_of_record == p
            if kind == "drop":
                shard_of_record = np.where(sel, num_shards,
                                           shard_of_record)
            else:  # duplicate: replay the shard's records
                shard_of_record = np.concatenate(
                    [shard_of_record, shard_of_record[sel]])
                columns = [np.concatenate([c, c[sel]]) for c in columns]
                n = len(shard_of_record)
    C = exchange_chunk_size(n, num_shards, min_bucket)
    N = num_shards * C
    dst = (pool.get((N,), np.int32, num_shards, tag=("xchg", "dst"))
           if pool is not None
           else np.full(N, num_shards, dtype=np.int32))
    dst[:n] = shard_of_record
    staged: List[np.ndarray] = []
    for ci, (col, fill) in enumerate(zip(columns, fills)):
        shape = (N,) + col.shape[1:]
        if pool is not None:
            buf = pool.get(shape, col.dtype, fill, tag=("xchg", ci))
        else:
            buf = np.full(shape, fill, dtype=col.dtype)
        buf[:n] = col
        staged.append(buf)
    # densest (source chunk, destination) pair: one flat bincount over
    # the real records (padding lanes land in the excluded column)
    if n:
        chunk_of = np.arange(n, dtype=np.int64) // C
        pair_max = int(np.bincount(
            chunk_of * (num_shards + 1)
            + np.minimum(dst[:n], num_shards),
            minlength=num_shards * (num_shards + 1))
            .reshape(num_shards, num_shards + 1)[:, :num_shards].max())
    else:
        pair_max = 0
    bucket_width = min(pad_bucket_size(pair_max, minimum=min_bucket), C)
    return dst, staged, bucket_width


def build_exchange_scatter(mesh: Mesh, agg, valued: bool = False):
    """The fused exchange+scatter program: ONE jitted shard_map over the
    whole mesh that (a) segment-sorts each shard's flat record chunk
    into per-destination buckets, (b) exchanges the buckets with
    ``all_to_all`` over the mesh axis, and (c) scatters the received
    rows into the [P, capacity] accumulator plane — the keyBy exchange
    and the aggregate step as one XLA program.

    ``valued=False`` folds raw input-leaf values (const leaves derive on
    device, like ``scatter_step``); ``valued=True`` folds explicit
    per-ACC-leaf partials (the two-phase local/global path, like
    ``valued_scatter_step``). Cached in the shared program cache per
    ``(device ids, aggregate layout, variant)`` — jobs and rebuilt
    engines share the executable (the multi-tenant zero-recompile
    contract), shapes one level down via jit + the pad_bucket_size
    tiers."""
    rank_backend = backend_of("exchange-rank")
    key = (tuple(d.id for d in mesh.devices.flat), agg.cache_key(),
           bool(valued), rank_backend)
    return PROGRAM_CACHE.get_or_build(
        "exchange-scatter", key,
        lambda: _build_exchange_scatter(mesh, agg, valued, rank_backend))


def _build_exchange_scatter(mesh: Mesh, agg, valued: bool,
                            rank_backend: str = "xla"):
    leaves = agg.leaves
    methods = tuple(SCATTER_METHOD[l.reduce] for l in leaves)
    n_leaves = len(leaves)
    num_shards = int(mesh.devices.size)
    # pallas_call has no shard_map replication rule — disable the check
    # for the pallas-ranked build only (the xla build stays byte-
    # identical in behavior to the pre-stateplane program)
    sm_kwargs = {"check_rep": False} if rank_backend == "pallas" else {}

    def _exchange(block):
        # [P, W] local block, dim0 = destination shard -> [P, W] with
        # dim0 = source shard (the ICI hop; identity on a 1-mesh)
        if num_shards == 1:
            return block
        return jax.lax.all_to_all(block, KEY_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(0,))
    def exchange_scatter(accs, dst, slots, values, bucket_width):
        W = int(bucket_width)

        def local(*args):
            accs_l = args[:n_leaves]         # each [1, cap]
            d = args[n_leaves]               # [C] destination shard
            s = args[n_leaves + 1]           # [C] destination slot
            vals_l = iter(args[n_leaves + 2:])
            # rank of record i within its destination = count of prior
            # same-destination records: preserves STREAM ORDER per
            # destination (chunks partition the stream contiguously, so
            # the received (source, rank) flattening is stream order —
            # the same order the host bucketing produces, which keeps
            # float folds bit-identical across modes). Padded / dropped
            # lanes (dst == num_shards) get the out-of-range flat
            # sentinel and are dropped by the scatter; the host sized W
            # to the batch's densest pair, so the rank < W guard only
            # bounds a miscount to a drop (-> oracle divergence)
            # instead of silent row corruption.
            flat = exchange_rank_flat(d, num_shards, W, rank_backend)
            recv_s = _exchange(
                jnp.zeros((num_shards * W,), jnp.int32)
                .at[flat].set(s, mode="drop")
                .reshape(num_shards, W)).reshape(-1)
            out = []
            for a, m, l in zip(accs_l, methods, leaves):
                if not valued and l.const is not None:
                    # bucket lanes that received no record hold slot 0
                    # (the reserved identity slot) — keep it pure
                    v = jnp.where(
                        recv_s == 0,
                        jnp.asarray(l.identity, dtype=l.dtype),
                        jnp.asarray(l.const, dtype=l.dtype))
                else:
                    v = _exchange(
                        jnp.full((num_shards * W,), l.identity,
                                 dtype=l.dtype)
                        .at[flat].set(next(vals_l), mode="drop")
                        .reshape(num_shards, W)).reshape(-1)
                out.append(getattr(a.at[0, recv_s], m)(v))
            return tuple(out)

        n_vals = len(values)
        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 2 + n_vals),
            out_specs=(P(KEY_AXIS),) * n_leaves,
            **sm_kwargs,
        )(*accs, dst, slots, *values)

    return exchange_scatter


# ---------------------------------------------------------------------------
# Device-side collectives (used inside shard_map-ped steps)
# ---------------------------------------------------------------------------


def make_all_to_all_repartition(mesh: Mesh):
    """[P, P, B] block (dim0 = source shard sharded, dim1 = dest shard) ->
    redistributed so each shard holds the rows destined for it.

    This is the ICI replacement for the reference's network exchange between
    two keyed stages.
    """

    @jax.jit
    def repartition(block):
        def local(x):  # x: [1, P, B, ...]; dim1 indexed by destination shard
            # exchange blocks: after this, dim1 is indexed by SOURCE shard
            return jax.lax.all_to_all(x, KEY_AXIS, split_axis=1, concat_axis=1)

        return shard_map(
            local, mesh=mesh,
            in_specs=P(KEY_AXIS), out_specs=P(KEY_AXIS))(block)

    return repartition


def make_global_combine(mesh: Mesh, reduce: str = "sum"):
    """Two-phase aggregation: per-shard partials [P, ...] -> full reduction
    replicated on every shard (psum/pmax/pmin over ICI)."""

    op = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}[reduce]

    local_reduce = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[reduce]

    @jax.jit
    def combine(partials):
        def local(x):  # [1, ...] per shard
            return op(local_reduce(x, axis=0), KEY_AXIS)

        return shard_map(
            local, mesh=mesh,
            in_specs=P(KEY_AXIS), out_specs=P())(partials)

    return combine
