"""The two-level (ICI/DCN) exchange: the pod-scale data plane.

A flat ``all_to_all`` over a process-spanning mesh treats every
(source, destination) shard pair uniformly — intra-host traffic that
could ride ICI pays the DCN latency of the slowest link, and the
payload fragments into ``P x P`` tiny blocks. The two-level program
family splits the keyBy exchange along the physical topology
(:class:`~flink_tpu.parallel.mesh.HostTopology`):

- **Stage 1 (ICI)**: each shard segment-sorts its flat record chunk by
  the destination's LOCAL index (one-hot-cumsum ranks, the same
  order-preserving discipline as the flat program) and ``all_to_all``s
  the ``[L, W1]`` buckets over the intra-host ``local`` axis. After
  stage 1 every record sits on the shard whose local index matches its
  destination's — intra-host records are home, cross-host records need
  only the host hop.
- **Stage 2 (DCN)**: the received rows (flattened in (source-local,
  rank) order — stream order restricted to the source host) bucket by
  destination HOST into ``[H, W2]`` and ``all_to_all`` over the
  ``hosts`` axis. Only the off-diagonal blocks cross the DCN; the
  genuinely cross-host residue is batched into one block per host pair
  instead of ``L x L`` fragments. The receive flattening (source-host,
  rank) is GLOBAL stream order (chunks partition the stream host-major),
  so the single scatter that follows folds every slot's records in
  stream order — float folds stay bit-identical to the flat exchange
  AND the host bucketing path.

Both stages are their own jitted programs (so the flight recorder can
attribute ICI vs DCN time as distinct span kinds) with their own
``pad_bucket_size`` tier (``W1`` = densest (chunk, dest-local) pair,
``W2`` = densest (source-host, dest-shard) pair) — steady-state
compiles stay 0 across the tier lattice. Cached in the shared
PROGRAM_CACHE keyed ``(device ids, topology, layout)`` — tenants and
rebuilt engines share the executables. The flat single-axis program
remains the single-host fast path (``HostTopology(1, P)`` never routes
here).

The chaos payload point ``exchange.dcn_send`` models a lossy DCN link:
drop/duplicate/delay per (src_host, dst_host) bucket, cross-host pairs
only — the intra-host stage rides ICI and has its own fault points.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_tpu.chaos import injection as chaos
from flink_tpu.ops.segment_ops import SCATTER_METHOD, pad_bucket_size
from flink_tpu.parallel.mesh import (
    HOST_AXIS,
    LOCAL_AXIS,
    HostTopology,
    pod_mesh_view,
    shard_map,
)
from flink_tpu.stateplane.backends import backend_of
from flink_tpu.stateplane.rank import exchange_rank_flat
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE


class ExchangeTraffic:
    """Per-engine two-level traffic accounting: how many records stayed
    on ICI vs genuinely crossed the DCN (the smoke's vacuity guard and
    the NOTES scaling-walk split read these)."""

    __slots__ = ("rows_intra_host", "rows_cross_host", "batches")

    def __init__(self) -> None:
        self.rows_intra_host = 0
        self.rows_cross_host = 0
        self.batches = 0

    def as_dict(self) -> Dict[str, int]:
        return {"rows_intra_host": self.rows_intra_host,
                "rows_cross_host": self.rows_cross_host,
                "exchange2_batches": self.batches}

    @staticmethod
    def dict_of(traffic) -> Dict[str, int]:
        """``traffic.as_dict()`` or the zero dict for engines running
        the flat exchange — ONE shape for every ``exchange2_traffic``
        accessor (engines must not re-inline the key set)."""
        if traffic is not None:
            return traffic.as_dict()
        return ExchangeTraffic().as_dict()


def two_level_active(topology, shuffle_mode: str) -> bool:
    """THE activation rule, shared by every engine: a multi-host
    factorization under the device data plane."""
    return (topology is not None and topology.num_hosts > 1
            and shuffle_mode == "device")


def stage_two_level_exchange(
    shard_of_record: np.ndarray,
    topology: HostTopology,
    columns: Sequence[np.ndarray],
    fills: Sequence,
    min_bucket: int = 256,
    pool=None,
    traffic: Optional[ExchangeTraffic] = None,
) -> Tuple[np.ndarray, List[np.ndarray], int, int]:
    """Stage flat record columns for the two-level exchange.

    Identical staging contract to
    :func:`~flink_tpu.parallel.shuffle.stage_device_exchange` (flat
    padded columns of length ``P * C``, padding lanes carry the
    out-of-range destination ``P``), plus the per-LEVEL bucket tiers:
    returns ``(dst, staged_columns, w1, w2)`` where ``w1`` bounds the
    densest (source chunk, destination local index) pair and ``w2`` the
    densest (source host, destination shard) pair — each level's
    compiled program allocates exactly its own bucket capacity.
    """
    from flink_tpu.parallel.shuffle import exchange_chunk_size

    H, L = topology.num_hosts, topology.local_devices
    num_shards = topology.num_shards
    shard_of_record = np.asarray(shard_of_record)
    n = len(shard_of_record)
    columns = [np.asarray(c) for c in columns]
    if chaos.armed():
        # DCN link faults: payload kinds per CROSS-host (src, dst) pair
        # (the intra-host stage is ICI — shuffle.device_exchange and the
        # engines' post-dispatch crash point cover it). The source host
        # of a record is its staging chunk's host; provisional chunking
        # from the pre-mutation length keeps the rule deterministic.
        C0 = exchange_chunk_size(n, num_shards, min_bucket)
        src_host = (np.arange(n, dtype=np.int64) // C0) // L
        dst_host = shard_of_record // L
        cross = src_host != dst_host
        if cross.any():
            pairs = np.unique(
                np.stack([src_host[cross], dst_host[cross]], axis=1),
                axis=0)
            drop_mask = np.zeros(n, dtype=bool)
            dup_mask = np.zeros(n, dtype=bool)
            for sh, dh in pairs.tolist():
                rule = chaos.payload_action(
                    "exchange.dcn_send",
                    kinds=("drop", "duplicate", "delay"),
                    src_host=int(sh), dst_host=int(dh))
                if rule is None:
                    continue
                sel = cross & (src_host == sh) & (dst_host == dh)
                if rule.kind == "drop":
                    drop_mask |= sel
                elif rule.kind == "duplicate":
                    dup_mask |= sel
            if drop_mask.any():
                # dropped rows re-route to the padding destination:
                # they vanish before the stage-1 collective, exactly a
                # lost DCN bucket (the oracle diff catches it)
                shard_of_record = np.where(drop_mask, num_shards,
                                           shard_of_record)
            if dup_mask.any():
                shard_of_record = np.concatenate(
                    [shard_of_record, shard_of_record[dup_mask]])
                columns = [np.concatenate([c, c[dup_mask]])
                           for c in columns]
                n = len(shard_of_record)
    C = exchange_chunk_size(n, num_shards, min_bucket)
    N = num_shards * C
    dst = (pool.get((N,), np.int32, num_shards, tag=("xchg2", "dst"))
           if pool is not None
           else np.full(N, num_shards, dtype=np.int32))
    dst[:n] = shard_of_record
    staged: List[np.ndarray] = []
    for ci, (col, fill) in enumerate(zip(columns, fills)):
        shape = (N,) + col.shape[1:]
        if pool is not None:
            buf = pool.get(shape, col.dtype, fill, tag=("xchg2", ci))
        else:
            buf = np.full(shape, fill, dtype=col.dtype)
        buf[:n] = col
        staged.append(buf)
    # per-level densest pairs, one bincount pass each over the real
    # records (padding lanes excluded structurally)
    if n:
        real = dst[:n]
        live = real < num_shards
        idx = np.nonzero(live)[0]
        d_live = real[idx].astype(np.int64)
        chunk_of = idx // C
        # W1: records of chunk c destined to local index l (any host)
        dl = d_live % L
        w1_max = int(np.bincount(chunk_of * L + dl,
                                 minlength=num_shards * L).max()) \
            if len(idx) else 0
        # W2: records of source host (c // L) destined to shard d
        sh = chunk_of // L
        w2_max = int(np.bincount(sh * num_shards + d_live,
                                 minlength=H * num_shards).max()) \
            if len(idx) else 0
        if traffic is not None:
            crossed = int((sh != d_live // L).sum())
            traffic.rows_cross_host += crossed
            traffic.rows_intra_host += int(len(idx)) - crossed
            traffic.batches += 1
    else:
        w1_max = w2_max = 0
        if traffic is not None:
            traffic.batches += 1
    w1 = min(pad_bucket_size(w1_max, minimum=min_bucket), C)
    # stage 2's input is the [L, W1] receive block: a (host, shard)
    # pair can at most fill it
    w2 = min(pad_bucket_size(w2_max, minimum=min_bucket), L * w1)
    return dst, staged, w1, w2


# ---------------------------------------------------------------------------
# program families
# ---------------------------------------------------------------------------


def _mesh_key(mesh) -> Tuple[int, ...]:
    return tuple(d.id for d in mesh.devices.flat)


def _stage1_route(mesh2, H: int, L: int, fill_specs,
                  rank_backend: str = "xla"):
    """Stage 1: route (dst, slot, values...) by destination LOCAL index
    over the intra-host axis. Returns per-column received buckets
    flattened ``[L * W1]`` in (source-local, rank) order."""
    num_shards = H * L
    sm_kwargs = {"check_rep": False} if rank_backend == "pallas" else {}

    def _xc_local(block):
        if L == 1:
            return block
        return jax.lax.all_to_all(block, LOCAL_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(3,))
    def stage1(dst, slots, values, w1):
        W1 = int(w1)

        def local(*args):
            d = args[0]                 # [C] global destination shard
            s = args[1]                 # [C] destination slot
            vals = args[2:]
            dl = jnp.where(d < num_shards,
                           jax.lax.rem(d, L), L)
            flat = exchange_rank_flat(dl, L, W1, rank_backend)
            outs = []
            # the destination shard rides the exchange (stage 2 needs
            # the host part); empty lanes carry the padding sentinel
            outs.append(_xc_local(
                jnp.full((L * W1,), num_shards, dtype=jnp.int32)
                .at[flat].set(d, mode="drop")
                .reshape(L, W1)).reshape(-1))
            outs.append(_xc_local(
                jnp.zeros((L * W1,), jnp.int32)
                .at[flat].set(s, mode="drop")
                .reshape(L, W1)).reshape(-1))
            for v, (dt, fill) in zip(vals, fill_specs):
                outs.append(_xc_local(
                    jnp.full((L * W1,), fill, dtype=dt)
                    .at[flat].set(v, mode="drop")
                    .reshape(L, W1)).reshape(-1))
            return tuple(outs)

        n_vals = len(values)
        spec = P((HOST_AXIS, LOCAL_AXIS))
        return shard_map(
            local, mesh=mesh2,
            in_specs=(spec,) * (2 + n_vals),
            out_specs=(spec,) * (2 + n_vals),
            **sm_kwargs,
        )(dst, slots, *values)

    return stage1


def _stage2_rank(d2, H: int, L: int, num_shards: int, W2: int,
                 rank_backend: str = "xla"):
    """Shared stage-2 bucketing: destination-host rank-within-
    destination (the stateplane exchange-rank combinator) over the
    stage-1 receive order."""
    dh = jnp.where(d2 < num_shards, d2 // L, H)
    return exchange_rank_flat(dh, H, W2, rank_backend)


def build_exchange2_steps(mesh, topology: HostTopology, agg,
                          valued: bool = False):
    """The two-level exchange+scatter pair for the mesh engines'
    aggregate planes: ``(stage1, stage2)`` jitted programs. ``stage2``
    folds the received rows into the [P, capacity] accumulators with
    the same per-slot stream-order guarantee as
    ``build_exchange_scatter`` — bit-identical output, two dispatches.
    """
    rank_backend = backend_of("exchange-rank")
    key = (_mesh_key(mesh), topology.num_hosts,
           topology.local_devices, agg.cache_key(), bool(valued),
           rank_backend)
    return (
        PROGRAM_CACHE.get_or_build(
            "exchange2-stage1", key,
            lambda: _build_fold_stage1(mesh, topology, agg, valued,
                                       rank_backend)),
        PROGRAM_CACHE.get_or_build(
            "exchange2-stage2", key,
            lambda: _build_fold_stage2(mesh, topology, agg, valued,
                                       rank_backend)),
    )


def _exchanged_leaves(agg, valued: bool):
    """The leaves whose value columns ride the exchange — all of them
    in the valued (two-phase partial) variant, only the const-free ones
    otherwise (const leaves derive on device at the final fold)."""
    if valued:
        return list(agg.leaves)
    return [l for l in agg.leaves if l.const is None]


def _build_fold_stage1(mesh, topology: HostTopology, agg, valued: bool,
                       rank_backend: str = "xla"):
    H, L = topology.num_hosts, topology.local_devices
    mesh2 = pod_mesh_view(mesh, topology)
    fill_specs = tuple((np.dtype(l.dtype).str, l.identity)
                       for l in _exchanged_leaves(agg, valued))
    return _stage1_route(mesh2, H, L, fill_specs, rank_backend)


def _build_fold_stage2(mesh, topology: HostTopology, agg, valued: bool,
                       rank_backend: str = "xla"):
    H, L = topology.num_hosts, topology.local_devices
    num_shards = H * L
    mesh2 = pod_mesh_view(mesh, topology)
    leaves = agg.leaves
    methods = tuple(SCATTER_METHOD[l.reduce] for l in leaves)
    n_leaves = len(leaves)
    sm_kwargs = {"check_rep": False} if rank_backend == "pallas" else {}

    def _xc_hosts(block):
        if H == 1:
            return block
        return jax.lax.all_to_all(block, HOST_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(0,))
    def stage2(accs, dst2, slots2, vals2, w2):
        W2 = int(w2)

        def local(*args):
            accs_l = args[:n_leaves]     # each [1, cap]
            d2 = args[n_leaves]          # [L*W1] destination shard
            s2 = args[n_leaves + 1]      # [L*W1] destination slot
            vals_l = iter(args[n_leaves + 2:])
            flat = _stage2_rank(d2, H, L, num_shards, W2, rank_backend)
            recv_s = _xc_hosts(
                jnp.zeros((H * W2,), jnp.int32)
                .at[flat].set(s2, mode="drop")
                .reshape(H, W2)).reshape(-1)
            out = []
            for a, m, l in zip(accs_l, methods, leaves):
                if not valued and l.const is not None:
                    # empty bucket lanes hold slot 0 (the reserved
                    # identity slot) — keep it pure
                    v = jnp.where(
                        recv_s == 0,
                        jnp.asarray(l.identity, dtype=l.dtype),
                        jnp.asarray(l.const, dtype=l.dtype))
                else:
                    v = _xc_hosts(
                        jnp.full((H * W2,), l.identity, dtype=l.dtype)
                        .at[flat].set(next(vals_l), mode="drop")
                        .reshape(H, W2)).reshape(-1)
                out.append(getattr(a.at[0, recv_s], m)(v))
            return tuple(out)

        n_vals = len(vals2)
        spec = P((HOST_AXIS, LOCAL_AXIS))
        return shard_map(
            local, mesh=mesh2,
            in_specs=(spec,) * (n_leaves + 2 + n_vals),
            out_specs=(spec,) * n_leaves,
            **sm_kwargs,
        )(*accs, dst2, slots2, *vals2)

    return stage2


def build_join_exchange2_steps(mesh, topology: HostTopology,
                               dtypes: Tuple[str, ...]):
    """The two-level variant of ``join-exchange-put``: stage 1 routes
    the (slot, value...) rows by destination local index, stage 2 hops
    the host axis and writes the received rows into the side table's
    plane (``.set`` — last write in stream order wins, identical to the
    flat join exchange)."""
    rank_backend = backend_of("exchange-rank")
    key = (_mesh_key(mesh), topology.num_hosts,
           topology.local_devices, tuple(dtypes), rank_backend)
    return (
        PROGRAM_CACHE.get_or_build(
            "join-exchange2-stage1", key,
            lambda: _build_join_stage1(mesh, topology, dtypes,
                                       rank_backend)),
        PROGRAM_CACHE.get_or_build(
            "join-exchange2-stage2", key,
            lambda: _build_join_stage2(mesh, topology, dtypes,
                                       rank_backend)),
    )


def _build_join_stage1(mesh, topology: HostTopology, dtypes,
                       rank_backend: str = "xla"):
    H, L = topology.num_hosts, topology.local_devices
    mesh2 = pod_mesh_view(mesh, topology)
    fill_specs = tuple((np.dtype(dt).str, 0) for dt in dtypes)
    return _stage1_route(mesh2, H, L, fill_specs, rank_backend)


def _build_join_stage2(mesh, topology: HostTopology, dtypes,
                       rank_backend: str = "xla"):
    H, L = topology.num_hosts, topology.local_devices
    num_shards = H * L
    mesh2 = pod_mesh_view(mesh, topology)
    n_cols = len(dtypes)
    sm_kwargs = {"check_rep": False} if rank_backend == "pallas" else {}

    def _xc_hosts(block):
        if H == 1:
            return block
        return jax.lax.all_to_all(block, HOST_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(0,))
    def stage2(planes, dst2, slots2, vals2, w2):
        W2 = int(w2)

        def local(*args):
            planes_l = args[:n_cols]
            d2 = args[n_cols]
            s2 = args[n_cols + 1]
            vs = args[n_cols + 2:]
            flat = _stage2_rank(d2, H, L, num_shards, W2, rank_backend)
            recv_s = _xc_hosts(
                jnp.zeros((H * W2,), jnp.int32)
                .at[flat].set(s2, mode="drop")
                .reshape(H, W2)).reshape(-1)
            out = []
            for pl, v in zip(planes_l, vs):
                rv = _xc_hosts(
                    jnp.zeros((H * W2,), pl.dtype)
                    .at[flat].set(v, mode="drop")
                    .reshape(H, W2)).reshape(-1)
                # empty lanes carry recv_s == 0: the reserved scratch
                # slot absorbs them
                out.append(pl.at[0, recv_s].set(rv))
            return tuple(out)

        spec = P((HOST_AXIS, LOCAL_AXIS))
        return shard_map(
            local, mesh=mesh2,
            in_specs=(spec,) * (2 * n_cols + 2),
            out_specs=(spec,) * n_cols,
            **sm_kwargs,
        )(*planes, dst2, slots2, *vals2)

    return stage2
