"""Mesh-sharded windowed keyed aggregation.

The multi-device form of ``flink_tpu.windowing.windower.SliceSharedWindower``:
state lives in ``[num_shards, capacity]`` device arrays with the leading axis
sharded over the key-group mesh axis; every step (scatter / fire / reset) is
ONE jitted ``shard_map`` program over the whole mesh. Records are routed to
their owning shard by the reference's key-group formula
(reference: KeyGroupRangeAssignment.java:124-127 via
flink_tpu.state.keygroups) — the same contract that makes checkpoints
re-shardable.

Scaling contract (SURVEY.md §2.9): shard count == mesh size == the
"parallelism" of the keyed operator; max_parallelism == number of key groups.
Cross-shard communication: none during scatter (records are bucketed to their
owner on the host, the device_put with a sharded layout IS the shuffle);
window fire is shard-local because every key's slices live on one shard
(keyed state locality, same as the reference). The collectives
(all_to_all/psum in flink_tpu.parallel.shuffle) appear when chaining keyed
stages or doing global two-phase aggregation.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.ops.segment_ops import (
    SCATTER_METHOD,
    MERGE_FN,
    pad_bucket_size,
    sticky_bucket,
)
from flink_tpu.parallel.mesh import KEY_AXIS
from flink_tpu.parallel.shuffle import bucket_by_shard, shard_records
from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.slot_table import HostSlotIndex
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.bookkeeping import SliceBookkeeper
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD


# Compiled step programs cached by (mesh devices, aggregate layout) so
# repeated engines (warmup + measured runs, restarted jobs) share executables.
_STEP_CACHE: Dict[tuple, tuple] = {}


class MeshWindowEngine:
    """Windowed keyed aggregation sharded over a 1-D device mesh."""

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        mesh: Mesh,
        capacity_per_shard: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        fire_projector=None,
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        #: host-side (cross-shard) fired-row reduction; the single-device
        #: engine fuses this into the fire kernel, here it runs after the
        #: per-shard results are assembled (the per-shard transfer is
        #: already bounded by the fire bucket)
        self.fire_projector = fire_projector
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self.capacity = max(int(capacity_per_shard), 1024)
        self.max_parallelism = max_parallelism
        self.allowed_lateness = allowed_lateness
        if max_parallelism < self.P:
            raise ValueError(
                f"max_parallelism {max_parallelism} < mesh size {self.P}")

        from flink_tpu.state.slot_table import make_slot_index

        # growable per-shard indexes: hot-key skew concentrating (key,
        # slice) pairs on one shard grows the table instead of killing the
        # job (SURVEY hard-part (e)); device arrays stay uniform [P, cap]
        # sized to the LARGEST shard index (SPMD shape requirement)
        self.indexes = [
            make_slot_index(
                self.capacity, growable=True,
                on_grow=lambda old, new: self._shard_index_grew(new))
            for _ in range(self.P)
        ]
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._replicated = NamedSharding(mesh, P())
        self.accs: Tuple[jnp.ndarray, ...] = tuple(
            jax.device_put(
                jnp.full((self.P, self.capacity), leaf.identity,
                         dtype=leaf.dtype),
                self._sharding)
            for leaf in agg.leaves
        )
        self._build_steps()
        # window lifecycle metadata is global: watermarks and window ends are
        # aligned across shards
        self.book = SliceBookkeeper(assigner, allowed_lateness)
        # incremental-snapshot bookkeeping, the mesh form of
        # SlotTable._dirty: a [P, capacity] host bitmap of slots touched
        # since the last snapshot + namespaces freed since (tombstones)
        self._dirty = np.zeros((self.P, self.capacity), dtype=bool)
        self._freed_ns: List[int] = []
        self._gather_bucket = 0

    @property
    def late_records_dropped(self) -> int:
        return self.book.late_records_dropped

    # -------------------------------------------------------- jitted programs

    def _build_steps(self) -> None:
        (self._scatter_step, self._fire_step, self._reset_step,
         self._gather_step) = build_mesh_steps(self.mesh, self.agg)

    def _shard_index_grew(self, new_capacity: int) -> None:
        """One shard's index outgrew the device column count: widen the
        [P, capacity] arrays (all shards — SPMD shapes are uniform; the
        other shards' indexes keep their smaller capacities and simply
        address a prefix)."""
        if new_capacity <= self.capacity:
            return
        old = self.capacity
        self.capacity = new_capacity
        grown = []
        for a, leaf in zip(self.accs, self.agg.leaves):
            host = np.asarray(a)
            padded = np.full((self.P, new_capacity), leaf.identity,
                             dtype=leaf.dtype)
            padded[:, :old] = host
            grown.append(jax.device_put(jnp.asarray(padded),
                                        self._sharding))
        self.accs = tuple(grown)
        dirty = np.zeros((self.P, new_capacity), dtype=bool)
        dirty[:, :old] = self._dirty
        self._dirty = dirty


    def _put_sharded(self, host_block: np.ndarray) -> jnp.ndarray:
        return jax.device_put(host_block, self._sharding)

    # ---------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        key_ids = batch.key_ids
        slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
        live = self.book.live_mask(slice_ends)
        if live is not None:
            key_ids, slice_ends = key_ids[live], slice_ends[live]
            batch = batch.filter(live)
            if len(batch) == 0:
                return
        self.book.register_slices(slice_ends)

        # route to owning shard, bucket into [P, B] blocks
        shards = shard_records(key_ids, self.P, self.max_parallelism)
        values = self.agg.map_input(batch)
        in_leaves = self.agg.input_leaves
        counts, blocked, order = bucket_by_shard(
            shards, self.P,
            columns=[key_ids, slice_ends,
                     *[np.asarray(v, dtype=l.dtype)
                       for v, l in zip(values, in_leaves)]],
            fills=[0, 0, *[l.identity for l in in_leaves]],
        )
        key_block, ns_block = blocked[0], blocked[1]
        value_blocks = blocked[2:]

        # per-shard slot assignment (host)
        B = key_block.shape[1]
        slot_block = np.zeros((self.P, B), dtype=np.int32)
        for p in range(self.P):
            c = int(counts[p])
            if c:
                slot_block[p, :c] = self.indexes[p].lookup_or_insert(
                    key_block[p, :c], ns_block[p, :c])
                self._dirty[p, slot_block[p, :c]] = True

        self.accs = self._scatter_step(
            self.accs,
            self._put_sharded(slot_block),
            tuple(self._put_sharded(v) for v in value_blocks),
        )

    # ------------------------------------------------------------------ fire

    def on_watermark(self, watermark: int) -> List[RecordBatch]:
        out: List[RecordBatch] = []
        while True:
            w_end = self.book.next_window(watermark)
            if w_end is None:
                break
            batch = self._fire_window(w_end)
            if batch is not None and len(batch) > 0:
                out.append(batch)
            self.book.mark_fired(w_end)
        expired = self.book.expired_slices(watermark)
        if expired:
            self._free_slices(expired)
        return out

    def _fire_window(self, window_end: int) -> Optional[RecordBatch]:
        slice_ends = self.assigner.slice_ends_for_window(window_end)
        k = len(slice_ends)
        per_shard_mats: List[np.ndarray] = []
        per_shard_keys: List[np.ndarray] = []
        w_max = 0
        for p in range(self.P):
            idx = self.indexes[p]
            chunks = [(i, idx.slots_for_namespace(se))
                      for i, se in enumerate(slice_ends)]
            chunks = [(i, s) for i, s in chunks if len(s) > 0]
            if not chunks:
                per_shard_mats.append(np.zeros((0, k), dtype=np.int32))
                per_shard_keys.append(np.empty(0, dtype=np.int64))
                continue
            all_slots = np.concatenate([s for _, s in chunks])
            all_sidx = np.concatenate(
                [np.full(len(s), i, dtype=np.int32) for i, s in chunks])
            all_keys = idx.slot_key[all_slots]
            keys, inv = np.unique(all_keys, return_inverse=True)
            mat = np.zeros((len(keys), k), dtype=np.int32)
            mat[inv, all_sidx] = all_slots
            per_shard_mats.append(mat)
            per_shard_keys.append(keys)
            w_max = max(w_max, len(keys))
        if w_max == 0:
            return None
        W = sticky_bucket(w_max, getattr(self, "_fire_bucket", 0), minimum=64)
        self._fire_bucket = W
        sm = np.zeros((self.P, W, k), dtype=np.int32)
        for p, mat in enumerate(per_shard_mats):
            sm[p, : len(mat)] = mat
        results = {name: np.asarray(arr)
                   for name, arr in self._fire_step(
                       self.accs, self._put_sharded(sm)).items()}
        # assemble host batch
        key_cols: List[np.ndarray] = []
        res_cols: Dict[str, List[np.ndarray]] = {n: [] for n in results}
        for p in range(self.P):
            m = len(per_shard_keys[p])
            if m == 0:
                continue
            key_cols.append(per_shard_keys[p])
            for name, arr in results.items():
                res_cols[name].append(arr[p][:m])
        keys = np.concatenate(key_cols)
        merged = {name: np.concatenate(chunks)
                  for name, chunks in res_cols.items()}
        if self.fire_projector is not None:
            keys, merged = self.fire_projector.project_host(keys, merged)
        m = len(keys)
        cols = {
            KEY_ID_FIELD: keys,
            WINDOW_START_FIELD: np.full(
                m, self.assigner.window_start(window_end), dtype=np.int64),
            WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
            TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
        }
        cols.update(merged)
        return RecordBatch(cols)

    def _free_slices(self, ends: List[int]) -> None:
        f_max = 0
        freed: List[Optional[np.ndarray]] = []
        self._freed_ns.extend(int(e) for e in ends)
        for p in range(self.P):
            slots = self.indexes[p].free_namespaces(ends)
            freed.append(slots)
            if slots is not None:
                self._dirty[p, slots] = False
                f_max = max(f_max, len(slots))
        if f_max == 0:
            return
        F = sticky_bucket(f_max, getattr(self, "_reset_bucket", 0))
        self._reset_bucket = F
        block = np.zeros((self.P, F), dtype=np.int32)
        for p, slots in enumerate(freed):
            if slots is not None:
                block[p, : len(slots)] = slots
        self.accs = self._reset_step(self.accs, self._put_sharded(block))

    # ---------------------------------------------------------- point query

    def query_windows(self, key_id: int) -> Dict[int, Dict[str, float]]:
        """Queryable-state point lookup, mesh form: route the key to its
        owning shard (the same key-group formula the data path uses), probe
        that shard's host index, gather its slice accumulators off the
        device, and compose window results on host (slice sharing, as
        SlotTable.query_windows). Read-only."""
        shard = int(shard_records(
            np.asarray([key_id], dtype=np.int64), self.P,
            self.max_parallelism)[0])
        idx = self.indexes[shard]
        live_ns = np.asarray([int(n) for n in idx.namespaces],
                             dtype=np.int64)
        if len(live_ns) == 0:
            return {}
        keys = np.full(len(live_ns), int(key_id), dtype=np.int64)
        slots = idx.lookup(keys, live_ns)
        hit = slots >= 0
        if not hit.any():
            return {}
        slice_slot = {int(n): int(s)
                      for n, s, h in zip(live_ns, slots, hit) if h}
        assigner = self.assigner
        windows = sorted({
            int(w)
            for se in slice_slot
            for w in assigner.window_ends_for_slice(se)})
        k = max(len(assigner.slice_ends_for_window(w)) for w in windows)
        # pad W to a bucket (slot 0 = reserved identity) — exact shapes
        # would recompile fire_step per distinct live-window count
        W = pad_bucket_size(len(windows), minimum=64)
        sm = np.zeros((self.P, W, k), dtype=np.int32)
        for i, w in enumerate(windows):
            for j, se in enumerate(assigner.slice_ends_for_window(w)):
                sm[shard, i, j] = slice_slot.get(int(se), 0)
        results = self._fire_step(self.accs, self._put_sharded(sm))
        return {w: {name: np.asarray(col)[shard][i].item()
                    for name, col in results.items()}
                for i, w in enumerate(windows)}

    # -------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        """Logical snapshot merged over shards, re-shardable by key group.

        mode: "full" (new incremental base), "delta" (dirty rows +
        tombstones only), "savepoint" (full, preserving dirty tracking) —
        the same contract as SliceSharedWindower.snapshot, so mesh and
        single-device checkpoints are mutually restorable."""
        if mode == "delta":
            return {"table": self._snapshot_delta(), **self.book.snapshot()}
        accs_host = [np.asarray(a) for a in self.accs]
        parts = []
        for p in range(self.P):
            idx = self.indexes[p]
            used = idx.used_slots()
            key_ids = idx.slot_key[used]
            parts.append({
                "key_id": key_ids,
                "namespace": idx.slot_ns[used],
                "key_group": assign_key_groups(key_ids, self.max_parallelism),
                **{f"leaf_{i}": accs_host[i][p][used]
                   for i in range(len(self.accs))},
            })
        merged = {
            k: np.concatenate([pt[k] for pt in parts]) for k in parts[0]
        } if parts else {}
        if mode != "savepoint":
            self._dirty[:] = False
            self._freed_ns.clear()
        return {"table": merged, **self.book.snapshot()}

    def _snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Dirty rows gathered off the device in ONE sharded program +
        freed-namespace tombstones (same format as SlotTable.snapshot_delta)."""
        per_shard = []
        g_max = 0
        for p in range(self.P):
            used = self.indexes[p].slot_used
            dirty = np.nonzero(self._dirty[p][:len(used)]
                               & used)[0].astype(np.int32)
            per_shard.append(dirty)
            g_max = max(g_max, len(dirty))
        freed = np.asarray(sorted(set(self._freed_ns)), dtype=np.int64)
        if g_max == 0:
            empty = {f"leaf_{i}": np.empty(0, dtype=l.dtype)
                     for i, l in enumerate(self.agg.leaves)}
            out = {
                "__delta__": np.asarray(True),
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "key_group": np.empty(0, dtype=np.int32),
                "freed_namespaces": freed,
                **empty,
            }
        else:
            G = sticky_bucket(g_max, self._gather_bucket)
            self._gather_bucket = G
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, dirty in enumerate(per_shard):
                block[p, :len(dirty)] = dirty
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            leaves_host = [np.asarray(g) for g in gathered]
            key_cols, ns_cols = [], []
            leaf_cols = [[] for _ in leaves_host]
            for p, dirty in enumerate(per_shard):
                m = len(dirty)
                if m == 0:
                    continue
                idx = self.indexes[p]
                key_cols.append(idx.slot_key[dirty])
                ns_cols.append(idx.slot_ns[dirty])
                for i, lh in enumerate(leaves_host):
                    leaf_cols[i].append(lh[p][:m])
            key_ids = np.concatenate(key_cols)
            out = {
                "__delta__": np.asarray(True),
                "key_id": key_ids,
                "namespace": np.concatenate(ns_cols),
                "key_group": assign_key_groups(key_ids,
                                               self.max_parallelism),
                "freed_namespaces": freed,
                **{f"leaf_{i}": np.concatenate(cols)
                   for i, cols in enumerate(leaf_cols)},
            }
        self._dirty[:] = False
        self._freed_ns.clear()
        return out

    def restore(self, snap: Dict[str, object]) -> None:
        """Restore, re-sharding by key group (works across mesh sizes)."""
        table = snap["table"]
        key_ids = np.asarray(table["key_id"], dtype=np.int64)
        namespaces = np.asarray(table["namespace"], dtype=np.int64)
        leaves = [np.asarray(table[f"leaf_{i}"])
                  for i in range(len(self.agg.leaves))]
        if len(key_ids):
            shards = shard_records(key_ids, self.P, self.max_parallelism)
            # resolve ALL slots first: inserts may grow the table
            # (on_grow widens self.accs / self.capacity), so the host
            # copy must be taken only after growth has settled
            per_shard_slots: Dict[int, np.ndarray] = {}
            for p in range(self.P):
                mask = shards == p
                if mask.any():
                    per_shard_slots[p] = self.indexes[p].lookup_or_insert(
                        key_ids[mask], namespaces[mask])
            accs_host = [np.array(a) for a in self.accs]
            for p, slots in per_shard_slots.items():
                mask = shards == p
                for acc, vals in zip(accs_host, leaves):
                    acc[p][slots] = vals[mask]
            self.accs = tuple(
                jax.device_put(jnp.asarray(a), self._sharding)
                for a in accs_host)
        # restored state IS the new incremental base
        self._dirty[:] = False
        self._freed_ns.clear()
        self.book.restore(snap)


def build_mesh_steps(mesh: Mesh, agg: AggregateFunction):
    """(scatter, fire, reset, gather) shard_map step programs over a
    [P, capacity] sharded slot table — shared by the mesh window and mesh
    session engines (cached per (devices, aggregate layout))."""
    cache_key = (tuple(d.id for d in mesh.devices.flat), agg.cache_key())
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    leaves = agg.leaves
    methods = tuple(SCATTER_METHOD[l.reduce] for l in agg.leaves)
    merges = tuple(MERGE_FN[l.reduce] for l in agg.leaves)
    idents = tuple(l.identity for l in agg.leaves)
    finish = agg.finish
    n_leaves = len(agg.leaves)
    n_inputs = len(agg.input_leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_step(accs, slots, values):
        # accs: ([P, cap], ...) sharded; slots: [P, B]; values: one
        # [P, B] block per *input* leaf (const leaves broadcast on device)
        def local(*args):
            accs_l = args[:n_leaves]          # each [1, cap]
            slots_l = args[n_leaves]          # [1, B]
            vals_l = iter(args[n_leaves + 1:])  # each [1, B]
            # .at[...].op() returns the full [1, cap] block
            out = []
            for a, m, l in zip(accs_l, methods, leaves):
                if l.const is not None:
                    # padded lanes target identity slot 0 — keep it pure
                    v = jnp.where(
                        slots_l[0] == 0,
                        jnp.asarray(l.identity, dtype=l.dtype),
                        jnp.asarray(l.const, dtype=l.dtype))
                else:
                    v = next(vals_l)[0]
                out.append(getattr(a.at[0, slots_l[0]], m)(v))
            return tuple(out)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1 + n_inputs),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots, *values)

    # hoisted so the jitted closures capture only plain values, never
    # an engine (the step cache outlives engines; a capture would pin
    # the first engine's device arrays in memory for the process)
    names = sorted(agg.output_names)

    @jax.jit
    def fire_step(accs, slot_matrix):
        # slot_matrix: [P, W, k] sharded -> result cols each [P, W]
        def local(*args):
            accs_l = args[:n_leaves]          # [1, cap]
            sm = args[n_leaves][0]            # [W, k]
            merged = tuple(
                m(a[0][sm], axis=1) for a, m in zip(accs_l, merges))
            out = finish(merged)              # dict name -> [W]
            return tuple(out[name][None] for name in names)

        outs = jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * len(names),
        )(*accs, slot_matrix)
        return dict(zip(names, outs))

    @partial(jax.jit, donate_argnums=(0,))
    def reset_step(accs, slots):
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            return tuple(
                a.at[0, slots_l[0]].set(i)
                for a, i in zip(accs_l, idents)
            )

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots)

    @jax.jit
    def gather_step(accs, slots):
        # slots: [P, G] sharded -> per-leaf [P, G] raw accumulator
        # values (delta-snapshot / point-query readback)
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            return tuple(a[0][slots_l[0]][None] for a in accs_l)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots)

    _STEP_CACHE[cache_key] = steps = (scatter_step, fire_step,
                                      reset_step, gather_step)
    return steps

