"""Mesh-sharded windowed keyed aggregation.

The multi-device form of ``flink_tpu.windowing.windower.SliceSharedWindower``:
state lives in ``[num_shards, capacity]`` device arrays with the leading axis
sharded over the key-group mesh axis; every step (scatter / fire / reset) is
ONE jitted ``shard_map`` program over the whole mesh. Records are routed to
their owning shard by the reference's key-group formula
(reference: KeyGroupRangeAssignment.java:124-127 via
flink_tpu.state.keygroups) — the same contract that makes checkpoints
re-shardable.

Scaling contract (SURVEY.md §2.9): shard count == mesh size == the
"parallelism" of the keyed operator; max_parallelism == number of key groups.
Cross-shard communication: none during scatter (records are bucketed to their
owner on the host, the device_put with a sharded layout IS the shuffle);
window fire is shard-local because every key's slices live on one shard
(keyed state locality, same as the reference). The collectives
(all_to_all/psum in flink_tpu.parallel.shuffle) appear when chaining keyed
stages or doing global two-phase aggregation.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.chaos import injection as chaos
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.observe import flight_recorder as flight
from flink_tpu.ops.segment_ops import (
    SCATTER_METHOD,
    MERGE_FN,
    pad_bucket_size,
    sticky_bucket,
)
from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.parallel.shuffle import (
    bucket_by_shard,
    build_exchange_scatter,
    shard_records,
    stage_device_exchange,
)
from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.slot_table import HostSlotIndex, resolve_slot_hints
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.bookkeeping import SliceBookkeeper
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD


# Compiled step programs cached by (mesh devices, aggregate layout) so
# repeated engines (warmup + measured runs, restarted jobs) AND
# concurrent jobs on one mesh share executables — the cache lives in the
# tenancy layer's SharedProgramCache (per-job hit/miss attribution; see
# flink_tpu/tenancy/program_cache.py).
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

# Tiny non-donated slice dispatched after everything queued so far: its
# readiness proves the device consumed every earlier host buffer (the
# mesh form of SlotTable.make_fence). jit caches per input sharding.
_FENCE_STEP = jax.jit(lambda a: a[:1, :1])


class _DeviceSpan:
    """Times a device-interaction block into the owner's
    ``device_inline_s`` (see MeshSpillSupport._init_pipeline)."""

    __slots__ = ("_owner", "_t0")

    def __init__(self, owner) -> None:
        self._owner = owner

    def __enter__(self) -> "_DeviceSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        self._owner.device_inline_s += dt
        # same section, same number, into the flight-recorder timeline —
        # the bench breakdown and a Perfetto trace read ONE measurement
        flight.instant("device.dispatch", duration_s=dt)


class MeshSpillSupport:
    """Per-shard spill tier shared by the mesh window and mesh session
    engines: LRU namespace eviction under a per-device slot budget, batched
    reload, and the bookkeeping both engines need. Hosts must provide
    ``P, indexes, spills, agg, accs, _dirty, _ns_touch, _put_sharded`` and
    the ``_gather_step/_reset_step/_put_step`` programs."""

    max_device_slots: int = 0
    #: (MemoryManager, owner) — managed accounting of the [P, capacity]
    #: device footprint (flink_tpu/core/memory.py); None = unmanaged
    _memory = None
    #: the ingest data plane: "device" routes records through the fused
    #: in-program exchange (one flat device_put + all_to_all + scatter
    #: in ONE compiled program), "host" through the [P, B] bucketing +
    #: sharded device_put (the explicit fallback — see parallel.shuffle)
    shuffle_mode: str = "device"
    #: the (hosts, local) factorization of the mesh, when it spans
    #: hosts/processes: device-mode ingest then runs the TWO-LEVEL
    #: ICI/DCN exchange (parallel/exchange2.py) instead of the flat
    #: single-axis program; None (or a 1-host topology) keeps the flat
    #: fast path — every engine on a single-process mesh is unchanged
    host_topology = None
    #: intra- vs cross-host row accounting for the two-level exchange
    #: (smoke vacuity guard + the NOTES traffic split)
    _exchange2_traffic = None
    #: live non-contiguous shard->key-group assignment installed by
    #: reassign_key_groups(); None = the contiguous formula (the common
    #: case — every routing site goes through _route so a rebalanced
    #: table threads the whole data plane without per-site branching)
    _assignment = None
    #: hot-range rebalances applied (counterpart of reshards_completed)
    rebalances_completed: int = 0
    #: report dict of the most recent reassign_key_groups()
    last_rebalance = None

    @staticmethod
    def _check_shuffle_mode(mode: str) -> str:
        if mode not in ("host", "device"):
            raise ValueError(
                f"shuffle_mode must be 'host' or 'device', got {mode!r}")
        return mode

    def _route(self, key_ids) -> np.ndarray:
        """key id -> owning shard, THE engine routing decision: the
        contiguous ``shard_records`` formula, or the live assignment
        table after a hot-range rebalance. Every internal routing site
        (ingest, merges, fires, queries, spill restore, checkpoint
        restore, handoff redistribution) goes through here so an
        installed table re-routes the whole data plane at once."""
        if self._assignment is not None:
            return self._assignment.shard_of_keys(
                key_ids, self.max_parallelism).astype(np.int64)
        return shard_records(key_ids, self.P, self.max_parallelism,
                             self.key_group_range)

    def _set_host_topology(self, topology) -> None:
        if topology is not None:
            topology.check_covers(self.P)
        self.host_topology = topology
        if topology is not None and self._exchange2_traffic is None:
            from flink_tpu.parallel.exchange2 import ExchangeTraffic

            self._exchange2_traffic = ExchangeTraffic()

    def _two_level_active(self) -> bool:
        from flink_tpu.parallel.exchange2 import two_level_active

        return two_level_active(self.host_topology, self.shuffle_mode)

    def exchange2_traffic(self) -> Dict[str, int]:
        """Two-level exchange traffic split (zeros when flat)."""
        from flink_tpu.parallel.exchange2 import ExchangeTraffic

        return ExchangeTraffic.dict_of(self._exchange2_traffic)

    def _reserve_rows(self, rows: int) -> None:
        if self._memory is not None:
            manager, owner = self._memory
            manager.reserve(owner, rows * sum(
                np.dtype(leaf.dtype).itemsize
                for leaf in self.agg.leaves))

    def release_memory(self) -> None:
        if self._memory is not None:
            manager, owner = self._memory
            manager.release_all(owner)

    def _init_spill(self, spill_dir: Optional[str],
                    spill_host_max_bytes: int) -> None:
        from flink_tpu.state.paged_spill import PagedSpillMap
        from flink_tpu.state.slot_table import SpillTier

        #: kept for reshard(): the rebuilt mesh plane re-creates its
        #: spill tiers from the same configuration
        self._spill_dir = spill_dir
        self._spill_host_max_bytes = spill_host_max_bytes
        #: one spill tier per shard (keys move between shards only
        #: through reshard(), so spilled namespaces are shard-local like
        #: the device rows)
        self.spills = [
            SpillTier(
                f"{spill_dir.rstrip('/')}/shard-{p}" if spill_dir else None,
                spill_host_max_bytes // self.P
                if spill_host_max_bytes else 0)
            for p in range(self.P)
        ]
        self._ns_touch: List[Dict[int, int]] = [{} for _ in range(self.P)]
        self._touch_clock = 0
        self._reload_bucket = 0
        #: namespace-layout spill traffic (the paged layout counts on its
        #: PagedSpillMaps instead); survives reshard — a job-lifetime
        #: counter must not reset when the mesh resizes
        if not hasattr(self, "_ns_counters"):
            self._ns_counters = PagedSpillMap.zero_counters()
        self._init_pipeline(getattr(self, "max_dispatch_ahead", 2))

    # ------------------------------------------------- host/device pipelining

    def _init_pipeline(self, depth: int) -> None:
        """Double-buffered dispatch-ahead: the host preps (and buckets)
        batch k+1 while the device still runs batch k. ``depth`` bounds
        how many dispatched-but-unfenced batches may be in flight; the
        shuffle pool rotates the same number of buffer generations, so a
        staging buffer is only rewritten after the dispatch that read it
        has provably completed (fence discipline — device_put from a
        host buffer is NOT synchronous on a real accelerator link)."""
        from collections import deque

        from flink_tpu.parallel.shuffle import ShuffleBufferPool

        self._pipeline_depth = max(int(depth or 1), 1)
        self._shuffle_pool = ShuffleBufferPool(
            generations=self._pipeline_depth)
        self._dispatch_fences = deque()
        #: wall seconds the host spent BLOCKED on dispatch fences (the
        #: in-flight device work the pipeline could not hide) — the
        #: bench reads this to attribute fence waits to device time
        #: instead of host prep; survives reshard like the counters
        if not hasattr(self, "pipeline_wait_s"):
            self.pipeline_wait_s = 0.0
        #: wall seconds spent INSIDE device interactions on the ingest
        #: path (H2D puts, the fused exchange / scatter / merge / put
        #: dispatches, eviction gathers + their D2H reads). On an
        #: async accelerator link these overlap host prep; on the CPU
        #: backend they execute inline, so the bench subtracts them
        #: from process_batch wall time to report genuine host prep.
        if not hasattr(self, "device_inline_s"):
            self.device_inline_s = 0.0
        #: monotonically increasing per-engine batch sequence — the
        #: flight recorder's batch_id attribution (survives reshard)
        if not hasattr(self, "_flight_batch"):
            self._flight_batch = 0

    def _flight_ingest(self):
        """Open the ``batch.ingest`` span for one ``process_batch`` and
        advance the engine's batch sequence (sub-spans and instants
        opened below it inherit the batch id from the ambient thread
        context)."""
        self._flight_batch += 1
        return flight.ingest_span(self._flight_batch)

    def _device_span(self) -> "_DeviceSpan":
        """Context manager accumulating into ``device_inline_s`` —
        a slotted object, not a per-call generator (this sits on the
        per-batch path the host-prep gate measures)."""
        return _DeviceSpan(self)

    # ------------------------------------------------------------- watchdog

    #: device watchdog (runtime/watchdog.py) — None keeps every hook a
    #: single attribute check (the default; harness/executor attach one)
    _watchdog = None

    def attach_watchdog(self, wd) -> None:
        """Wrap this engine's device interactions (dispatch fences,
        eviction/fire harvests, batched device_get reads, serving
        lookups) in the watchdog's deadline-tracked sections, and run
        its shard-health probe at batch boundaries."""
        self._watchdog = wd
        if wd is not None:
            wd.rebind(self.P,
                      [d.id for d in self.mesh.devices.flat])
            # host-granular escalation needs the (hosts, local) map
            wd.set_topology(self.host_topology)

    def _wd_section(self, op: str, shard: int = -1):
        wd = self._watchdog
        if wd is None:
            from flink_tpu.runtime.watchdog import NULL_SECTION

            return NULL_SECTION
        return wd.section(op, shard)

    def _wd_boundary(self) -> None:
        """Batch-boundary health probe: the one point a shard may be
        DECLARED dead (engine state is consistent at a known source
        position here — see watchdog.boundary_probe)."""
        wd = self._watchdog
        if wd is not None:
            wd.boundary_probe()

    def _ingest_subbatch(self, batch) -> None:
        """Recursive ingest of a SPLIT sub-batch (working-set bounding):
        the watchdog is detached for the inner call — a shard declared
        dead between sub-batches would leave the step half-absorbed on
        the survivors, which is not a consistent failover point. The
        boundary probe stays at the OUTER batch boundary."""
        wd = self._watchdog
        self._watchdog = None
        try:
            self.process_batch(batch)
        finally:
            self._watchdog = wd

    def _harvest_get(self, tree, op: str = "fire_harvest"):
        """The watchdog-sectioned form of the batched-D2H harvest (ONE
        ``jax.device_get`` per harvest point — the TRC01 discipline)."""
        with flight.span("fire.harvest"), self._wd_section(op):
            return jax.device_get(tree)

    # ---------------------------------------------------- read replica
    # (tenancy/replica.py — the boundary-published serving plane)

    #: armed by the tenancy layer (session cluster / tests); None keeps
    #: every hook a single attribute check on the ingest path
    _replica = None
    #: set where the replica's shadow of the slot metadata goes stale
    #: wholesale (restore, reshard, shard loss) — the next publish
    #: rebuilds the plane and republishes every resident row
    _rep_rebuild = False

    def arm_replica(self, plane=None):
        """Attach (or build) the read-replica plane this engine
        publishes into at watermark boundaries. Must run on the task
        thread (single-owner), before or between batches."""
        from flink_tpu.tenancy.replica import ReplicaPlane

        if plane is None:
            plane = ReplicaPlane(self.mesh, self.agg.leaves,
                                 self.capacity)
        plane.warm_tiers()
        self._replica = plane
        self._rep_cold_pending: Dict[int, list] = {}
        self._rep_rebuild = True
        return plane

    def _rep_note_cold(self, p: int, keys, nss) -> None:
        """Record rows leaving residency (evictions) so a row created
        AND evicted within one publish interval still reaches the
        replica index as a cold entry at the next boundary."""
        if self._replica is not None:
            self._rep_cold_pending.setdefault(p, []).append(
                (np.asarray(keys, dtype=np.int64).copy(),
                 np.asarray(nss, dtype=np.int64).copy()))

    def _rep_mark(self, p: int, slots) -> None:
        """Note value-changing scatters for the next publish delta
        (residency/identity changes are derived by the publish diff
        instead — see _publish_replica). While a rebuild is pending
        (reshard/restore/growth changed the plane shape under the
        shadow) marks are moot — the rebuild republishes everything."""
        rep = self._replica
        if rep is not None and not self._rep_rebuild \
                and not rep.needs_rebuild(self.P, self.capacity):
            rep.mark_dirty(p, slots)

    def _rep_extra(self, p: int, keys: np.ndarray,
                   nss: np.ndarray):
        """Per-row adapter payload published with the index entries
        (sessions: the session END; windows: none — the namespace IS
        the slice end)."""
        return None

    def _rep_publish_split(self, p: int, keys: np.ndarray,
                           nss: np.ndarray):
        """Hook: ``(drop_mask, cold_mask)`` over the publish upserts, or
        None (default — publish everything resident). The session
        engine's hot-key splitting uses it to keep PARTIAL rows out of
        the serving index: salted sub-rows are dropped outright (their
        synthetic keys are never looked up), and a split key's main row
        is entered COLD so the lookup routes through ``cold_fetch`` to
        the live engine's combined fold — a split key still answers one
        lookup, with the full value."""
        return None

    def _rep_probe_cold(self, p: int, keys: np.ndarray,
                        nss: np.ndarray) -> np.ndarray:
        """For pairs that left the resident set since the last publish:
        True = the row serves from the page tier (evicted), False =
        freed (fired/expired — drop from the index). Namespace-layout
        default: a namespace present in the shard's spill tier is cold
        (eviction moves whole namespaces)."""
        nsset = set(int(x) for x in self.spills[p].namespaces) \
            if self._spill_active else set()
        return np.asarray([int(ns) in nsset for ns in nss], dtype=bool)

    def _publish_replica(self, watermark: int) -> None:
        """Publish the boundary delta into the replica plane: diff the
        engine's per-shard slot metadata against the replica's shadow
        (plus the scatter-site dirty marks), hand the changed slots to
        ONE device-to-device copy program, and seal the next
        generation. Runs at the END of on_watermark — the fires and
        frees of this boundary are already applied, so the sealed view
        is exactly the engine state a checkpoint cut here would
        capture."""
        rep = self._replica
        if rep is None:
            return
        if rep.min_interval_s and not self._rep_rebuild:
            s = rep.sealed
            if s is not None and (time.monotonic() - s.published_at
                                  < rep.min_interval_s):
                # batch this boundary into the next publish: the dirty
                # marks keep accumulating, the diff/copy cost is paid
                # once per interval, and the cache invalidation rate is
                # bounded (staleness <= the interval, by construction)
                return
        with flight.span("serving.replica_publish",
                         watermark=int(watermark)):
            include_spilled = False
            if self._rep_rebuild or rep.needs_rebuild(self.P,
                                                      self.capacity):
                rep.rebuild(self.mesh, self.capacity)
                rep.warm_tiers()
                self._rep_cold_pending = {}
                self._rep_rebuild = False
                # the rebuild's full republish covers resident rows;
                # rows already cold (restored/re-homed pages) must
                # re-enter the index too — enumerated below
                include_spilled = self._spill_active
            per_shard = {}
            for p in range(self.P):
                idx = self.indexes[p]
                used = idx.slot_used
                L = len(used)
                cur_used = np.asarray(used[:L], dtype=bool)
                cur_key = np.asarray(idx.slot_key[:L])
                cur_ns = np.asarray(idx.slot_ns[:L])
                r_used = rep.rep_used[p][:L]
                r_key = rep.rep_key[p][:L]
                r_ns = rep.rep_ns[p][:L]
                moved = (cur_key != r_key) | (cur_ns != r_ns)
                ident_change = cur_used & (~r_used | moved)
                up = np.nonzero(ident_change
                                | (rep.rep_dirty[p][:L] & cur_used))[0]
                gone = np.nonzero(r_used & (~cur_used | moved))[0]
                cold: List[Tuple[int, int]] = []
                freed: List[Tuple[int, int]] = []
                if len(gone):
                    g_keys = r_key[gone].copy()
                    g_ns = r_ns[gone].copy()
                    # a pair re-homed to a NEW slot is covered by its
                    # upsert there; only pairs no longer resident at
                    # all need the cold/freed split
                    miss = idx.lookup(g_keys, g_ns) < 0
                    if miss.any():
                        mk, mn = g_keys[miss], g_ns[miss]
                        is_cold = self._rep_probe_cold(p, mk, mn)
                        for j in range(len(mk)):
                            if is_cold[j]:
                                cold.append((int(mk[j]), int(mn[j]),
                                             None))
                            else:
                                freed.append((int(mk[j]), int(mn[j])))
                # rows created AND evicted since the last publish were
                # never resident at a boundary — the eviction sites
                # recorded them; enter them cold (skipping any that
                # reloaded back to residency, covered by the diff)
                pend = self._rep_cold_pending.get(p)
                if pend:
                    pk = np.concatenate([a for a, _ in pend])
                    pn = np.concatenate([b for _, b in pend])
                    nonres = idx.lookup(pk, pn) < 0
                    if nonres.any():
                        ck, cn = pk[nonres], pn[nonres]
                        still = self._rep_probe_cold(p, ck, cn)
                        cx = self._rep_extra(p, ck, cn)
                        for j in range(len(ck)):
                            if still[j]:
                                cold.append((
                                    int(ck[j]), int(cn[j]),
                                    None if cx is None else cx[j]))
                    # cleared after the publish SUCCEEDS (torn-publish
                    # re-derivability — see below)
                up_keys = cur_key[up].copy()
                up_ns = cur_ns[up].copy()
                split = self._rep_publish_split(p, up_keys, up_ns)
                if split is not None:
                    drop, coldm = split
                    if coldm.any():
                        cks, cns = up_keys[coldm], up_ns[coldm]
                        cx = self._rep_extra(p, cks, cns)
                        for j in range(len(cks)):
                            cold.append((int(cks[j]), int(cns[j]),
                                         None if cx is None else cx[j]))
                    keep = ~(drop | coldm)
                    if not keep.all():
                        up = up[keep]
                        up_keys = up_keys[keep]
                        up_ns = up_ns[keep]
                per_shard[p] = {
                    "up_slots": up.astype(np.int32),
                    "up_keys": up_keys,
                    "up_ns": up_ns,
                    "up_extra": self._rep_extra(p, up_keys, up_ns),
                    "cold": cold,
                    "freed": freed,
                    "fresh": bool(ident_change.any()),
                }
                per_shard[p]["_shadow"] = (L, cur_used, cur_key, cur_ns)
            if include_spilled:
                cold0 = per_shard[0]["cold"]
                for part in self._spill_snapshot_parts():
                    ck = np.asarray(part["key_id"], dtype=np.int64)
                    cn = np.asarray(part["namespace"], dtype=np.int64)
                    split = self._rep_publish_split(0, ck, cn)
                    if split is not None:
                        keep = ~split[0]  # spilled rows are already cold
                        ck, cn = ck[keep], cn[keep]
                    cx = self._rep_extra(0, ck, cn)
                    for j in range(len(ck)):
                        cold0.append((int(ck[j]), int(cn[j]),
                                      None if cx is None else cx[j]))
                if cold0:
                    per_shard[0]["fresh"] = True
            # the metadata shadow, dirty marks and pending cold events
            # update ONLY after the publish succeeds: a fault inside
            # the publish (serving.replica_publish chaos, a device
            # error) must leave the delta re-derivable — otherwise the
            # torn boundary's rows silently never reach the replica
            rep.publish(self.accs, per_shard, int(watermark))
            for p, d in per_shard.items():
                L, cur_used, cur_key, cur_ns = d.pop("_shadow")
                rep.rep_used[p][:L] = cur_used
                rep.rep_used[p][L:] = False
                rep.rep_key[p][:L] = cur_key
                rep.rep_ns[p][:L] = cur_ns
                rep.rep_dirty[p][:] = False
                self._rep_cold_pending[p] = []

    def make_fence(self):
        """A tiny non-donated device value enqueued AFTER everything
        dispatched so far — used by the engine's own dispatch-ahead
        bound and by the task loop's pipelining fences
        (runtime/operators.py)."""
        return _FENCE_STEP(self.accs[0])

    def _await_dispatch_slot(self) -> None:
        """Block until < depth dispatches are outstanding. MUST run
        before this batch's staging buffers are (re)written."""
        if len(self._dispatch_fences) < self._pipeline_depth:
            return
        t0 = time.perf_counter()
        with self._wd_section("fence_drain"):
            while len(self._dispatch_fences) >= self._pipeline_depth:
                # flint: disable=TRC01 -- the depth-bounded fence drain
                # IS the dispatch-ahead backpressure point: it blocks
                # only when the host ran a full pipeline depth ahead of
                # the device
                self._dispatch_fences.popleft().block_until_ready()
        dt = time.perf_counter() - t0
        self.pipeline_wait_s += dt
        flight.instant("device.fence_wait", duration_s=dt)

    def _push_dispatch_fence(self) -> None:
        # chaos: a fence failure mid-dispatch-ahead — the batch's device
        # work is enqueued but its completion proof is lost, which in a
        # real stack is a device reset/preemption: the engine dies here
        # with up to `depth` batches in flight (the hardest restore case)
        chaos.fault_point("mesh.dispatch_fence",
                          in_flight=len(self._dispatch_fences))
        # fence creation dispatches a (tiny) device program — an inline
        # device interaction, attributed as such for the host-prep gate
        with self._device_span(), self._wd_section("dispatch_fence"):
            self._dispatch_fences.append(self.make_fence())

    @property
    def _spill_active(self) -> bool:
        return self.max_device_slots > 0

    def _any_spilled(self, slice_ends) -> bool:
        return self._spill_active and any(
            int(se) in self.spills[p]
            for p in range(self.P) for se in slice_ends)

    def _touch(self, p: int, namespaces) -> None:
        self._touch_clock += 1
        clock = self._touch_clock
        touch = self._ns_touch[p]
        for ns in namespaces:
            touch[int(ns)] = clock

    def _make_headroom(self, p: int, needed: int, protect: set) -> None:
        while self.indexes[p].free_headroom() < needed:
            self._evict_cold(p, protect)

    def _reserve(self, p: int, keys: np.ndarray, nss: np.ndarray) -> None:
        """Ensure shard ``p`` can absorb the genuinely NEW (key, ns)
        pairs among (keys, nss): under ample headroom this is one cheap
        over-counting check; otherwise a read-only probe counts the
        misses and cold namespaces are evicted to make room, protecting
        the namespaces this batch touches."""
        if not self._spill_active:
            return
        from flink_tpu.state.slot_table import unique_pairs

        uk, un, _ = unique_pairs(np.asarray(keys, dtype=np.int64),
                                 np.asarray(nss, dtype=np.int64))
        if self.indexes[p].free_headroom() >= len(uk):
            return
        needed = int((self.indexes[p].lookup(uk, un) < 0).sum())
        if needed:
            self._make_headroom(
                p, needed, protect={int(x) for x in np.unique(un)})

    def _evict_cold(self, p: int, protect: set) -> None:
        """Evict shard ``p``'s least-recently-touched namespaces to its
        spill tier until a workable fraction of the shard's slots is free —
        one gather + one reset kernel for the whole eviction batch (the
        other shards' rows in the [P, G] blocks are identity no-ops)."""
        from flink_tpu.state.slot_table import SlotTableFullError

        idx = self.indexes[p]
        target_free = max(idx.capacity // 8, 1024)
        touch = self._ns_touch[p]
        candidates = sorted(
            (ns for ns in idx.namespaces if int(ns) not in protect),
            key=lambda ns: touch.get(int(ns), 0))
        if not candidates:
            raise SlotTableFullError(
                f"shard {p}: device slot budget exhausted and every "
                "namespace in the current batch is protected — raise "
                "state.slot-table.max-device-slots or reduce batch size")
        chosen: List[Tuple[int, np.ndarray]] = []
        freed = 0
        for ns in candidates:
            if freed >= target_free:
                break
            slots = idx.slots_for_namespace(int(ns))
            chosen.append((int(ns), slots))
            freed += len(slots)
        empty = [ns for ns, s in chosen if len(s) == 0]
        if empty:
            idx.free_namespaces(empty)
        chosen = [(ns, s) for ns, s in chosen if len(s) > 0]
        if not chosen:
            return
        all_slots = np.concatenate([s for _, s in chosen])
        n = len(all_slots)
        G = sticky_bucket(n, self._gather_bucket)
        self._gather_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        block[p, :n] = all_slots
        gathered = self._gather_step(self.accs, self._put_sharded(block))
        # ONE batched D2H read for all leaves (per-array np.asarray pays
        # one link round-trip per leaf — see runtime/pending.py)
        leaves_host = [g[p][:n]
                       for g in self._harvest_get(gathered,
                                                  "evict_harvest")]
        off = 0
        for ns, slots in chosen:
            m = len(slots)
            entry = {
                "key_id": np.asarray(idx.slot_key[slots]),
                **{f"leaf_{i}": leaves_host[i][off:off + m]
                   for i in range(len(leaves_host))},
            }
            self.spills[p].put(ns, entry,
                               dirty=bool(self._dirty[p, slots].any()))
            # replica: never-published rows going cold (see _evict_cohorts)
            self._rep_note_cold(p, entry["key_id"],
                                np.full(m, int(ns), dtype=np.int64))
            off += m
            self._ns_touch[p].pop(ns, None)
        self._ns_counters["pages_evicted"] += len(chosen)
        self._ns_counters["rows_evicted"] += n
        idx.free_namespaces([ns for ns, _ in chosen])
        self._dirty[p, all_slots] = False
        R = sticky_bucket(n, getattr(self, "_reset_bucket", 0))
        self._reset_bucket = R
        rb = np.zeros((self.P, R), dtype=np.int32)
        rb[p, :n] = all_slots
        self.accs = self._reset_step(self.accs, self._put_sharded(rb))

    def _ensure_resident(self, per_shard: Dict[int, np.ndarray]) -> None:
        """Reload any spilled namespaces among each shard's touched set
        back onto the device — ALL shards' reloads batch into one insert
        pass + ONE put kernel."""
        if not self._spill_active:
            return
        entries: Dict[int, List[Tuple[int, Dict[str, np.ndarray]]]] = {}
        rows: Dict[int, int] = {}
        for p, nss in per_shard.items():
            sp = self.spills[p]
            if len(sp) == 0:
                continue
            es = []
            for ns in nss:
                ns = int(ns)
                if ns in sp:
                    e = sp.pop(ns)
                    if e is not None and len(e["key_id"]):
                        es.append((ns, e))
            if es:
                entries[p] = es
                rows[p] = sum(len(e["key_id"]) for _, e in es)
        if not entries:
            return
        self._ns_counters["pages_reloaded"] += sum(
            len(es) for es in entries.values())
        self._ns_counters["rows_reloaded"] += sum(rows.values())
        # headroom first, for every shard (evictions dispatch their own
        # kernels; slots resolved after growth/eviction settle)
        for p, need in rows.items():
            self._make_headroom(
                p, need, protect={int(n) for n in per_shard[p]})
        B = sticky_bucket(max(rows.values()), self._reload_bucket)
        self._reload_bucket = B
        slot_block = np.zeros((self.P, B), dtype=np.int32)
        val_blocks = [np.full((self.P, B), l.identity, dtype=l.dtype)
                      for l in self.agg.leaves]
        for p, es in entries.items():
            keys = np.concatenate([
                np.asarray(e["key_id"], dtype=np.int64) for _, e in es])
            nss = np.concatenate([
                np.full(len(e["key_id"]), ns, dtype=np.int64)
                for ns, e in es])
            n = len(keys)
            slots = self.indexes[p].lookup_or_insert(keys, nss)
            slot_block[p, :n] = slots
            for i, l in enumerate(self.agg.leaves):
                # assemble straight into the staged block row (one
                # concatenate per leaf, no intermediate copy)
                np.concatenate(
                    [np.asarray(e[f"leaf_{i}"], dtype=l.dtype)
                     for _, e in es],
                    out=val_blocks[i][p, :n])
            # reloaded rows keep their dirtiness: rows dirty at spill time
            # have not been in any snapshot since
            was_dirty = np.concatenate([
                np.full(len(e["key_id"]),
                        bool(e.get("__was_dirty__", False)), dtype=bool)
                for _, e in es])
            self._dirty[p, slots] = was_dirty
            self._touch(p, [ns for ns, _ in es])
        self.accs = self._put_step(
            self.accs, self._put_sharded(slot_block),
            tuple(self._put_sharded(v) for v in val_blocks))

    def _drop_spilled(self, ends, freed_touch: bool = True) -> None:
        """Discard spilled namespaces (fully fired/expired elsewhere)."""
        if not self._spill_active:
            return
        for p in range(self.P):
            sp = self.spills[p]
            if len(sp):
                for e in ends:
                    if int(e) in sp:
                        sp.drop(int(e))
            if freed_touch:
                touch = self._ns_touch[p]
                for e in ends:
                    touch.pop(int(e), None)

    def _spill_snapshot_parts(self) -> List[Dict[str, np.ndarray]]:
        """Logical-snapshot rows for every spilled namespace. Paged
        entries (the mesh session engine) carry their own ``ns`` column
        and one entry spans many sessions; dead rows are dropped."""
        parts: List[Dict[str, np.ndarray]] = []
        pmaps = getattr(self, "_pmaps", None)
        for p in range(self.P):
            sp = self.spills[p]
            for ns in sp.namespaces:
                entry = sp.peek(int(ns))
                if entry is None:
                    continue
                ekeys = np.asarray(entry["key_id"], dtype=np.int64)
                if "ns" in entry:  # paged entry: per-row namespaces
                    rns = np.asarray(entry["ns"], dtype=np.int64)
                    # lazy tombstones: only rows still mapped to this
                    # page are logical state (paged_spill)
                    alive = pmaps[p].live_row_mask(int(ns), rns)
                    ekeys, rns = ekeys[alive], rns[alive]
                    sel = alive
                else:
                    rns = np.full(len(ekeys), int(ns), dtype=np.int64)
                    sel = slice(None)
                if len(ekeys) == 0:
                    continue
                parts.append({
                    "key_id": ekeys,
                    "namespace": rns,
                    "key_group": assign_key_groups(
                        ekeys, self.max_parallelism),
                    **{f"leaf_{i}": np.asarray(
                        entry[f"leaf_{i}"],
                        dtype=self.agg.leaves[i].dtype)[sel]
                       for i in range(len(self.agg.leaves))},
                })
        return parts

    def _spill_delta_append(self, out: Dict[str, np.ndarray]) -> None:
        """Append spilled-but-dirty namespaces to a delta snapshot and
        clear their dirtiness. For paged entries only the dirty ROWS of
        a dirty page travel (pages are immutable once spilled, so the
        per-row dirty column captured at eviction stays authoritative)."""
        if not self._spill_active:
            return
        pmaps = getattr(self, "_pmaps", None)
        for p in range(self.P):
            sp = self.spills[p]
            for ns in sp.dirty_namespaces():
                entry = sp.peek(int(ns))
                if entry is None:
                    continue
                ekeys = np.asarray(entry["key_id"], dtype=np.int64)
                if "ns" in entry:  # paged entry
                    rns_all = np.asarray(entry["ns"], dtype=np.int64)
                    # dirty AND live: a tombstoned row is resident again
                    # (its device copy travels) or freed
                    sel = (np.asarray(entry["dirty"], dtype=bool)
                           & pmaps[p].live_row_mask(int(ns), rns_all))
                    ekeys = ekeys[sel]
                    rns = rns_all[sel]
                else:
                    sel = slice(None)
                    rns = np.full(len(ekeys), int(ns), dtype=np.int64)
                if len(ekeys) == 0:
                    continue
                out["key_id"] = np.concatenate([out["key_id"], ekeys])
                out["namespace"] = np.concatenate([out["namespace"], rns])
                out["key_group"] = np.concatenate([
                    out["key_group"],
                    assign_key_groups(ekeys, self.max_parallelism)])
                for i, l in enumerate(self.agg.leaves):
                    out[f"leaf_{i}"] = np.concatenate([
                        out[f"leaf_{i}"],
                        np.asarray(entry[f"leaf_{i}"],
                                   dtype=l.dtype)[sel]])
            sp.clear_dirty()

    def _spill_restore_rows(self, key_ids: np.ndarray,
                            namespaces: np.ndarray,
                            leaves: List[np.ndarray]) -> None:
        """Spill-enabled restore: rows land in each shard's spill tier
        grouped by namespace and reload lazily on first access — a
        snapshot far larger than the HBM budget restores with bounded
        device memory (same contract as SlotTable.restore)."""
        shards = self._route(key_ids)
        for p in range(self.P):
            mask = shards == p
            if not mask.any():
                continue
            ns_p = namespaces[mask]
            keys_p = key_ids[mask]
            leaves_p = [l[mask] for l in leaves]
            order = np.argsort(ns_p, kind="stable")
            s_ns, s_keys = ns_p[order], keys_p[order]
            s_leaves = [l[order] for l in leaves_p]
            bounds = np.nonzero(np.diff(s_ns))[0] + 1
            starts = np.concatenate(([0], bounds))
            ends = np.concatenate((bounds, [len(s_ns)]))
            sp = self.spills[p]
            for a, b in zip(starts.tolist(), ends.tolist()):
                ns = int(s_ns[a])
                entry = {"key_id": s_keys[a:b],
                         **{f"leaf_{i}": s_leaves[i][a:b]
                            for i in range(len(s_leaves))}}
                if ns in sp:
                    sp.drop(ns)
                sp.put(ns, entry, dirty=False)

    # -------------------------------------------------------- observability

    def spill_counters(self) -> Dict[str, int]:
        """Spill traffic summed over shards (namespace layout counts on
        the engine; the paged layout overrides and sums its maps)."""
        from flink_tpu.state.paged_spill import PagedSpillMap

        out = PagedSpillMap.zero_counters()
        for k, v in getattr(self, "_ns_counters", {}).items():
            out[k] += v
        return out

    def shard_resident_rows(self) -> List[int]:
        """Device-resident rows per shard — the key-imbalance signal the
        autoscaler reads before trusting a hot shard to mean overload."""
        return [int(idx.slot_used.sum()) for idx in self.indexes]

    def key_imbalance(self) -> float:
        """max/mean resident rows per shard (1.0 = perfectly balanced).

        A hot shard with high imbalance is a SKEW problem, not a
        capacity problem: fewer shards would concentrate the same keys
        harder, so the scaling policy refuses to scale down on it.
        The formula lives in autoscale.policy (one definition for the
        gauge and for the guard that acts on it)."""
        from flink_tpu.autoscale.policy import key_imbalance

        return key_imbalance(self.shard_resident_rows())

    # ---------------------------------------------------------- tenant quota

    def enforce_resident_budget(self, max_total_rows: int) -> int:
        """Quota backstop (flink_tpu.tenancy.quotas): evict this
        engine's OWN coldest rows until its device-resident total is at
        most ``max_total_rows`` — rows land in the engine's private
        spill tier, exactly like steady-state eviction. Structural
        isolation: the method only walks ``self``'s shards, so one
        job's enforcement can never reclaim another job's rows.
        Returns rows shed. Raises when no spill tier is configured
        (nowhere to shed to — the ledger counts a quota violation)."""
        from flink_tpu.state.slot_table import SlotTableFullError

        if not self._spill_active:
            raise RuntimeError(
                "engine has no spill tier — a resident-row quota needs "
                "state.slot-table.max-device-slots (+ spill dir) so "
                "over-budget rows have somewhere to go")
        max_total_rows = max(int(max_total_rows), 0)
        if getattr(self, "_paged", False):
            # the current batch's rows carry the live clock; the backstop
            # runs between scheduling quanta, so advancing it makes every
            # resident row evictable
            self._touch_clock += 1
        per = self.shard_resident_rows()
        shed = 0
        while sum(per) > max_total_rows:
            p = int(np.argmax(np.asarray(per)))
            if per[p] <= 0:
                break
            try:
                if getattr(self, "_paged", False):
                    self._evict_cold_paged(p)
                else:
                    self._evict_cold(p, protect=set())
            except SlotTableFullError:
                break
            new = self.shard_resident_rows()
            freed = sum(per) - sum(new)
            if freed <= 0:
                break
            shed += freed
            per = new
        return shed

    # ------------------------------------------------- live rescale (reshard)

    #: live rescales completed since engine construction
    reshards_completed: int = 0
    #: report dict of the most recent reshard (None until the first)
    last_reshard: Optional[Dict[str, object]] = None

    def _make_shard_indexes(self) -> List:
        """Fresh per-shard host indexes at the CURRENT self.P/capacity
        (shared by __init__ and the reshard rebuild)."""
        from flink_tpu.state.slot_table import make_slot_index

        return [
            make_slot_index(
                self.capacity, growable=True,
                on_grow=lambda old, new: self._shard_index_grew(new),
                max_capacity=self.max_device_slots,
                track_namespaces=getattr(self, "_track_ns", True),
                full_hint=("state spills to host beyond "
                           "state.slot-table.max-device-slots"
                           if self.max_device_slots
                           else "raise state.slot-table.capacity"))
            for _ in range(self.P)
        ]

    def reshard(self, new_shards: int, devices=None) -> Dict[str, object]:
        """LIVE key-group migration to a new mesh size — no checkpoint
        round-trip, no stop-and-redeploy.

        Rescaling *is* key-group-range reassignment (reference:
        KeyGroupRangeAssignment.java — the same group->subtask formula
        the data path routes by): the engine drains its dispatch-ahead
        fences, lifts every logical row (device-resident AND spilled)
        off the old mesh with its dirtiness and recency intact, rebuilds
        the [P, capacity] plane over a mesh of ``new_shards`` devices,
        and lands the rows on their new owners — resident rows through
        ONE batched put program (the cross-shard reload machinery),
        cold rows straight into the new shards' spill tiers. Window/
        session metadata (bookkeeper / interval set) is global host
        state and never moves. Delta-snapshot bookkeeping survives: rows
        dirty before the reshard are still dirty after, and freed-
        namespace tombstones carry over, so the next incremental
        checkpoint is exactly what it would have been.

        Callers must have harvested in-flight async fires first (their
        device buffers reference the pre-reshard arrays); the operator
        wrapper (WindowAggOperator.reshard) enforces this.

        NOT exception-atomic: a failure mid-handoff (e.g. an injected
        ``rescale.handoff`` chaos fault) leaves the engine unusable —
        the failover path is checkpoint-restore-at-new-parallelism,
        exactly how the chaos harness recovers.
        """
        new_shards = int(new_shards)
        if new_shards < 1:
            raise ValueError(f"new_shards must be >= 1, got {new_shards}")
        if new_shards == self.P and devices is None:
            return {"from": self.P, "to": self.P, "rows_moved": 0,
                    "resident_rows": 0, "spilled_rows": 0,
                    "seconds": 0.0, "noop": True}
        if self.max_parallelism < new_shards:
            raise ValueError(
                f"cannot reshard to {new_shards} shards: max_parallelism "
                f"{self.max_parallelism} bounds the shard count (the "
                "key-group space cannot be split finer)")
        if self.key_group_range is not None:
            first, last = self.key_group_range
            span = int(last) - int(first) + 1
            if span < new_shards:
                raise ValueError(
                    f"cannot reshard to {new_shards} shards: this engine "
                    f"owns only {span} key groups "
                    f"[{int(first)}, {int(last)}]")
        if devices is None and new_shards > len(jax.devices()):
            raise ValueError(
                f"cannot reshard to {new_shards} shards: only "
                f"{len(jax.devices())} devices exist")
        t0 = time.perf_counter()
        with flight.span("reshard.handoff"):
            # quiesce: prove the device consumed every staged host buffer
            # before the staging pool and the accumulator plane are
            # replaced
            while self._dispatch_fences:
                # flint: disable=TRC01 -- reshard quiesce: the mesh plane
                # is about to be torn down, every in-flight dispatch must
                # land
                self._dispatch_fences.popleft().block_until_ready()
            chaos.fault_point("rescale.handoff", stage="drain",
                              from_shards=self.P, to_shards=new_shards)
            rows = self._collect_handoff()
            old_p = self.P
            # a live rebalanced assignment is defined over the OLD shard
            # count: changing P resets to the contiguous layout (the
            # lifted rows re-route below; the rebalancer re-detects on
            # the new mesh if the skew persists)
            self._assignment = None
            self._rebuild_mesh_plane(new_shards, devices)
            # the hardest crash point: old state lifted, new plane empty
            # — recovery is restore-from-checkpoint (the engine object is
            # dead)
            chaos.fault_point("rescale.handoff", stage="commit",
                              from_shards=old_p, to_shards=new_shards)
            resident_rows, spilled_rows = self._redistribute_handoff(rows)
        self.reshards_completed += 1
        self.last_reshard = {
            "from": old_p, "to": new_shards,
            "rows_moved": int(len(rows["key_id"])),
            "resident_rows": resident_rows,
            "spilled_rows": spilled_rows,
            "seconds": time.perf_counter() - t0,
        }
        return self.last_reshard

    def reassign_key_groups(self, assignment) -> Dict[str, object]:
        """LIVE hot-range rebalance: move key groups BETWEEN shards at a
        batch boundary without changing P — the skew response the
        rescale path cannot provide (more shards under a hot range just
        concentrates the same keys).

        Same handoff discipline as :meth:`reshard` (drain fences ->
        lift rows -> rebuild plane -> redistribute by the NEW routing),
        with its own chaos fault point (``rebalance.handoff``) at the
        same two stages. The full row lift is acceptable because the
        rebalance policy's cooldown makes moves rare; the win is
        steady-state throughput, not handoff latency.

        NOT exception-atomic, like reshard: a crash mid-handoff is
        recovered by checkpoint restore (the restoring engine routes by
        ITS OWN assignment — snapshots are key-id addressed and carry no
        assignment, so restore after a crash-at-commit is well-defined).
        """
        from flink_tpu.state.keygroups import KeyGroupAssignment

        if not isinstance(assignment, KeyGroupAssignment):
            raise TypeError(
                f"expected KeyGroupAssignment, got {type(assignment).__name__}")
        if assignment.num_shards != self.P:
            raise ValueError(
                f"assignment is for {assignment.num_shards} shards, "
                f"engine has {self.P} — rebalance moves groups, "
                "reshard() changes P")
        if self.key_group_range is not None:
            first = int(self.key_group_range[0])
            span = int(self.key_group_range[1]) - first + 1
        else:
            first, span = 0, self.max_parallelism
        if assignment.first != first or assignment.span != span:
            raise ValueError(
                f"assignment covers groups [{assignment.first}, "
                f"{assignment.first + assignment.span - 1}], engine owns "
                f"[{first}, {first + span - 1}]")
        cur = self._assignment if self._assignment is not None else \
            KeyGroupAssignment.contiguous(self.P, self.max_parallelism,
                                          self.key_group_range)
        moved = np.nonzero(assignment.table != cur.table)[0]
        if len(moved) == 0:
            return {"groups_moved": 0, "rows_moved": 0,
                    "resident_rows": 0, "spilled_rows": 0,
                    "seconds": 0.0, "noop": True}
        t0 = time.perf_counter()
        with flight.span("reshard.handoff"):
            while self._dispatch_fences:
                # flint: disable=TRC01 -- rebalance quiesce: the mesh
                # plane is about to be torn down, every in-flight
                # dispatch must land
                self._dispatch_fences.popleft().block_until_ready()
            chaos.fault_point("rebalance.handoff", stage="drain",
                              groups_moved=len(moved))
            rows = self._collect_handoff()
            # install the table BEFORE redistribution: _route must send
            # the lifted rows to their NEW owners
            self._assignment = None if assignment.is_contiguous \
                else assignment
            self._rebuild_mesh_plane(self.P)
            chaos.fault_point("rebalance.handoff", stage="commit",
                              groups_moved=len(moved))
            resident_rows, spilled_rows = self._redistribute_handoff(rows)
        self.rebalances_completed += 1
        self.last_rebalance = {
            "groups_moved": int(len(moved)),
            "rows_moved": int(len(rows["key_id"])),
            "resident_rows": resident_rows,
            "spilled_rows": spilled_rows,
            "seconds": time.perf_counter() - t0,
        }
        return self.last_rebalance

    @property
    def key_group_assignment(self):
        """The EFFECTIVE assignment (explicit table, or the contiguous
        default) — what serving-side ``host_of_key_group`` routing must
        follow after a rebalance."""
        from flink_tpu.state.keygroups import KeyGroupAssignment

        if self._assignment is not None:
            return self._assignment
        return KeyGroupAssignment.contiguous(
            self.P, self.max_parallelism, self.key_group_range)

    def _collect_handoff(self, skip_shards=()) -> Dict[str, np.ndarray]:
        """Lift every logical row off the current mesh: key/namespace/
        leaf columns plus the handoff metadata restore does not need —
        per-row dirtiness (delta-snapshot correctness), recency clocks
        (who stays resident on a scale-down), and residency.

        ``skip_shards``: shards whose state must NOT be read (a lost
        device — its plane slice and spill tier are gone; partial
        failover restores that range from its checkpoint unit instead).
        """
        leaves = self.agg.leaves
        paged = bool(getattr(self, "_paged", False))
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        keys: List[np.ndarray] = []
        nss: List[np.ndarray] = []
        dirty: List[np.ndarray] = []
        touch: List[np.ndarray] = []
        resident: List[np.ndarray] = []
        leaf_cols: List[List[np.ndarray]] = [[] for _ in leaves]
        skip = set(skip_shards)
        for p in range(self.P):
            if p in skip:
                continue
            idx = self.indexes[p]
            used = idx.used_slots()
            if len(used):
                u_ns = np.asarray(idx.slot_ns[used], dtype=np.int64)
                keys.append(np.asarray(idx.slot_key[used],
                                       dtype=np.int64))
                nss.append(u_ns)
                dirty.append(np.asarray(self._dirty[p][used], dtype=bool))
                if paged:
                    touch.append(self._slot_touch[p][used].copy())
                else:
                    nt = self._ns_touch[p]
                    touch.append(np.asarray(
                        [nt.get(int(x), 0) for x in u_ns],
                        dtype=np.int64))
                resident.append(np.ones(len(used), dtype=bool))
                for i in range(len(leaves)):
                    leaf_cols[i].append(accs_host[i][p][used])
            sp = self.spills[p]
            if len(sp) == 0:
                continue
            dirty_set = set(sp.dirty_namespaces())
            pmap = self._pmaps[p] if paged else None
            for ns in sp.namespaces:
                entry = sp.peek(int(ns))
                if entry is None:
                    continue
                ekeys = np.asarray(entry["key_id"], dtype=np.int64)
                if "ns" in entry:  # paged page: per-row ns + tombstones
                    rns = np.asarray(entry["ns"], dtype=np.int64)
                    alive = pmap.live_row_mask(int(ns), rns)
                    if not alive.any():
                        continue
                    ekeys, rns = ekeys[alive], rns[alive]
                    # only rows not shipped by a snapshot since their
                    # eviction are still dirty (tier flag gates, the
                    # per-row column refines — same rule as
                    # _spill_delta_append)
                    row_dirty = (
                        np.asarray(entry["dirty"], dtype=bool)[alive]
                        if int(ns) in dirty_set
                        else np.zeros(len(ekeys), dtype=bool))
                    sel = alive
                else:
                    rns = np.full(len(ekeys), int(ns), dtype=np.int64)
                    row_dirty = np.full(len(ekeys), int(ns) in dirty_set,
                                        dtype=bool)
                    sel = slice(None)
                if len(ekeys) == 0:
                    continue
                keys.append(ekeys)
                nss.append(rns)
                dirty.append(row_dirty)
                touch.append(np.zeros(len(ekeys), dtype=np.int64))
                resident.append(np.zeros(len(ekeys), dtype=bool))
                for i, l in enumerate(leaves):
                    leaf_cols[i].append(
                        np.asarray(entry[f"leaf_{i}"],
                                   dtype=l.dtype)[sel])
        if not keys:
            return {
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "dirty": np.empty(0, dtype=bool),
                "touch": np.empty(0, dtype=np.int64),
                "resident": np.empty(0, dtype=bool),
                **{f"leaf_{i}": np.empty(0, dtype=l.dtype)
                   for i, l in enumerate(leaves)},
            }
        return {
            "key_id": np.concatenate(keys),
            "namespace": np.concatenate(nss),
            "dirty": np.concatenate(dirty),
            "touch": np.concatenate(touch),
            "resident": np.concatenate(resident),
            **{f"leaf_{i}": np.concatenate(leaf_cols[i])
               for i in range(len(leaves))},
        }

    def _rebuild_mesh_plane(self, new_shards: int, devices=None) -> None:
        """Re-point the engine at a fresh [new_shards, capacity] plane:
        new mesh, indexes, spill tiers, identity accumulators and step
        programs. Job-lifetime state survives: the recency clock, the
        namespace-layout spill counters, and the delta tombstones
        (_freed_ns) are NOT reset — only the per-mesh containers are."""
        from flink_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(new_shards, devices=devices)
        self.release_memory()
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        t = self.host_topology
        if t is not None and t.num_shards != self.P:
            # a reshard / partial failover changed the device count:
            # the (hosts, local) factorization no longer describes the
            # mesh — fall back to the flat single-axis exchange (the
            # evacuated mesh is host-local until a re-plan re-declares
            # a topology)
            self.host_topology = None
        # the replica's metadata shadow describes the OLD plane — the
        # next boundary publish rebuilds it over the new mesh
        self._rep_rebuild = True
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        if hasattr(self, "_replicated"):
            self._replicated = NamedSharding(mesh, P())
        clock = getattr(self, "_touch_clock", 0)
        # the old tiers' fs-resident pages would otherwise be orphaned
        # on disk (collect only peeks) — reclaim them before rebinding
        for sp in self.spills:
            for ns in list(sp.namespaces):
                sp.discard(int(ns))
        if getattr(self, "_paged", False):
            # fold the outgoing maps' lifetime counters into the
            # engine-held dict spill_counters() also sums — a rescale
            # must not zero the job's monotonic spill gauges
            for pm in self._pmaps:
                for k, v in pm.counters().items():
                    self._ns_counters[k] += v
        self.indexes = self._make_shard_indexes()
        self._init_spill(self._spill_dir, self._spill_host_max_bytes)
        self._touch_clock = clock  # recency survives the move
        if getattr(self, "_paged", False):
            self._init_paged()
        self._reserve_rows(self.P * self.capacity)
        self.accs = tuple(
            jax.device_put(
                jnp.full((self.P, self.capacity), leaf.identity,
                         dtype=leaf.dtype),
                self._sharding)
            for leaf in self.agg.leaves)
        self._build_steps()
        self._dirty = np.zeros((self.P, self.capacity), dtype=bool)
        # sticky bucket sizes are per-mesh-shape dispatch amortizers
        self._gather_bucket = 0
        self._reset_bucket = 0
        self._fire_bucket = 0
        self._merge_bucket = 0

    def _redistribute_handoff(
            self, rows: Dict[str, np.ndarray]) -> Tuple[int, int]:
        """Land the collected rows on their new owners (the same
        key-group formula the data path routes by). Returns
        (resident_rows, spilled_rows).

        Residency policy under a device budget: previously-resident rows
        stay resident while they fit; on a scale-down the hottest rows
        (by carried recency clock) win and the overflow lands in the new
        shard's spill tier. The namespace layout decides per NAMESPACE
        (its eviction unit — a namespace split between device and tier
        would double-apply on the next reload), the paged layout per ROW
        (its pages already span namespaces)."""
        leaves = self.agg.leaves
        keys = rows["key_id"]
        nss = rows["namespace"]
        n = len(keys)
        if n == 0:
            return 0, 0
        paged = bool(getattr(self, "_paged", False))
        shards = self._route(keys)
        stay = rows["resident"].copy()
        if self._spill_active:
            # slot 0 is the reserved identity row — usable capacity is
            # one short of the budget
            budget = self.max_device_slots - 1
            if paged:
                for p in range(self.P):
                    sel = np.nonzero(stay & (shards == p))[0]
                    if len(sel) > budget:
                        order = np.argsort(rows["touch"][sel],
                                           kind="stable")
                        stay[sel[order[: len(sel) - budget]]] = False
            else:
                for p in range(self.P):
                    sel = np.nonzero(shards == p)[0]
                    if not len(sel):
                        continue
                    uniq, inv = np.unique(nss[sel], return_inverse=True)
                    grp_res = np.zeros(len(uniq), dtype=bool)
                    np.logical_or.at(grp_res, inv, rows["resident"][sel])
                    grp_touch = np.zeros(len(uniq), dtype=np.int64)
                    np.maximum.at(grp_touch, inv, rows["touch"][sel])
                    grp_rows = np.bincount(inv, minlength=len(uniq))
                    stay_grp = np.zeros(len(uniq), dtype=bool)
                    free = budget
                    cand = np.nonzero(grp_res)[0]
                    for g in cand[np.argsort(-grp_touch[cand],
                                             kind="stable")].tolist():
                        if grp_rows[g] <= free:
                            stay_grp[g] = True
                            free -= int(grp_rows[g])
                    stay[sel] = stay_grp[inv]
        # resident rows: resolve every slot FIRST (inserts may grow the
        # plane; growth must settle before the host blocks are built),
        # then land all shards' values in ONE batched put program
        per_shard: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for p in range(self.P):
            sel = np.nonzero(stay & (shards == p))[0]
            if len(sel):
                per_shard[p] = (sel, self.indexes[p].lookup_or_insert(
                    keys[sel], nss[sel]))
        if per_shard:
            B = sticky_bucket(
                max(len(sel) for sel, _ in per_shard.values()),
                self._reload_bucket)
            self._reload_bucket = B
            slot_block = np.zeros((self.P, B), dtype=np.int32)
            val_blocks = [np.full((self.P, B), l.identity, dtype=l.dtype)
                          for l in leaves]
            for p, (sel, slots) in per_shard.items():
                m = len(sel)
                slot_block[p, :m] = slots
                for i in range(len(leaves)):
                    val_blocks[i][p, :m] = rows[f"leaf_{i}"][sel]
                self._dirty[p, slots] = rows["dirty"][sel]
                if paged:
                    self._slot_touch[p][slots] = rows["touch"][sel]
                elif self._spill_active:
                    self._touch(p, np.unique(nss[sel]).tolist())
            self.accs = self._put_step(
                self.accs, self._put_sharded(slot_block),
                tuple(self._put_sharded(v) for v in val_blocks))
        # cold rows re-home into the new shards' spill tiers, dirtiness
        # intact (pages for the paged layout, per-ns entries otherwise)
        cold_total = 0
        cold = ~stay
        if cold.any():
            for p in range(self.P):
                sel = np.nonzero(cold & (shards == p))[0]
                if not len(sel):
                    continue
                cold_total += len(sel)
                c_keys, c_nss = keys[sel], nss[sel]
                c_dirty = rows["dirty"][sel]
                c_leaves = [rows[f"leaf_{i}"][sel]
                            for i in range(len(leaves))]
                if paged:
                    from flink_tpu.state.paged_spill import (
                        restore_into_pages,
                    )

                    restore_into_pages(
                        self.spills[p], self._pmaps[p], c_keys, c_nss,
                        c_leaves,
                        page_rows=max(self.indexes[p].capacity // 8,
                                      1024),
                        dirty=c_dirty)
                else:
                    order = np.argsort(c_nss, kind="stable")
                    s_ns, s_keys = c_nss[order], c_keys[order]
                    s_dirty = c_dirty[order]
                    s_leaves = [l[order] for l in c_leaves]
                    bounds = np.nonzero(np.diff(s_ns))[0] + 1
                    starts = np.concatenate(([0], bounds))
                    stops = np.concatenate((bounds, [len(s_ns)]))
                    sp = self.spills[p]
                    for a, b in zip(starts.tolist(), stops.tolist()):
                        ns = int(s_ns[a])
                        entry = {"key_id": s_keys[a:b],
                                 **{f"leaf_{i}": s_leaves[i][a:b]
                                    for i in range(len(leaves))}}
                        sp.put(ns, entry,
                               dirty=bool(s_dirty[a:b].any()))
        return int(stay.sum()), cold_total


    # ---------------------------------------------- partial failover (shard)

    #: report dict of the most recent shard loss (None until the first)
    last_shard_loss: Optional[Dict[str, object]] = None

    def shard_key_groups(self) -> List[Tuple[int, int]]:
        """GLOBAL ``(first, last)`` inclusive key groups per shard —
        the unit of failure/recovery, and the split shard-granular
        checkpoints key their units by (the exact inverse of
        ``shard_records``' routing formula). Undefined under a live
        rebalanced assignment (a shard's groups are no longer ONE
        range) — use :meth:`shard_key_group_runs` there."""
        from flink_tpu.state.keygroups import shard_key_group_ranges

        if self._assignment is not None:
            raise ValueError(
                "shard->key-group ownership is non-contiguous under a "
                "live rebalanced assignment — shard_key_group_runs() "
                "gives the per-run decomposition")
        return shard_key_group_ranges(self.P, self.max_parallelism,
                                      self.key_group_range)

    def shard_key_group_runs(self) -> List[Tuple[int, int, int]]:
        """GLOBAL ``(first, last, shard)`` maximal same-shard runs in
        key-group order — the checkpoint-unit granularity that stays
        well-defined under a rebalanced assignment (contiguous layout:
        exactly one run per shard)."""
        if self._assignment is not None:
            return self._assignment.runs()
        return [(g0, g1, p) for p, (g0, g1)
                in enumerate(self.shard_key_groups())]

    def lose_shard(self, dead: int) -> Tuple[int, int]:
        """Simulated device loss of shard ``dead``: its resident plane
        slice, spill tier and key-range metadata are gone WHOLESALE
        (the TaskManager-loss failure domain). Survivors' fences drain,
        their rows lift intact (dirtiness + recency preserved — the
        reshard machinery), the mesh rebuilds over the remaining
        ``P - 1`` devices, and the survivors' rows land on their new
        owners. Returns the DEAD shard's (first, last) key groups — the
        caller then restores exactly that range from its checkpoint
        unit (:meth:`restore_key_groups`) and replays only that range's
        records from the unit's source position.

        Like ``reshard``, not exception-atomic: a failure mid-evacuation
        falls back to whole-job checkpoint restore.
        """
        return self.lose_shards([dead])

    def lose_shards(self, dead) -> Tuple[int, int]:
        """Multi-shard loss in ONE evacuation — the HOST failure
        domain: a lost process takes its whole contiguous slice of
        shards (``HostTopology.shards_of_host``), survivors evacuate
        once, the mesh rebuilds over ``P - k`` devices, and the caller
        restores the dead shards' ``k`` checkpoint units. The dead
        shards must be CONTIGUOUS in flat shard order (hosts are, by
        construction — host-major layout), so the merged key-group
        span ``(first, last)`` returned covers exactly their units and
        the bounded replay is one contiguous range."""
        if self._assignment is not None:
            raise ValueError(
                "partial failover under a live rebalanced assignment is "
                "not supported: a dead shard's groups are no longer one "
                "contiguous range, so the bounded contiguous replay "
                "contract does not hold — whole-job restore applies")
        dead_set = sorted({int(d) for d in dead})
        if not dead_set:
            raise ValueError("no shards to lose")
        for d in dead_set:
            if not (0 <= d < self.P):
                raise ValueError(
                    f"no shard {d} on a {self.P}-shard mesh")
        if dead_set != list(range(dead_set[0], dead_set[-1] + 1)):
            raise ValueError(
                f"dead shards must be contiguous (a host's slice), "
                f"got {dead_set}")
        if len(dead_set) >= self.P:
            raise ValueError(
                "cannot partially fail over the whole mesh — "
                "whole-job restore applies")
        t0 = time.perf_counter()
        ranges = self.shard_key_groups()
        dead_range = (int(ranges[dead_set[0]][0]),
                      int(ranges[dead_set[-1]][1]))
        # quiesce the SURVIVORS: every in-flight dispatch must land
        # before the plane is torn down (the dead shards' fences are
        # moot — their state is discarded unread below)
        while self._dispatch_fences:
            # flint: disable=TRC01 -- failover quiesce: the mesh plane
            # is about to be rebuilt, in-flight dispatches must land
            self._dispatch_fences.popleft().block_until_ready()
        rows = self._collect_handoff(skip_shards=set(dead_set))
        devices = [d for i, d in enumerate(self.mesh.devices.flat)
                   if i not in dead_set]
        old_p = self.P
        self._rebuild_mesh_plane(old_p - len(dead_set),
                                 devices=devices)
        resident_rows, spilled_rows = self._redistribute_handoff(rows)
        # the dead ranges' host metadata dies with their shards (engine
        # hook: session intervals for the window engines' global book
        # there is nothing per-key to drop)
        self._drop_meta_key_groups(
            range(dead_range[0], dead_range[1] + 1))
        wd = self._watchdog
        if wd is not None:
            # survivors renumber 0..P-k-1; the dead device ids stay in
            # the watchdog's quarantine HISTORY for budget accounting
            wd.rebind(self.P,
                      [d.id for d in self.mesh.devices.flat])
        self.last_shard_loss = {
            "dead_shard": dead_set[0], "dead_shards": dead_set,
            "from": old_p, "to": self.P,
            "key_groups": dead_range,
            "survivor_rows": int(len(rows["key_id"])),
            "resident_rows": resident_rows,
            "spilled_rows": spilled_rows,
            "seconds": time.perf_counter() - t0,
        }
        return dead_range

    def restore_key_groups(self, snap: Dict[str, object],
                           groups) -> int:
        """Partial restore INTO a live engine: land only ``groups``'
        rows (survivors untouched) and merge the unit's metadata (the
        engine hook rolls watermark/staleness guards back to the
        checkpoint so the range's replayed records are accepted).
        Restored rows are CLEAN — they are in the checkpoint, so the
        next delta must not re-ship them; survivors keep their genuine
        dirtiness. Returns rows restored."""
        # restored values bypass the scatter sites: the replica shadow
        # cannot tell them apart — republish wholesale
        self._rep_rebuild = True
        table = snap.get("table", {}) or {}
        key_ids = np.asarray(table.get("key_id", []), dtype=np.int64)
        gset = np.asarray(sorted(int(g) for g in groups),
                          dtype=np.int64)
        n_restored = 0
        if len(key_ids):
            kg = table.get("key_group")
            kg = (np.asarray(kg, dtype=np.int64) if kg is not None
                  else assign_key_groups(key_ids, self.max_parallelism))
            keep = np.isin(kg, gset)
            key_ids = key_ids[keep]
            namespaces = np.asarray(table["namespace"],
                                    dtype=np.int64)[keep]
            leaves = [np.asarray(table[f"leaf_{i}"])[keep]
                      for i in range(len(self.agg.leaves))]
            n_restored = int(len(key_ids))
        if n_restored:
            shards = self._route(key_ids)
            if getattr(self, "_paged", False):
                from flink_tpu.state.paged_spill import (
                    restore_into_pages,
                )

                for p in range(self.P):
                    mask = shards == p
                    if not mask.any():
                        continue
                    # APPEND: the survivors' pages must stay intact;
                    # the restored namespaces (per-session sids) were
                    # never held by the surviving tiers
                    restore_into_pages(
                        self.spills[p], self._pmaps[p], key_ids[mask],
                        namespaces[mask], [l[mask] for l in leaves],
                        page_rows=max(self.indexes[p].capacity // 8,
                                      1024),
                        append=True)
            else:
                # land resident: resolve all slots first (growth must
                # settle), then ONE batched put program for all shards
                per_shard: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
                for p in range(self.P):
                    mask = shards == p
                    if not mask.any():
                        continue
                    if self._spill_active:
                        self._reserve(p, key_ids[mask],
                                      namespaces[mask])
                    slots = self.indexes[p].lookup_or_insert(
                        key_ids[mask], namespaces[mask])
                    per_shard[p] = (np.nonzero(mask)[0], slots)
                B = sticky_bucket(
                    max(len(s) for _, s in per_shard.values()),
                    self._reload_bucket)
                self._reload_bucket = B
                slot_block = np.zeros((self.P, B), dtype=np.int32)
                val_blocks = [
                    np.full((self.P, B), l.identity, dtype=l.dtype)
                    for l in self.agg.leaves]
                for p, (sel, slots) in per_shard.items():
                    m = len(sel)
                    slot_block[p, :m] = slots
                    for i in range(len(val_blocks)):
                        val_blocks[i][p, :m] = leaves[i][sel]
                    # restored rows are the checkpoint's — clean
                    self._dirty[p, slots] = False
                    if self._spill_active:
                        self._touch(p, np.unique(
                            namespaces[sel]).tolist())
                self.accs = self._put_step(
                    self.accs, self._put_sharded(slot_block),
                    tuple(self._put_sharded(v) for v in val_blocks))
        self._merge_restored_meta(snap, groups)
        return n_restored

    # engine hooks (window engines: global book; session engines:
    # per-key interval metadata) ------------------------------------------

    def _drop_meta_key_groups(self, groups) -> None:
        """Discard the host metadata owned by ``groups`` (no-op for
        engines whose lifecycle metadata carries no per-key state)."""

    def _merge_restored_meta(self, snap: Dict[str, object],
                             groups) -> None:
        """Fold a checkpoint unit's metadata for ``groups`` into the
        live engine (partial failover)."""

    def _filter_meta_snapshot(self, snap: Dict[str, object],
                              groups) -> Dict[str, object]:
        """The non-table part of ``snap`` restricted to ``groups`` —
        default: global metadata replicates whole into every unit."""
        return {k: v for k, v in snap.items() if k != "table"}

    def _merge_meta_snapshots(self, units: List[Dict[str, object]]
                              ) -> Dict[str, object]:
        """Merge units' metadata for a whole-job restore assembled from
        (possibly different-age) shard units."""
        raise NotImplementedError

    #: delta/tombstone fields that replicate whole into every unit —
    #: applying another range's tombstones to a unit's base is a no-op
    #: (the base holds no rows of that range), so replication is safe
    #: and keeps each unit independently restorable
    _UNIT_PASSTHROUGH = ("__delta__", "freed_namespaces",
                         "tombstone_key_id", "tombstone_namespace")

    def snapshot_sharded(self, mode: str = "full"
                         ) -> Dict[Tuple[int, int], Dict[str, object]]:
        """One independently-restorable unit per shard: the logical
        snapshot split by the current shards' key-group ranges (rows by
        their ``key_group`` column — the delta machinery keeps
        increments per-shard through the same split), plus each unit's
        slice of the metadata. The union of the units is exactly
        ``snapshot(mode)``."""
        snap = self.snapshot(mode)
        table = snap.get("table", {}) or {}
        kg = np.asarray(table.get("key_group", ()), dtype=np.int64)
        units: Dict[Tuple[int, int], Dict[str, object]] = {}
        # one unit per maximal same-shard RUN: under the contiguous
        # layout that is exactly one unit per shard (unchanged); under a
        # rebalanced assignment a shard contributes one unit per run it
        # owns, and the union of units is still exactly snapshot(mode)
        for g0, g1, _p in self.shard_key_group_runs():
            if len(kg):
                mask = (kg >= g0) & (kg <= g1)
                unit_table = {
                    k: (v if k in self._UNIT_PASSTHROUGH
                        else np.asarray(v)[mask])
                    for k, v in table.items()
                }
            else:
                unit_table = dict(table)
            units[(int(g0), int(g1))] = {
                "table": unit_table,
                **self._filter_meta_snapshot(
                    snap, range(int(g0), int(g1) + 1)),
            }
        return units

    def merge_unit_snapshots(self, units: List[Dict[str, object]]
                             ) -> Dict[str, object]:
        """Reassemble one engine snapshot from shard units (whole-job
        restore; units may come from DIFFERENT checkpoints when a torn
        unit fell back to an older complete one — the caller replays
        each range from its own unit's source position)."""
        tables = [u.get("table", {}) or {} for u in units]
        tables = [t for t in tables if t]
        merged: Dict[str, object] = {}
        if tables:
            cols = set().union(*(set(t) for t in tables))
            for k in sorted(cols):
                parts = [np.asarray(t[k]) for t in tables if k in t]
                if k == "__delta__":
                    merged[k] = np.asarray(True)
                elif k == "freed_namespaces":
                    merged[k] = (np.unique(np.concatenate(parts))
                                 if parts else np.empty(0,
                                                        dtype=np.int64))
                else:
                    # tombstone_key_id / tombstone_namespace are ROW-
                    # PAIRED parallel columns (apply_table_delta packs
                    # them): a per-column unique would break the pair
                    # correspondence — plain concatenation keeps it
                    # (duplicate pairs apply idempotently)
                    merged[k] = (np.concatenate(parts) if parts
                                 else np.empty(0, dtype=np.int64))
        return {"table": merged, **self._merge_meta_snapshots(units)}


class MeshPagedSpillSupport(MeshSpillSupport):
    """Paged (cohort) spill for session-shaped mesh state — the mesh form
    of the single-device ``spill_layout="pages"`` machinery
    (flink_tpu.state.paged_spill, shared): per shard, the unit of
    movement is an eviction cohort of the coldest rows (slot-granular
    touch clocks, not namespace recency), reloads extract exactly the
    requested rows by stored row index and leave LAZY TOMBSTONES in
    their pages (space comes back via threshold compaction, never
    read-path rewrites), and the host index runs registry-free
    (``track_namespaces=False`` — one row per session id makes the
    per-namespace registry O(live sessions) Python per batch).

    Device traffic stays batched across shards: all shards' page reloads
    land in ONE put program, and all shards short on headroom evict in
    ONE gather + ONE reset program per round (the other shards' rows
    identity no-ops)."""

    def _init_paged(self) -> None:
        from flink_tpu.state.paged_spill import PagedSpillMap

        #: one membership map (+ counters) per shard — spilled pages are
        #: shard-local like the device rows
        self._pmaps = [PagedSpillMap() for _ in range(self.P)]
        # latency tier: fire-path extractions queue their page sweeps
        # (reap/compact) instead of running them inline — the engine
        # drains the queue on its next ingest step, keeping the fire
        # span a bounded delta (space reclamation is time-insensitive)
        for pm in self._pmaps:
            pm.defer_sweeps = True
        #: [P, capacity] per-slot touch clocks (the paged analog of the
        #: namespace recency map)
        self._slot_touch = np.zeros((self.P, self.capacity),
                                    dtype=np.int64)

    def _drain_deferred_sweeps(self) -> None:
        """Run the page sweeps queued by fire-path extractions (ingest
        boundary — see PagedSpillMap.defer_sweeps)."""
        from flink_tpu.state.paged_spill import run_deferred_sweeps

        for p, pm in enumerate(self._pmaps):
            if pm.deferred_pages:
                run_deferred_sweeps(self.spills[p], pm)

    def _paged_grow(self, new_capacity: int) -> None:
        if new_capacity <= self._slot_touch.shape[1]:
            return
        grown = np.zeros((self.P, new_capacity), dtype=np.int64)
        grown[:, : self._slot_touch.shape[1]] = self._slot_touch
        self._slot_touch = grown

    def spill_counters(self) -> Dict[str, int]:
        """Spill traffic summed over shards (zeros when unbudgeted);
        the namespace-layout engine counters ride along so a
        spill_layout="namespaces" session engine still reports."""
        out = super().spill_counters()
        for pm in getattr(self, "_pmaps", ()):
            for k, v in pm.counters().items():
                out[k] += v
        return out

    def _resolve_slots_paged(
            self, per_shard: Dict[int, Tuple[np.ndarray, np.ndarray]],
            fresh: Optional[Dict[int, np.ndarray]] = None,
            hints: Optional[Dict[int, np.ndarray]] = None,
    ) -> Dict[int, np.ndarray]:
        """Batched slot resolution over shards with page reload and
        cohort eviction: resident rows of THIS batch get a fresh clock
        (protecting them from the eviction the batch itself triggers),
        missing pairs reload by page (ONE put program for all shards),
        then only the still-missing pairs insert.

        ``fresh``: optional per-shard bool masks marking pairs the
        caller KNOWS were allocated this batch (fresh session ids from
        the monotonic allocator) — they cannot be resident or paged, so
        they skip both the index probe and the page query and go
        straight to insert. At high-cardinality shapes most of a
        batch's sessions are fresh, and the skipped page query is a
        sorted-match over the full spilled-row map.

        ``hints``: per-shard folded device slots from the native
        session-metadata plane (-1 unknown). A hint is VERIFIED against
        the shard index's metadata views (``verify_slot_hints``) before
        use — verified rows skip the hash probe entirely, stale folds
        fall back to it, so the state evolution is identical to the
        hint-free path (same hits, same misses, same insert order).

        Callers pass session-shaped pairs (one row per globally-unique
        sid), so no dedup pass runs here and the insert probe is
        restricted to the pre-lookup's misses — the resident-majority
        steady state pays ONE native hash probe per row. Duplicate
        pairs stay correct (the insert dedups); they only overcount the
        eviction headroom."""
        from flink_tpu.state.paged_spill import reload_rows_for

        self._touch_clock += 1
        clock = self._touch_clock
        leaf_dtypes = [l.dtype for l in self.agg.leaves]
        reloads: Dict[int, Tuple[np.ndarray, List[np.ndarray]]] = {}
        extracted: Dict[int, Tuple] = {}
        out: Dict[int, np.ndarray] = {}
        missing_by_shard: Dict[int, np.ndarray] = {}
        needs: Dict[int, int] = {}
        for p, (keys, nss) in per_shard.items():
            keys = np.asarray(keys, dtype=np.int64)
            nss = np.asarray(nss, dtype=np.int64)
            idx = self.indexes[p]
            fr = fresh.get(p) if fresh is not None else None
            hint = hints.get(p) if hints is not None else None
            if hint is not None:
                pre = resolve_slot_hints(idx, keys, nss, hint, skip=fr)
            elif fr is not None and fr.any():
                pre = np.full(len(keys), -1, dtype=np.int32)
                probe = ~fr
                if probe.any():
                    pre[probe] = idx.lookup(keys[probe], nss[probe])
            else:
                pre = idx.lookup(keys, nss)
                fr = None
            hit = pre >= 0
            self._slot_touch[p][pre[hit]] = clock
            missing = ~hit
            n_missing = int(missing.sum())
            if n_missing:
                if len(self._pmaps[p]):
                    # pure host work: rows leave their pages by index
                    # (lazy tombstones — see paged_spill); fresh pairs
                    # are never spilled, so only the non-fresh misses
                    # query the page map
                    q = missing if fr is None else (missing & ~fr)
                    rl = reload_rows_for(self.spills[p], self._pmaps[p],
                                         nss[q], leaf_dtypes) \
                        if q.any() else None
                    if rl is not None:
                        extracted[p] = rl
                missing_by_shard[p] = missing
                needs[p] = n_missing
            out[p] = pre
            per_shard[p] = (keys, nss)
        # one batched eviction round covers every shard short on
        # headroom (one gather + one reset, not one pair per shard)
        if needs:
            self._make_headroom_paged_multi(needs)
        for p, rl in extracted.items():
            rkeys, rns, rdirty, rvals = rl
            rslots = self.indexes[p].lookup_or_insert(rkeys, rns)
            # reloaded rows keep their dirtiness (not snapshotted
            # since) and take the current clock — the cohort is
            # likely about to fire
            self._dirty[p, rslots] = rdirty
            self._slot_touch[p][rslots] = clock
            reloads[p] = (rslots.astype(np.int32), rvals)
        if reloads:
            B = sticky_bucket(max(len(r[0]) for r in reloads.values()),
                              self._reload_bucket)
            self._reload_bucket = B
            slot_block = np.zeros((self.P, B), dtype=np.int32)
            val_blocks = [np.full((self.P, B), l.identity, dtype=l.dtype)
                          for l in self.agg.leaves]
            for p, (rslots, rvals) in reloads.items():
                n = len(rslots)
                slot_block[p, :n] = rslots
                for i in range(len(val_blocks)):
                    val_blocks[i][p, :n] = rvals[i]
            with self._device_span():
                self.accs = self._put_step(
                    self.accs, self._put_sharded(slot_block),
                    tuple(self._put_sharded(v) for v in val_blocks))
        for p, missing in missing_by_shard.items():
            keys, nss = per_shard[p]
            # insert ONLY the pre-lookup misses (reloaded rows resolve
            # as hits here; genuinely fresh sids insert)
            slots = out[p]
            slots[missing] = self.indexes[p].lookup_or_insert(
                keys[missing], nss[missing])
            self._slot_touch[p][slots[missing]] = clock
        return out

    def _make_headroom_paged(self, p: int, needed: int) -> None:
        self._make_headroom_paged_multi({p: needed})

    def _make_headroom_paged_multi(self, needs: Dict[int, int]) -> None:
        """Evict cold cohorts for EVERY shard short on headroom in one
        round: however many shards must evict, the batch costs one
        gather + one reset program (per-shard eviction paid a dispatch
        + device sync per shard — at the thrashing shape most batches
        evict on ~6 of 8 shards, so batching cuts the eviction syncs
        ~6x)."""
        pending = {p: n for p, n in needs.items()
                   if self.indexes[p].free_headroom() < n}
        while pending:
            self._evict_cohorts({p: self._choose_eviction_cohort(p)
                                 for p in pending})
            pending = {p: n for p, n in pending.items()
                       if self.indexes[p].free_headroom() < n}

    def _evict_cold_paged(self, p: int) -> None:
        """Single-shard form (kept for tests/direct callers)."""
        self._evict_cohorts({p: self._choose_eviction_cohort(p)})

    def _choose_eviction_cohort(self, p: int) -> np.ndarray:
        """Shard ``p``'s coldest slots (touch < current clock) — the
        rows this round's page will carry."""
        from flink_tpu.state.slot_table import SlotTableFullError

        idx = self.indexes[p]
        used = idx.used_slots()
        touch = self._slot_touch[p][used]
        evictable = used[touch < self._touch_clock]
        if len(evictable) == 0:
            raise SlotTableFullError(
                f"shard {p}: device slot budget exhausted and every "
                "resident row was touched by the current batch — raise "
                "state.slot-table.max-device-slots or reduce batch size")
        # a quarter of the table per round: every round pays one
        # gather + one D2H sync + a cohort-choice pass over the used
        # set, so fewer/larger cohorts amortize the fixed costs; the
        # lazy-tombstone tier keeps over-eviction cheap (a re-touched
        # row reloads by index, no page rewrite)
        target = min(max(idx.capacity // 4, 1024), len(evictable))
        if target < len(evictable):
            et = self._slot_touch[p][evictable]
            sel = np.argpartition(et, target - 1)[:target]
            chosen = evictable[sel]
        else:
            chosen = evictable
        return np.asarray(chosen, dtype=np.int32)

    def _evict_cohorts(self, cohorts: Dict[int, np.ndarray]) -> None:
        """Move each shard's chosen cohort to its spill tier as one
        page — ONE gather + ONE reset program for all shards (rows of
        non-evicting shards are identity no-ops)."""
        from flink_tpu.state.paged_spill import spill_page

        n_max = max(len(c) for c in cohorts.values())
        G = sticky_bucket(n_max, self._gather_bucket)
        self._gather_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        for p, chosen in cohorts.items():
            block[p, : len(chosen)] = chosen
        with self._device_span():
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            # ONE batched D2H
            gathered_host = self._harvest_get(gathered, "evict_harvest")
        for p, chosen in cohorts.items():
            idx = self.indexes[p]
            n = len(chosen)
            entry = {
                "key_id": np.asarray(idx.slot_key[chosen]),
                "ns": np.asarray(idx.slot_ns[chosen]),
                "dirty": self._dirty[p, chosen].copy(),
                **{f"leaf_{i}": g[p][:n]
                   for i, g in enumerate(gathered_host)},
            }
            # replica: a row evicted before it was ever published
            # resident must still enter the index cold at the next
            # boundary (the publish drains these events)
            self._rep_note_cold(p, entry["key_id"], entry["ns"])
            spill_page(self.spills[p], self._pmaps[p], entry)
            idx.free_slots(chosen)
            self._dirty[p, chosen] = False
        R = sticky_bucket(n_max, getattr(self, "_reset_bucket", 0))
        self._reset_bucket = R
        rb = np.zeros((self.P, R), dtype=np.int32)
        for p, chosen in cohorts.items():
            rb[p, : len(chosen)] = chosen
        with self._device_span():
            self.accs = self._reset_step(self.accs,
                                         self._put_sharded(rb))

    def _free_rows_paged(self, p: int, slots: np.ndarray,
                         nss) -> None:
        """Slot-addressed free for the registry-free index (the caller
        resolved the rows this batch); spilled copies — rare, resolves
        reload first — are marked dead and their empty pages reaped."""
        from flink_tpu.state.paged_spill import drop_spilled_sessions

        if self._spill_active and len(self._pmaps[p]):
            drop_spilled_sessions(self.spills[p], self._pmaps[p],
                                  np.asarray(nss, dtype=np.int64))
        slots = np.asarray(slots, dtype=np.int32)
        if len(slots):
            self.indexes[p].free_slots(slots)
            self._dirty[p, slots] = False

    def _paged_restore_rows(self, key_ids: np.ndarray,
                            namespaces: np.ndarray,
                            leaves: List[np.ndarray]) -> None:
        """Paged restore: rows land in each shard's spill tier as
        page-sized entries and reload lazily by page."""
        from flink_tpu.state.paged_spill import restore_into_pages

        shards = self._route(key_ids)
        for p in range(self.P):
            mask = shards == p
            if not mask.any():
                if len(self._pmaps[p]):
                    restore_into_pages(  # clears stale pages
                        self.spills[p], self._pmaps[p],
                        np.empty(0, dtype=np.int64),
                        np.empty(0, dtype=np.int64),
                        [np.empty(0, dtype=l.dtype)
                         for l in self.agg.leaves], 1024)
                continue
            restore_into_pages(
                self.spills[p], self._pmaps[p], key_ids[mask],
                namespaces[mask], [l[mask] for l in leaves],
                page_rows=max(self.indexes[p].capacity // 8, 1024))


class MeshWindowEngine(MeshSpillSupport):
    """Windowed keyed aggregation sharded over a 1-D device mesh."""

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        mesh: Mesh,
        capacity_per_shard: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        fire_projector=None,
        max_device_slots: int = 0,
        spill_dir: Optional[str] = None,
        spill_host_max_bytes: int = 0,
        key_group_range: Optional[Tuple[int, int]] = None,
        memory=None,
        max_dispatch_ahead: int = 2,
        shuffle_mode: str = "device",
        host_topology=None,
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        self.shuffle_mode = self._check_shuffle_mode(shuffle_mode)
        #: dispatch-ahead depth (double-buffered by default; see
        #: MeshSpillSupport._init_pipeline)
        self.max_dispatch_ahead = max(int(max_dispatch_ahead or 1), 1)
        #: (first, last) inclusive GLOBAL key groups this engine owns; the
        #: mesh shards within the range (mesh x stage — see shard_records)
        self.key_group_range = key_group_range
        #: (MemoryManager, owner) — the [P, capacity] accumulator
        #: footprint is managed like the single-device table's
        self._memory = memory
        #: host-side (cross-shard) fired-row reduction; the single-device
        #: engine fuses this into the fire kernel, here it runs after the
        #: per-shard results are assembled (the per-shard transfer is
        #: already bounded by the fire bucket)
        self.fire_projector = fire_projector
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self._set_host_topology(host_topology)
        #: per-SHARD HBM slot budget — the raw
        #: state.slot-table.max-device-slots value, which is PER DEVICE
        #: (each shard owns one chip's HBM, so total capacity scales with
        #: the mesh while each chip stays bounded): beyond it, cold
        #: namespaces spill to the per-shard host/fs tier and reload on
        #: access (reference: RocksDBKeyedStateBackend.java — RocksDB
        #: state was never bounded by memory either)
        self.max_device_slots = int(max_device_slots or 0)
        self.capacity = max(int(capacity_per_shard), 1024)
        if self.max_device_slots:
            self.max_device_slots = max(self.max_device_slots, 1024)
            self.capacity = min(self.capacity, self.max_device_slots)
        self.max_parallelism = max_parallelism
        self.allowed_lateness = allowed_lateness
        if max_parallelism < self.P:
            raise ValueError(
                f"max_parallelism {max_parallelism} < mesh size {self.P}")

        # growable per-shard indexes: hot-key skew concentrating (key,
        # slice) pairs on one shard grows the table instead of killing the
        # job (SURVEY hard-part (e)); device arrays stay uniform [P, cap]
        # sized to the LARGEST shard index (SPMD shape requirement)
        self.indexes = self._make_shard_indexes()
        self._init_spill(spill_dir, spill_host_max_bytes)
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._replicated = NamedSharding(mesh, P())
        self._reserve_rows(self.P * self.capacity)
        self.accs: Tuple[jnp.ndarray, ...] = tuple(
            jax.device_put(
                jnp.full((self.P, self.capacity), leaf.identity,
                         dtype=leaf.dtype),
                self._sharding)
            for leaf in agg.leaves
        )
        self._build_steps()
        # window lifecycle metadata is global: watermarks and window ends are
        # aligned across shards
        self.book = SliceBookkeeper(assigner, allowed_lateness)
        # incremental-snapshot bookkeeping, the mesh form of
        # SlotTable._dirty: a [P, capacity] host bitmap of slots touched
        # since the last snapshot + namespaces freed since (tombstones)
        self._dirty = np.zeros((self.P, self.capacity), dtype=bool)
        #: freed-namespace tombstone chunks (int64 arrays, deduped at
        #: snapshot time)
        self._freed_ns: List[np.ndarray] = []
        self._gather_bucket = 0

    @property
    def late_records_dropped(self) -> int:
        return self.book.late_records_dropped

    # -------------------------------------------------------- jitted programs

    def _build_steps(self) -> None:
        (self._scatter_step, self._fire_step, self._reset_step,
         self._gather_step, self._put_step, self._merge_step,
         self._valued_scatter_step) = build_mesh_steps(self.mesh, self.agg)
        # the fused exchange+scatter pair (device shuffle mode); built
        # through the shared program cache regardless of mode so a
        # mode flip or a second tenant never pays a family build
        self._exchange_scatter_step = build_exchange_scatter(
            self.mesh, self.agg, valued=False)
        self._exchange_valued_step = build_exchange_scatter(
            self.mesh, self.agg, valued=True)
        if self._two_level_active():
            from flink_tpu.parallel.exchange2 import (
                build_exchange2_steps,
            )

            self._exchange2_steps = build_exchange2_steps(
                self.mesh, self.host_topology, self.agg, valued=False)
            self._exchange2_valued = build_exchange2_steps(
                self.mesh, self.host_topology, self.agg, valued=True)

    def _shard_index_grew(self, new_capacity: int) -> None:
        """One shard's index outgrew the device column count: widen the
        [P, capacity] arrays (all shards — SPMD shapes are uniform; the
        other shards' indexes keep their smaller capacities and simply
        address a prefix)."""
        if new_capacity <= self.capacity:
            return
        self._reserve_rows(self.P * (new_capacity - self.capacity))
        old = self.capacity
        self.capacity = new_capacity
        grown = []
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        for host, leaf in zip(accs_host, self.agg.leaves):
            padded = np.full((self.P, new_capacity), leaf.identity,
                             dtype=leaf.dtype)
            padded[:, :old] = host
            grown.append(jax.device_put(jnp.asarray(padded),
                                        self._sharding))
        self.accs = tuple(grown)
        dirty = np.zeros((self.P, new_capacity), dtype=bool)
        dirty[:, :old] = self._dirty
        self._dirty = dirty


    def _put_sharded(self, host_block: np.ndarray) -> jnp.ndarray:
        return jax.device_put(host_block, self._sharding)

    # ---------------------------------------------------------------- ingest

    def _ns_group_plan(self, key_ids: np.ndarray,
                       slice_ends: np.ndarray) -> Optional[List[List[int]]]:
        """When one batch's touched-namespace working set exceeds the
        per-shard budget, plan namespace groups so only one group must be
        resident at a time (the mesh form of SlotTable.upsert's chunking;
        a single namespace whose per-shard key set alone exceeds the
        budget is the irreducible limit and fails loudly downstream).

        Cost of a namespace = max over shards of (resident rows + spilled
        rows + this batch's new pairs) — the slots it needs while its
        group is being scattered. Returns None when no chunking is needed.
        """
        from flink_tpu.state.slot_table import unique_pairs

        pk, pns, _ = unique_pairs(
            np.asarray(key_ids, dtype=np.int64),
            np.asarray(slice_ends, dtype=np.int64))
        uniq_ns = np.unique(pns)
        if len(uniq_ns) <= 1:
            return None
        budget = max(self.max_device_slots // 2, 1024)
        pshards = self._route(pk)
        costs: Dict[int, int] = {}
        for ns in uniq_ns.tolist():
            ns = int(ns)
            sel = pns == ns
            per_shard_new = np.bincount(pshards[sel], minlength=self.P)
            worst = 0
            for p in range(self.P):
                worst = max(
                    worst,
                    len(self.indexes[p].slots_for_namespace(ns))
                    + self.spills[p].rows(ns)
                    + int(per_shard_new[p]))
            costs[ns] = worst
        if sum(costs.values()) <= budget:
            return None
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_cost = 0
        for ns in sorted(costs):
            c = costs[ns]
            if cur and cur_cost + c > budget:
                groups.append(cur)
                cur, cur_cost = [], 0
            cur.append(ns)
            cur_cost += c
        groups.append(cur)
        return groups if len(groups) > 1 else None

    def process_batch(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        with self._flight_ingest():
            self._process_batch_inner(batch)

    def _process_batch_inner(self, batch: RecordBatch) -> None:
        n = len(batch)
        # batch boundary: the engine is consistent at a known source
        # position — the one point the watchdog may declare a shard dead
        self._wd_boundary()
        key_ids = batch.key_ids
        slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
        if self._spill_active and n > 1:
            groups = self._ns_group_plan(key_ids, slice_ends)
            if groups is not None:
                for g in groups:
                    mask = np.isin(slice_ends, np.asarray(g))
                    if mask.any():
                        self._ingest_subbatch(batch.filter(mask))
                return
        live = self.book.live_mask(slice_ends)
        if live is not None:
            key_ids, slice_ends = key_ids[live], slice_ends[live]
            batch = batch.filter(live)
            if len(batch) == 0:
                return
        self.book.register_slices(slice_ends)

        # route to owning shard, bucket into [P, B] blocks
        shards = self._route(key_ids)
        from flink_tpu.runtime.local_agg import (
            is_partial_batch,
            partial_leaf_values,
        )

        partial = is_partial_batch(batch)
        if partial:
            # locally pre-aggregated rows (two-phase agg): one explicit
            # value per ACC leaf, folded with the valued scatter (the
            # mesh form of SlotTable.upsert_valued)
            values = partial_leaf_values(batch, self.agg)
            leaves = self.agg.leaves
        else:
            values = self.agg.map_input(batch)
            leaves = self.agg.input_leaves
        if self.shuffle_mode == "device":
            self._process_batch_device(key_ids, slice_ends, shards,
                                       values, leaves, partial)
            return
        # pipelining: wait for a dispatch slot BEFORE rewriting the
        # pooled staging buffers, then bucket while the device still
        # runs the previous batches
        self._await_dispatch_slot()
        self._shuffle_pool.flip()
        counts, blocked = bucket_by_shard(
            shards, self.P,
            columns=[key_ids, slice_ends,
                     *[np.asarray(v, dtype=l.dtype)
                       for v, l in zip(values, leaves)]],
            fills=[0, 0, *[l.identity for l in leaves]],
            pool=self._shuffle_pool,
        )
        key_block, ns_block = blocked[0], blocked[1]
        value_blocks = blocked[2:]

        if self._spill_active:
            # reload spilled namespaces this batch touches (batched across
            # shards), then refresh recency
            touched = {p: np.unique(ns_block[p, :int(counts[p])])
                       for p in range(self.P) if int(counts[p])}
            self._ensure_resident(touched)
            for p, nss in touched.items():
                self._touch(p, nss.tolist())

        # per-shard slot assignment (host)
        B = key_block.shape[1]
        slot_block = np.zeros((self.P, B), dtype=np.int32)
        for p in range(self.P):
            c = int(counts[p])
            if not c:
                continue
            self._reserve(p, key_block[p, :c], ns_block[p, :c])
            slot_block[p, :c] = self.indexes[p].lookup_or_insert(
                key_block[p, :c], ns_block[p, :c])
            self._dirty[p, slot_block[p, :c]] = True
            self._rep_mark(p, slot_block[p, :c])

        step = self._valued_scatter_step if partial else self._scatter_step
        with self._device_span():
            self.accs = step(
                self.accs,
                self._put_sharded(slot_block),
                tuple(self._put_sharded(v) for v in value_blocks),
            )
        self._push_dispatch_fence()

    def _process_batch_device(self, key_ids, slice_ends, shards, values,
                              leaves, partial: bool) -> None:
        """Device-shuffle ingest: the host resolves slots (the index is
        host state) but never sorts or blocks the record columns — flat
        padded columns go up in ONE device_put and the fused
        exchange+scatter program (segment sort + all_to_all + scatter,
        one XLA program) routes them to their owner shards."""
        n = len(key_ids)
        # per-shard grouping for the HOST index work only: one stable
        # argsort over the destinations, contiguous slices per shard
        order = np.argsort(shards, kind="stable")
        counts = np.bincount(shards, minlength=self.P)
        offsets = np.zeros(self.P + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        s_keys = key_ids[order]
        s_ns = slice_ends[order]
        if self._spill_active:
            touched = {
                p: np.unique(s_ns[offsets[p]:offsets[p + 1]])
                for p in range(self.P) if counts[p]}
            self._ensure_resident(touched)
            for p, nss in touched.items():
                self._touch(p, nss.tolist())
        slots_sorted = np.empty(n, dtype=np.int32)
        for p in range(self.P):
            a, b = int(offsets[p]), int(offsets[p + 1])
            if a == b:
                continue
            self._reserve(p, s_keys[a:b], s_ns[a:b])
            slots = self.indexes[p].lookup_or_insert(
                s_keys[a:b], s_ns[a:b])
            slots_sorted[a:b] = slots
            self._dirty[p, slots] = True
            self._rep_mark(p, slots)
        rec_slots = np.empty(n, dtype=np.int32)
        rec_slots[order] = slots_sorted
        # pipelining: claim a dispatch slot BEFORE rewriting the pooled
        # flat staging buffers (their previous consumer must have
        # finished — the same fence discipline as the host blocks)
        self._await_dispatch_slot()
        self._shuffle_pool.flip()
        columns = [rec_slots,
                   *[np.asarray(v, dtype=l.dtype)
                     for v, l in zip(values, leaves)]]
        fills = [0, *[l.identity for l in leaves]]
        if self._two_level_active():
            # pod mesh: the two-level ICI/DCN exchange — stage 1 routes
            # by destination local index over the intra-host axis,
            # stage 2 batches the cross-host residue over the hosts
            # axis and scatters in global stream order (bit-identical
            # to the flat program; two dispatches so the recorder can
            # attribute ICI vs DCN time)
            from flink_tpu.parallel.exchange2 import (
                stage_two_level_exchange,
            )

            with flight.span("prep.stage"):
                dst, staged, w1, w2 = stage_two_level_exchange(
                    shards, self.host_topology, columns=columns,
                    fills=fills, pool=self._shuffle_pool,
                    traffic=self._exchange2_traffic)
            s1, s2 = (self._exchange2_valued if partial
                      else self._exchange2_steps)
            with self._device_span(), flight.span("exchange.stage1"):
                put = jax.device_put((dst, *staged), self._sharding)
                inter = s1(put[0], put[1], tuple(put[2:]), w1)
            with self._device_span(), flight.span("exchange.stage2"):
                self.accs = s2(self.accs, inter[0], inter[1],
                               tuple(inter[2:]), w2)
        else:
            dst, staged, width = stage_device_exchange(
                shards, self.P,
                columns=columns,
                fills=fills,
                pool=self._shuffle_pool,
            )
            with self._device_span():
                # ONE host->device hop for the whole batch: every flat
                # column in a single device_put against the key-group
                # sharding
                put = jax.device_put((dst, *staged), self._sharding)
                step = (self._exchange_valued_step if partial
                        else self._exchange_scatter_step)
                self.accs = step(self.accs, put[0], put[1],
                                 tuple(put[2:]), width)
        # "crash mid-batch after the fused dispatch": the scatter is in
        # flight on the device queue, the host dies before the fence —
        # the hardest restore case for the device data plane
        chaos.fault_point("shuffle.device_exchange", records=n)
        self._push_dispatch_fence()

    # ------------------------------------------------------------------ fire

    #: fires may be dispatched async (on_watermark(async_ok=True)
    #: returns PendingFire handles): the fire kernel and its D2H copies
    #: overlap the next ingest step's host prep, and the harvest is ONE
    #: batched device_get once the copies land — the mesh window form
    #: of the session engine's overlapped fire harvests (latency tier)
    supports_async_fires = True

    def on_watermark(self, watermark: int,
                     async_ok: bool = False) -> List[RecordBatch]:
        self._wd_boundary()
        with flight.fire_span(watermark):
            out = self._on_watermark_inner(watermark, async_ok)
        # replica publish AFTER the fires/frees of this boundary (and
        # outside the fire span — it is serving-plane work, budgeted
        # under its own serving.replica_publish span)
        self._publish_replica(watermark)
        return out

    def _on_watermark_inner(self, watermark: int,
                            async_ok: bool = False) -> List[RecordBatch]:
        out: List[RecordBatch] = []
        while True:
            w_end = self.book.next_window(watermark)
            if w_end is None:
                break
            batch = self._fire_window(w_end, async_ok=async_ok)
            if batch is not None and (not hasattr(batch, "__len__")
                                      or len(batch) > 0):
                out.append(batch)
            self.book.mark_fired(w_end)
        expired = self.book.expired_slices(watermark)
        if expired:
            # the donated reset is device-queue-ordered BEHIND the fire
            # kernels dispatched above, so a deferred (async) host read
            # of the fire outputs never races the frees
            self._free_slices(expired)
        return out

    def _fire_window(self, window_end: int,
                     async_ok: bool = False) -> Optional[RecordBatch]:
        chaos.fault_point("mesh.window_fire", window_end=window_end)
        slice_ends = self.assigner.slice_ends_for_window(window_end)
        if self._any_spilled(slice_ends):
            # hybrid fire: resident slices merge on device (one kernel),
            # spilled slices merge on host — the device budget stays
            # independent of the window's slice count (the mesh form of
            # SlotTable.fire_hybrid). Host-merged values are already on
            # the host, so there is nothing to defer: stays synchronous
            # inside an async on_watermark.
            return self._fire_window_hybrid(window_end, slice_ends)
        k = len(slice_ends)
        per_shard_mats: List[np.ndarray] = []
        per_shard_keys: List[np.ndarray] = []
        w_max = 0
        for p in range(self.P):
            idx = self.indexes[p]
            chunks = [(i, idx.slots_for_namespace(se))
                      for i, se in enumerate(slice_ends)]
            chunks = [(i, s) for i, s in chunks if len(s) > 0]
            if not chunks:
                per_shard_mats.append(np.zeros((0, k), dtype=np.int32))
                per_shard_keys.append(np.empty(0, dtype=np.int64))
                continue
            all_slots = np.concatenate([s for _, s in chunks])
            all_sidx = np.concatenate(
                [np.full(len(s), i, dtype=np.int32) for i, s in chunks])
            all_keys = idx.slot_key[all_slots]
            keys, inv = np.unique(all_keys, return_inverse=True)
            mat = np.zeros((len(keys), k), dtype=np.int32)
            mat[inv, all_sidx] = all_slots
            per_shard_mats.append(mat)
            per_shard_keys.append(keys)
            w_max = max(w_max, len(keys))
        if w_max == 0:
            return None
        W = sticky_bucket(w_max, getattr(self, "_fire_bucket", 0), minimum=64)
        self._fire_bucket = W
        sm = np.zeros((self.P, W, k), dtype=np.int32)
        for p, mat in enumerate(per_shard_mats):
            sm[p, : len(mat)] = mat
        fire_out = self._fire_step(self.accs, self._put_sharded(sm))
        names = sorted(fire_out.keys())
        projector = self.fire_projector
        w_start = self.assigner.window_start(window_end)
        per_keys = per_shard_keys  # host arrays, stable after dispatch

        def build(host: List[np.ndarray]) -> Optional[RecordBatch]:
            key_cols: List[np.ndarray] = []
            res_cols: Dict[str, List[np.ndarray]] = {n: [] for n in names}
            for p in range(len(per_keys)):
                m = len(per_keys[p])
                if m == 0:
                    continue
                key_cols.append(per_keys[p])
                for name, arr in zip(names, host):
                    res_cols[name].append(arr[p][:m])
            keys = np.concatenate(key_cols)
            merged = {name: np.concatenate(chunks)
                      for name, chunks in res_cols.items()}
            if projector is not None:
                keys, merged = projector.project_host(keys, merged)
            m = len(keys)
            cols = {
                KEY_ID_FIELD: keys,
                WINDOW_START_FIELD: np.full(m, w_start, dtype=np.int64),
                WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
                TIMESTAMP_FIELD: np.full(m, window_end - 1,
                                         dtype=np.int64),
            }
            cols.update(merged)
            return RecordBatch(cols)

        if async_ok:
            from flink_tpu.runtime.pending import PendingFire

            # overlapped fire harvest: the kernel + D2H copies run while
            # the task loop keeps ingesting; the harvest is one batched
            # device_get when the copies land (runtime/pending.py)
            return PendingFire([fire_out[n] for n in names], build,
                               watchdog=self._watchdog)
        # sync path still batches all columns into ONE device_get
        return build(self._harvest_get([fire_out[n] for n in names]))

    def _fire_window_hybrid(self, window_end: int,
                            slice_ends) -> Optional[RecordBatch]:
        from flink_tpu.ops.segment_ops import HOST_COMBINE

        k = len(slice_ends)
        leaves = self.agg.leaves
        # device part: per-shard slot matrices over RESIDENT slices (the
        # index only knows resident namespaces), merged raw on device
        per_shard_mats: List[np.ndarray] = []
        per_shard_keys: List[np.ndarray] = []
        w_max = 0
        for p in range(self.P):
            idx = self.indexes[p]
            chunks = [(i, idx.slots_for_namespace(int(se)))
                      for i, se in enumerate(slice_ends)]
            chunks = [(i, s) for i, s in chunks if len(s) > 0]
            if not chunks:
                per_shard_mats.append(np.zeros((0, k), dtype=np.int32))
                per_shard_keys.append(np.empty(0, dtype=np.int64))
                continue
            all_slots = np.concatenate([s for _, s in chunks])
            all_sidx = np.concatenate(
                [np.full(len(s), i, dtype=np.int32) for i, s in chunks])
            all_keys = idx.slot_key[all_slots]
            keys, inv = np.unique(all_keys, return_inverse=True)
            mat = np.zeros((len(keys), k), dtype=np.int32)
            mat[inv, all_sidx] = all_slots
            per_shard_mats.append(mat)
            per_shard_keys.append(keys)
            w_max = max(w_max, len(keys))
        key_chunks: List[np.ndarray] = []
        leaf_chunks: List[List[np.ndarray]] = [[] for _ in leaves]
        if w_max:
            W = sticky_bucket(w_max, getattr(self, "_fire_bucket", 0),
                              minimum=64)
            self._fire_bucket = W
            sm = np.zeros((self.P, W, k), dtype=np.int32)
            for p, mat in enumerate(per_shard_mats):
                sm[p, : len(mat)] = mat
            merged = self._merge_step(self.accs, self._put_sharded(sm))
            merged_host = self._harvest_get(merged)  # ONE batched D2H
            for p in range(self.P):
                m = len(per_shard_keys[p])
                if m == 0:
                    continue
                key_chunks.append(per_shard_keys[p])
                for i in range(len(leaves)):
                    leaf_chunks[i].append(merged_host[i][p][:m])
        # host part: spilled slices of this window, every shard
        for p in range(self.P):
            sp = self.spills[p]
            for se in slice_ends:
                entry = sp.peek(int(se))
                if entry is None or len(entry["key_id"]) == 0:
                    continue
                key_chunks.append(
                    np.asarray(entry["key_id"], dtype=np.int64))
                for i, l in enumerate(leaves):
                    leaf_chunks[i].append(
                        np.asarray(entry[f"leaf_{i}"], dtype=l.dtype))
        if not key_chunks:
            return None
        all_keys = np.concatenate(key_chunks)
        uniq, inv = np.unique(all_keys, return_inverse=True)
        out_leaves = []
        for i, l in enumerate(leaves):
            acc = np.full(len(uniq), l.identity, dtype=l.dtype)
            HOST_COMBINE[l.reduce].at(acc, inv,
                                      np.concatenate(leaf_chunks[i]))
            out_leaves.append(acc)
        finished = self.agg.finish(tuple(out_leaves))
        merged_cols = {name: np.asarray(col)
                       for name, col in finished.items()}
        keys = uniq
        if self.fire_projector is not None:
            keys, merged_cols = self.fire_projector.project_host(
                keys, merged_cols)
        m = len(keys)
        if m == 0:
            return None
        cols = {
            KEY_ID_FIELD: keys,
            WINDOW_START_FIELD: np.full(
                m, self.assigner.window_start(window_end), dtype=np.int64),
            WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
            TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
        }
        cols.update(merged_cols)
        return RecordBatch(cols)

    def _free_slices(self, ends: List[int]) -> None:
        f_max = 0
        freed: List[Optional[np.ndarray]] = []
        self._freed_ns.append(np.asarray(list(ends), dtype=np.int64))
        self._drop_spilled(ends)
        for p in range(self.P):
            slots = self.indexes[p].free_namespaces(ends)
            freed.append(slots)
            if slots is not None:
                self._dirty[p, slots] = False
                f_max = max(f_max, len(slots))
        if f_max == 0:
            return
        F = sticky_bucket(f_max, getattr(self, "_reset_bucket", 0))
        self._reset_bucket = F
        block = np.zeros((self.P, F), dtype=np.int32)
        for p, slots in enumerate(freed):
            if slots is not None:
                block[p, : len(slots)] = slots
        self.accs = self._reset_step(self.accs, self._put_sharded(block))

    # ---------------------------------------------------------- point query

    def query_windows(self, key_id: int) -> Dict[int, Dict[str, float]]:
        """Queryable-state point lookup — a batch of one (the serving
        plane routes ALL reads through :meth:`query_batch`)."""
        return self.query_batch(
            np.asarray([key_id], dtype=np.int64))[0]

    def query_batch(self, key_ids) -> List[Dict[int, Dict[str, float]]]:
        """Batched point lookup, mesh form: every requested key routes to
        its owning shard (the key-group formula the data path uses), the
        whole batch's resident slice accumulators come back through ONE
        gather program + ONE batched device read, spilled slices answer
        from their shards' host tiers, and window results compose on host
        (slice sharing, as SlotTable.query_windows). Read-only — no
        residency change, no sticky-bucket mutation. One result dict
        ({window_end -> columns}) per requested key, request order."""
        from flink_tpu.windowing.windower import compose_windows

        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        if n == 0:
            return []
        leaves = self.agg.leaves
        shards = self._route(key_ids)
        #: per request row: slice end -> per-leaf 1-element raw values
        slice_vals: List[Dict[int, Tuple[np.ndarray, ...]]] = [
            {} for _ in range(n)]
        # resident probe: (requested keys on shard) x (live namespaces),
        # all shards' hits land in one [P, G] gather block
        lanes: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        g_max = 0
        for p in range(self.P):
            rows_p = np.nonzero(shards == p)[0]
            if not len(rows_p):
                continue
            idx = self.indexes[p]
            live_ns = np.asarray([int(x) for x in idx.namespaces],
                                 dtype=np.int64)
            if not len(live_ns):
                continue
            pk = np.repeat(key_ids[rows_p], len(live_ns))
            pn = np.tile(live_ns, len(rows_p))
            prow = np.repeat(rows_p, len(live_ns))
            slots = idx.lookup(pk, pn)
            hit = slots >= 0
            if hit.any():
                lanes[p] = (slots[hit].astype(np.int32), prow[hit],
                            pn[hit])
                g_max = max(g_max, int(hit.sum()))
        if lanes:
            G = pad_bucket_size(g_max, minimum=64)
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, (hs, _, _) in lanes.items():
                block[p, : len(hs)] = hs
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            # ONE batched D2H
            g_host = self._harvest_get(gathered, "serving_lookup")
            for p, (hs, prow, pn) in lanes.items():
                shard_leaves = [g[p] for g in g_host]
                for j in range(len(hs)):
                    slice_vals[int(prow[j])][int(pn[j])] = tuple(
                        g[j:j + 1] for g in shard_leaves)
        if self._spill_active:
            for p in range(self.P):
                rows_p = np.nonzero(shards == p)[0]
                if not len(rows_p):
                    continue
                sp = self.spills[p]
                if len(sp) == 0:
                    continue
                want = key_ids[rows_p]
                for ns in sp.namespaces:
                    entry = sp.peek(int(ns))
                    if entry is None:
                        continue
                    ek = np.asarray(entry["key_id"], dtype=np.int64)
                    if not len(ek):
                        continue
                    order = np.argsort(ek, kind="stable")
                    pos = np.searchsorted(ek[order], want)
                    pos = np.minimum(pos, len(ek) - 1)
                    ok = ek[order][pos] == want
                    for j in np.nonzero(ok)[0].tolist():
                        src = int(order[pos[j]])
                        slice_vals[int(rows_p[j])][int(ns)] = tuple(
                            np.asarray(entry[f"leaf_{i}"],
                                       dtype=l.dtype)[src:src + 1]
                            for i, l in enumerate(leaves))
        results: List[Dict[int, Dict[str, float]]] = []
        for r in range(n):
            sv = slice_vals[r]
            results.append(compose_windows(self.assigner, self.agg, sv)
                           if sv else {})
        return results

    # -------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        """Logical snapshot merged over shards, re-shardable by key group.

        mode: "full" (new incremental base), "delta" (dirty rows +
        tombstones only), "savepoint" (full, preserving dirty tracking) —
        the same contract as SliceSharedWindower.snapshot, so mesh and
        single-device checkpoints are mutually restorable."""
        if mode == "delta":
            return {"table": self._snapshot_delta(), **self.book.snapshot()}
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        parts = []
        for p in range(self.P):
            idx = self.indexes[p]
            used = idx.used_slots()
            key_ids = idx.slot_key[used]
            parts.append({
                "key_id": key_ids,
                "namespace": idx.slot_ns[used],
                "key_group": assign_key_groups(key_ids, self.max_parallelism),
                **{f"leaf_{i}": accs_host[i][p][used]
                   for i in range(len(self.accs))},
            })
        # spilled namespaces are part of the logical state
        parts.extend(self._spill_snapshot_parts())
        merged = {
            k: np.concatenate([pt[k] for pt in parts]) for k in parts[0]
        } if parts else {}
        if mode != "savepoint":
            self._dirty[:] = False
            self._freed_ns.clear()
            for sp in self.spills:
                sp.clear_dirty()
        return {"table": merged, **self.book.snapshot()}

    def _snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Dirty rows gathered off the device in ONE sharded program +
        freed-namespace tombstones (same format as SlotTable.snapshot_delta)."""
        per_shard = []
        g_max = 0
        for p in range(self.P):
            used = self.indexes[p].slot_used
            dirty = np.nonzero(self._dirty[p][:len(used)]
                               & used)[0].astype(np.int32)
            per_shard.append(dirty)
            g_max = max(g_max, len(dirty))
        freed = (np.unique(np.concatenate(self._freed_ns))
                 if self._freed_ns else np.empty(0, dtype=np.int64))
        if g_max == 0:
            empty = {f"leaf_{i}": np.empty(0, dtype=l.dtype)
                     for i, l in enumerate(self.agg.leaves)}
            out = {
                "__delta__": np.asarray(True),
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "key_group": np.empty(0, dtype=np.int32),
                "freed_namespaces": freed,
                **empty,
            }
        else:
            G = sticky_bucket(g_max, self._gather_bucket)
            self._gather_bucket = G
            block = np.zeros((self.P, G), dtype=np.int32)
            for p, dirty in enumerate(per_shard):
                block[p, :len(dirty)] = dirty
            gathered = self._gather_step(self.accs,
                                         self._put_sharded(block))
            leaves_host = jax.device_get(list(gathered))  # ONE batched D2H
            key_cols, ns_cols = [], []
            leaf_cols = [[] for _ in leaves_host]
            for p, dirty in enumerate(per_shard):
                m = len(dirty)
                if m == 0:
                    continue
                idx = self.indexes[p]
                key_cols.append(idx.slot_key[dirty])
                ns_cols.append(idx.slot_ns[dirty])
                for i, lh in enumerate(leaves_host):
                    leaf_cols[i].append(lh[p][:m])
            key_ids = np.concatenate(key_cols)
            out = {
                "__delta__": np.asarray(True),
                "key_id": key_ids,
                "namespace": np.concatenate(ns_cols),
                "key_group": assign_key_groups(key_ids,
                                               self.max_parallelism),
                "freed_namespaces": freed,
                **{f"leaf_{i}": np.concatenate(cols)
                   for i, cols in enumerate(leaf_cols)},
            }
        self._spill_delta_append(out)
        self._dirty[:] = False
        self._freed_ns.clear()
        return out

    def restore(self, snap: Dict[str, object],
                key_group_filter=None) -> None:
        """Restore, re-sharding by key group (works across mesh sizes).

        ``key_group_filter``: keep only rows in these GLOBAL key groups
        (subtask-expansion restore — the mesh x stage composition
        restores the merged logical snapshot into each subtask's owned
        range)."""
        table = snap["table"]
        key_ids = np.asarray(table["key_id"], dtype=np.int64)
        namespaces = np.asarray(table["namespace"], dtype=np.int64)
        leaves = [np.asarray(table[f"leaf_{i}"])
                  for i in range(len(self.agg.leaves))]
        if key_group_filter is not None and len(key_ids):
            groups = assign_key_groups(key_ids, self.max_parallelism)
            mask = np.isin(groups, np.asarray(sorted(key_group_filter)))
            key_ids, namespaces = key_ids[mask], namespaces[mask]
            leaves = [v[mask] for v in leaves]
        if self._spill_active and len(key_ids):
            self._spill_restore_rows(key_ids, namespaces, leaves)
        elif len(key_ids):
            shards = self._route(key_ids)
            # resolve ALL slots first: inserts may grow the table
            # (on_grow widens self.accs / self.capacity), so the host
            # copy must be taken only after growth has settled
            per_shard_slots: Dict[int, np.ndarray] = {}
            for p in range(self.P):
                mask = shards == p
                if mask.any():
                    per_shard_slots[p] = self.indexes[p].lookup_or_insert(
                        key_ids[mask], namespaces[mask])
            # one batched D2H read, then writable copies (restore
            # mutates them in place before re-uploading)
            accs_host = [np.array(a)
                         for a in jax.device_get(list(self.accs))]
            for p, slots in per_shard_slots.items():
                mask = shards == p
                for acc, vals in zip(accs_host, leaves):
                    acc[p][slots] = vals[mask]
            self.accs = tuple(
                jax.device_put(jnp.asarray(a), self._sharding)
                for a in accs_host)
        # restored state IS the new incremental base
        self._dirty[:] = False
        self._freed_ns.clear()
        for sp in self.spills:
            sp.clear_dirty()
        # restored VALUES bypass the scatter sites — the replica shadow
        # is stale wholesale; republish everything at the next boundary
        self._rep_rebuild = True
        self.book.restore(snap)

    # ------------------------------------------------ partial-failover hooks

    def _merge_restored_meta(self, snap, groups) -> None:
        # window lifecycle metadata is global: the book merge re-opens
        # the windows the restored range must re-fire during replay
        self.book.merge_restore(snap)

    def _merge_meta_snapshots(self, units):
        _NEG = -(1 << 62)
        pending = sorted({int(w) for u in units
                          for w in u.get("pending", ())})
        slw: Dict[int, int] = {}
        for u in units:
            slw.update(dict(u.get("slice_last_window", {})))
        return {
            "pending": pending,
            "slice_last_window": slw,
            # the OLDEST unit decides: its range's records replay from
            # its position and must pass the late-record guard exactly
            # as they originally did
            "watermark": min((u.get("watermark", _NEG) for u in units),
                             default=_NEG),
            "max_fired_end": min(
                (u.get("max_fired_end", _NEG) for u in units),
                default=_NEG),
            "late_records_dropped": max(
                (u.get("late_records_dropped", 0) for u in units),
                default=0),
        }


def build_mesh_steps(mesh: Mesh, agg: AggregateFunction):
    """(scatter, fire, reset, gather, put, merge) shard_map step programs
    over a [P, capacity] sharded slot table — shared by the mesh window and
    mesh session engines (cached per (devices, aggregate layout)).

    ``put`` overwrites slots with explicit per-leaf values (spill reload);
    ``merge`` is fire without the finish — raw merged leaves come back to
    the host so spilled slices can be combined there (the mesh form of
    SlotTable.fire_hybrid)."""
    cache_key = (tuple(d.id for d in mesh.devices.flat), agg.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "mesh-steps", cache_key, lambda: _build_mesh_steps(mesh, agg))


def _build_mesh_steps(mesh: Mesh, agg: AggregateFunction):
    leaves = agg.leaves
    methods = tuple(SCATTER_METHOD[l.reduce] for l in agg.leaves)
    merges = tuple(MERGE_FN[l.reduce] for l in agg.leaves)
    idents = tuple(l.identity for l in agg.leaves)
    finish = agg.finish
    n_leaves = len(agg.leaves)
    n_inputs = len(agg.input_leaves)

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_step(accs, slots, values):
        # accs: ([P, cap], ...) sharded; slots: [P, B]; values: one
        # [P, B] block per *input* leaf (const leaves broadcast on device)
        def local(*args):
            accs_l = args[:n_leaves]          # each [1, cap]
            slots_l = args[n_leaves]          # [1, B]
            vals_l = iter(args[n_leaves + 1:])  # each [1, B]
            # .at[...].op() returns the full [1, cap] block
            out = []
            for a, m, l in zip(accs_l, methods, leaves):
                if l.const is not None:
                    # padded lanes target identity slot 0 — keep it pure
                    v = jnp.where(
                        slots_l[0] == 0,
                        jnp.asarray(l.identity, dtype=l.dtype),
                        jnp.asarray(l.const, dtype=l.dtype))
                else:
                    v = next(vals_l)[0]
                out.append(getattr(a.at[0, slots_l[0]], m)(v))
            return tuple(out)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1 + n_inputs),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots, *values)

    # hoisted so the jitted closures capture only plain values, never
    # an engine (the step cache outlives engines; a capture would pin
    # the first engine's device arrays in memory for the process)
    names = sorted(agg.output_names)

    @jax.jit
    def fire_step(accs, slot_matrix):
        # slot_matrix: [P, W, k] sharded -> result cols each [P, W]
        def local(*args):
            accs_l = args[:n_leaves]          # [1, cap]
            sm = args[n_leaves][0]            # [W, k]
            merged = tuple(
                m(a[0][sm], axis=1) for a, m in zip(accs_l, merges))
            out = finish(merged)              # dict name -> [W]
            return tuple(out[name][None] for name in names)

        outs = shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * len(names),
        )(*accs, slot_matrix)
        return dict(zip(names, outs))

    @partial(jax.jit, donate_argnums=(0,))
    def reset_step(accs, slots):
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            return tuple(
                a.at[0, slots_l[0]].set(i)
                for a, i in zip(accs_l, idents)
            )

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots)

    @jax.jit
    def gather_step(accs, slots):
        # slots: [P, G] sharded -> per-leaf [P, G] raw accumulator
        # values (delta-snapshot / point-query readback)
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            return tuple(a[0][slots_l[0]][None] for a in accs_l)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots)

    @partial(jax.jit, donate_argnums=(0,))
    def put_step(accs, slots, values):
        # slots: [P, B]; values: one [P, B] block per LEAF — overwrite
        # semantics (spill reload into slots just reset to identity).
        # Padded lanes target slot 0 with identity values: harmless.
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            vals_l = args[n_leaves + 1:]
            return tuple(a.at[0, slots_l[0]].set(v[0])
                         for a, v in zip(accs_l, vals_l))

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (2 * n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots, *values)

    @jax.jit
    def merge_step(accs, slot_matrix):
        # slot_matrix: [P, W, k] sharded -> per-leaf [P, W] RAW merged
        # accumulators (no finish) for host-side hybrid-fire composition
        def local(*args):
            accs_l = args[:n_leaves]
            sm = args[n_leaves][0]
            return tuple(
                m(a[0][sm], axis=1)[None]
                for a, m in zip(accs_l, merges))

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slot_matrix)

    @partial(jax.jit, donate_argnums=(0,))
    def valued_scatter_step(accs, slots, values):
        # slots: [P, B]; values: one explicit [P, B] block per ACC LEAF
        # (locally pre-aggregated partials, flink_tpu/runtime/local_agg) —
        # folded with each leaf's own reduce; no const shortcut (a
        # partial COUNT is the combined count, not 1). The mesh form of
        # SlotTable.scatter_valued; decomposability guarantees the
        # per-leaf reduce merges partials exactly.
        def local(*args):
            accs_l = args[:n_leaves]
            slots_l = args[n_leaves]
            vals_l = args[n_leaves + 1:]
            return tuple(
                getattr(a.at[0, slots_l[0]], m)(v[0])
                for a, m, v in zip(accs_l, methods, vals_l))

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (2 * n_leaves + 1),
            out_specs=(P(KEY_AXIS),) * n_leaves,
        )(*accs, slots, *values)

    return (scatter_step, fire_step, reset_step, gather_step,
            put_step, merge_step, valued_scatter_step)


def build_delta_fire_step(mesh: Mesh, agg: AggregateFunction):
    """The delta-harvest program: fire + reset FUSED into one compiled
    program — ``merge+finish`` over each closing row's slots, then the
    fired slots reset to identity, in a single dispatch (the separate
    fire_step + reset_step pair paid two). The merged reads are data-
    dependencies of the donated writes, so XLA orders them correctly;
    the fire outputs are fresh buffers, safe for deferred (async)
    harvest. Cached in the shared PROGRAM_CACHE per (devices, aggregate
    layout) — family "delta-fire", 0 steady-state compiles (shapes ride
    the same sticky fire buckets as the unfused pair)."""
    cache_key = (tuple(d.id for d in mesh.devices.flat), agg.cache_key())
    return PROGRAM_CACHE.get_or_build(
        "delta-fire", cache_key, lambda: _build_delta_fire_step(mesh, agg))


def _build_delta_fire_step(mesh: Mesh, agg: AggregateFunction):
    merges = tuple(MERGE_FN[l.reduce] for l in agg.leaves)
    idents = tuple(l.identity for l in agg.leaves)
    finish = agg.finish
    n_leaves = len(agg.leaves)
    names = sorted(agg.output_names)

    @partial(jax.jit, donate_argnums=(0,))
    def delta_fire_step(accs, slot_matrix, reset_slots):
        # slot_matrix: [P, W, k] sharded; reset_slots: [P, W] (padded
        # lanes target the reserved identity slot 0 — reset is a no-op
        # there). Returns (new accs, {name -> [P, W] result columns}).
        def local(*args):
            accs_l = args[:n_leaves]
            sm = args[n_leaves][0]       # [W, k]
            rs = args[n_leaves + 1][0]   # [W]
            merged = tuple(
                m(a[0][sm], axis=1) for a, m in zip(accs_l, merges))
            out = finish(merged)
            fresh = tuple(
                a.at[0, rs].set(jnp.asarray(i, dtype=a.dtype))
                for a, i in zip(accs_l, idents))
            return fresh + tuple(out[name][None] for name in names)

        outs = shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_leaves + 2),
            out_specs=(P(KEY_AXIS),) * (n_leaves + len(names)),
        )(*accs, slot_matrix, reset_slots)
        return tuple(outs[:n_leaves]), dict(zip(names, outs[n_leaves:]))

    return delta_fire_step

