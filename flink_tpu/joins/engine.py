"""Device-native two-input join engines over dual keyed slot tables.

The two-input form of the mesh window/session engines: both inputs ride
the keyBy data plane (``parallel.shuffle`` — device-mode fused exchange
or host bucketing) co-partitioned onto the SAME mesh axis by the same
key-group routing, so a key's left and right rows always share a shard
and every probe is shard-local. Per batch the device runs at most three
programs — the ingest put/exchange, the banded probe, and (under
budget pressure) one eviction gather — all cached in the shared
``PROGRAM_CACHE`` and shape-bounded by the ``pad_bucket_size`` /
``sticky_bucket`` tier discipline, so steady state compiles nothing
(gated by the join phase of ``tools/recompile_smoke.py``).

- :class:`MeshIntervalJoinEngine` — keyed interval join (left row at
  ``t`` matches right rows in ``[t+lower, t+upper]``,
  reference: IntervalJoinOperator.java): a banded segment-intersection
  over the two sorted row tables. A new batch probes the OTHER side's
  table before inserting into its own (pair emitted by whichever side
  arrives second — the host operator's structural dedup), with the band
  ``[lo, lo+cnt)`` resolved on host metadata and the candidates
  gathered/intersected/emitted by ONE compiled program per batch.
- :class:`MeshTemporalJoinEngine` — event-time temporal join (``FOR
  SYSTEM_TIME AS OF``, reference: TemporalRowTimeJoinOperator.java):
  the right side is a VERSIONED state plane (version boundaries are the
  per-key sorted ``ts`` column of its slot table); left rows wait for
  the combined watermark, then one searchsorted-style gather program
  per batch picks each row's latest version at-or-before its time (the
  ``W == 1`` band). Version state compacts to the reference's
  cleanupState contract on every watermark.

``backend="host"`` runs the numpy oracle: identical metadata code,
identical emission order, value movement in host arrays — the
bit-identity pin for the device path (including under forced paged
eviction and mid-stream ``reshard()``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from flink_tpu.chaos import injection as chaos
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.joins.side_table import (
    JoinSideTable,
    pair_lower_bound,
)
from flink_tpu.ops.segment_ops import pad_bucket_size, sticky_bucket
from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.paged_spill import restore_into_pages

_NEG = -(1 << 62)

SIDE_NAMES = ("left", "right")

# tiny non-donated slice enqueued after everything dispatched so far —
# its readiness proves the device consumed every earlier staging buffer
# (the join engines' form of the mesh engines' fence; jit caches per
# input sharding)
_FENCE_STEP = jax.jit(lambda a: a[:1, :1])


def _suffixed_names(left_names: Sequence[str],
                    right_names: Sequence[str],
                    suffixes: Tuple[str, str]
                    ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """Output column name per input column, matching the host join
    operators' ``_merge_columns`` convention: a name present on both
    sides gets the side suffix, everything else passes through."""
    overlap = set(left_names) & set(right_names)
    lmap = {n: (n + suffixes[0] if n in overlap else n)
            for n in left_names}
    rmap = {n: (n + suffixes[1] if n in overlap else n)
            for n in right_names}
    return lmap, rmap


class JoinEngineBase:
    """Shared machinery of the two-input engines: the dual side tables,
    the data-plane staging, eviction, probing, checkpoints, partial
    restore, live reshard and the watchdog plumbing."""

    #: subclasses set: ("interval", lower, upper) or ("temporal",)
    kind: str = ""

    def __init__(self, mesh=None, num_shards: int = 1,
                 capacity_per_shard: int = 1 << 16,
                 max_parallelism: int = 128,
                 max_device_slots: int = 0,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0,
                 key_group_range: Optional[Tuple[int, int]] = None,
                 backend: str = "device",
                 shuffle_mode: str = "device",
                 host_topology=None,
                 suffixes: Tuple[str, str] = ("_l", "_r")) -> None:
        if backend not in ("device", "host"):
            raise ValueError(
                f"backend must be 'device' or 'host', got {backend!r}")
        if shuffle_mode not in ("device", "host"):
            raise ValueError(
                f"shuffle_mode must be 'device' or 'host', got "
                f"{shuffle_mode!r}")
        self.backend = backend
        self.shuffle_mode = shuffle_mode
        self.mesh = None
        if backend == "device":
            if mesh is None:
                from flink_tpu.parallel.mesh import make_mesh

                mesh = make_mesh(num_shards)
            self.mesh = mesh
            self.P = int(mesh.devices.size)
        else:
            self.P = int(num_shards)
        self.capacity = max(int(capacity_per_shard), 256)
        self.max_device_slots = int(max_device_slots or 0)
        if self.max_device_slots:
            self.capacity = min(self.capacity,
                                max(self.max_device_slots, 256))
        self.max_parallelism = int(max_parallelism)
        if self.max_parallelism < self.P:
            raise ValueError(
                f"max_parallelism {max_parallelism} < shard count "
                f"{self.P}")
        self.key_group_range = key_group_range
        self.spill_dir = spill_dir
        self.spill_host_max_bytes = int(spill_host_max_bytes or 0)
        self.suffixes = tuple(suffixes)
        #: per-side state (created lazily at the side's first batch —
        #: the value schema is observed, like the table-runtime's
        #: late-bound row types)
        self.sides: List[Optional[JoinSideTable]] = [None, None]
        self._planes: List[Optional[tuple]] = [None, None]
        self._next_rid = 1
        #: flight-recorder batch sequence (the join engines' analog of
        #: MeshSpillSupport._flight_batch)
        self._flight_batch = 0
        # sticky compile-shape tiers (per side where shapes differ)
        self._put_bucket = [0, 0]
        self._mirror_bucket = [0, 0]
        self._probe_bucket = [0, 0]
        self._band_bucket = [0, 0]
        self._gather_bucket = 0
        if backend == "device":
            from jax.sharding import NamedSharding, PartitionSpec
            from flink_tpu.parallel.mesh import KEY_AXIS
            from flink_tpu.parallel.shuffle import ShuffleBufferPool

            self._sharding = NamedSharding(self.mesh,
                                           PartitionSpec(KEY_AXIS))
            self._pool = ShuffleBufferPool(generations=2)
            self._fences: List = []
        #: (hosts, local) factorization when the mesh spans processes:
        #: device-mode ingest then runs the two-level ICI/DCN exchange
        #: (the join twin of the mesh engines' pod path)
        self.host_topology = None
        self._exchange2_traffic = None
        if host_topology is not None:
            if backend != "device":
                raise ValueError(
                    "host_topology requires the device backend")
            host_topology.check_covers(self.P)
            from flink_tpu.parallel.exchange2 import ExchangeTraffic

            self.host_topology = host_topology
            self._exchange2_traffic = ExchangeTraffic()

    def _two_level_active(self) -> bool:
        from flink_tpu.parallel.exchange2 import two_level_active

        return two_level_active(self.host_topology, self.shuffle_mode)

    def exchange2_traffic(self) -> Dict[str, int]:
        from flink_tpu.parallel.exchange2 import ExchangeTraffic

        return ExchangeTraffic.dict_of(self._exchange2_traffic)

    # ------------------------------------------------------------- watchdog

    _watchdog = None

    def attach_watchdog(self, wd) -> None:
        self._watchdog = wd
        if wd is not None and self.mesh is not None:
            wd.rebind(self.P, [d.id for d in self.mesh.devices.flat])
            wd.set_topology(self.host_topology)

    def _wd_section(self, op: str, shard: int = -1):
        wd = self._watchdog
        if wd is None:
            from flink_tpu.runtime.watchdog import NULL_SECTION

            return NULL_SECTION
        return wd.section(op, shard)

    def _wd_boundary(self) -> None:
        wd = self._watchdog
        if wd is not None:
            wd.boundary_probe()

    def _harvest_get(self, tree, op: str = "join_probe_harvest"):
        """ONE batched D2H per harvest point (the TRC01 discipline)."""
        import jax

        from flink_tpu.observe import flight_recorder as flight

        with flight.span("fire.harvest"), self._wd_section(op):
            return jax.device_get(tree)

    def _flight_ingest(self):
        """Open the ``batch.ingest`` flight span for one
        ``process_batch`` (the one contract, shared with the mesh
        engines — see flight_recorder.ingest_span)."""
        from flink_tpu.observe import flight_recorder as flight

        self._flight_batch += 1
        return flight.ingest_span(self._flight_batch)

    def _flight_fire(self, watermark: int):
        """Open the ``fire.dispatch`` flight span for one
        ``on_watermark`` (see flight_recorder.fire_span)."""
        from flink_tpu.observe import flight_recorder as flight

        return flight.fire_span(watermark)

    # ----------------------------------------------------------- data plane

    def _drain_fences(self) -> None:
        if self.backend != "device":
            return
        while self._fences:
            # flint: disable=TRC01 -- the depth-bounded fence drain is
            # the ingest backpressure point: it blocks only when the
            # host ran a full staging generation ahead of the device
            self._fences.pop(0).block_until_ready()

    def _push_fence(self) -> None:
        import jax

        planes = self._planes[0] or self._planes[1]
        if planes is None:
            return
        with self._wd_section("dispatch_fence"):
            self._fences.append(_FENCE_STEP(planes[0]))
        # one staging generation may be in flight; the next must wait
        if len(self._fences) > 1:
            with self._wd_section("fence_drain"):
                # flint: disable=TRC01 -- see _drain_fences: this is
                # the designed double-buffer backpressure point
                self._fences.pop(0).block_until_ready()

    def _ensure_side(self, side_idx: int, batch: RecordBatch
                     ) -> JoinSideTable:
        side = self.sides[side_idx]
        if side is not None:
            return side
        schema = sorted(
            (n, np.asarray(batch[n]).dtype) for n in batch.names()
            if n not in (KEY_ID_FIELD, TIMESTAMP_FIELD))
        return self._init_side(side_idx, schema)

    def _init_side(self, side_idx: int, schema) -> JoinSideTable:
        sdir = (f"{self.spill_dir.rstrip('/')}/{SIDE_NAMES[side_idx]}"
                if self.spill_dir else None)
        side = JoinSideTable(
            self.P, self.capacity, schema,
            max_device_slots=self.max_device_slots,
            spill_dir=sdir,
            # the operator's host page-memory budget splits across the
            # two sides (each side then splits per shard)
            spill_host_max_bytes=self.spill_host_max_bytes // 2,
            backend=self.backend)
        self.sides[side_idx] = side
        if self.backend == "device":
            import jax
            import jax.numpy as jnp

            self._planes[side_idx] = tuple(
                jax.device_put(
                    jnp.zeros((self.P, side.capacity),
                              dtype=side.schema[i][1]),
                    self._sharding)
                for i in side.device_cols)
        return side

    def _check_schema(self, side: JoinSideTable,
                      batch: RecordBatch, side_idx: int) -> None:
        names = set(batch.names()) - {KEY_ID_FIELD, TIMESTAMP_FIELD}
        declared = {n for n, _ in side.schema}
        if names != declared:
            raise RuntimeError(
                f"{SIDE_NAMES[side_idx]} join input changed columns "
                f"mid-stream: {sorted(declared)} -> {sorted(names)}")

    def _shards_of(self, keys: np.ndarray) -> np.ndarray:
        from flink_tpu.parallel.shuffle import shard_records

        return shard_records(keys, self.P, self.max_parallelism,
                             self.key_group_range)

    # --------------------------------------------------------------- ingest

    def _ingest(self, side_idx: int, keys: np.ndarray, ts: np.ndarray,
                values: List[np.ndarray], shards=None) -> None:
        """Insert rows into ``side_idx``'s table: route, make headroom,
        allocate slots, merge metadata, move values (device put /
        fused exchange / host shadow). ``values`` in schema order;
        ``shards`` lets a caller that already routed these keys (the
        probe path) skip the second routing pass."""
        side = self.sides[side_idx]
        n = len(keys)
        if n == 0:
            return
        if shards is None:
            shards = self._shards_of(keys)
        # chaos: the two-input data plane. Payload kinds (drop /
        # duplicate) mutate one shard's rows BEFORE any state mutation
        # — a bucket lost or replayed in flight; raise/delay fire at
        # the post-dispatch site below (crash mid-batch with the put
        # on the device queue — the hardest restore case)
        if chaos.armed():
            mutations: Dict[int, str] = {}
            for p in np.unique(shards).tolist():
                rule = chaos.payload_action(
                    "join.exchange",
                    kinds=("drop", "duplicate", "delay"),
                    shard=int(p), side=side_idx)
                if rule is not None and rule.kind in ("drop",
                                                      "duplicate"):
                    mutations[int(p)] = rule.kind
            for p, mkind in mutations.items():
                sel = shards == p
                if mkind == "drop":
                    keep = ~sel
                    keys, ts, shards = keys[keep], ts[keep], shards[keep]
                    values = [v[keep] for v in values]
                else:
                    keys = np.concatenate([keys, keys[sel]])
                    ts = np.concatenate([ts, ts[sel]])
                    shards = np.concatenate([shards, shards[sel]])
                    values = [np.concatenate([v, v[sel]])
                              for v in values]
            n = len(keys)
            if n == 0:
                return
        self._ingest_rows(side_idx, keys, ts, values, shards)
        chaos.fault_point("join.exchange", records=n, side=side_idx)

    def _ingest_rows(self, side_idx: int, keys, ts, values,
                     shards) -> None:
        """Route/allocate/insert, bisecting when one batch's per-shard
        rows exceed the plane (the working-set bound: rows of the SAME
        chunk cannot evict each other — same discipline as the session
        engine's batch split)."""
        side = self.sides[side_idx]
        n = len(keys)
        counts = np.bincount(shards, minlength=self.P)
        if side.spill_active and int(counts.max()) > side.capacity - 1 \
                and n > 1:
            half = n // 2
            self._ingest_rows(side_idx, keys[:half], ts[:half],
                              [v[:half] for v in values],
                              shards[:half])
            self._ingest_rows(side_idx, keys[half:], ts[half:],
                              [v[half:] for v in values],
                              shards[half:])
            return
        rids = np.arange(self._next_rid, self._next_rid + n,
                         dtype=np.int64)
        self._next_rid += n
        if side.spill_active:
            self._make_headroom(side_idx, counts)
        else:
            need = int(counts.max()) if n else 0
            while any(side.free_headroom(p) < counts[p]
                      for p in range(self.P)):
                self._grow_side(side_idx, max(
                    side.capacity * 2,
                    pad_bucket_size(side.capacity + need)))
        slots = np.zeros(n, dtype=np.int32)
        order = np.argsort(shards, kind="stable")
        offs = np.concatenate(([0], np.cumsum(counts)))
        for p in np.nonzero(counts)[0].tolist():
            sel = order[offs[p]:offs[p + 1]]
            sl = side.allocate(p, len(sel))
            slots[sel] = sl
            side.meta[p].merge_rows(
                keys[sel], ts[sel], rids[sel], sl,
                np.ones(len(sel), dtype=bool))
            for i in side.shadow:
                side.shadow[i][p][sl] = np.asarray(
                    values[i], dtype=side.schema[i][1])[sel]
        if self.backend == "device" and side.device_cols:
            self._device_put_rows(side_idx, shards, slots, values)

    def _device_put_rows(self, side_idx: int, shards, slots,
                         values) -> None:
        import jax

        from flink_tpu.parallel.shuffle import (
            bucket_by_shard,
            stage_device_exchange,
        )
        from flink_tpu.joins.kernels import (
            build_join_exchange_put,
            build_join_put,
        )

        side = self.sides[side_idx]
        planes = self._planes[side_idx]
        cols = [np.asarray(slots, dtype=np.int32)] + [
            np.asarray(values[i], dtype=side.schema[i][1])
            for i in side.device_cols]
        fills = [0] + [side.schema[i][1].type(0)
                       for i in side.device_cols]
        self._pool.flip()
        if self._two_level_active():
            # pod mesh: two-level ICI/DCN exchange then the plane
            # write — stream order preserved, so the last-write-wins
            # semantics stay bit-identical to the flat exchange
            from flink_tpu.observe import flight_recorder as flight
            from flink_tpu.parallel.exchange2 import (
                build_join_exchange2_steps,
                stage_two_level_exchange,
            )

            dst, staged, w1, w2 = stage_two_level_exchange(
                shards, self.host_topology, columns=cols, fills=fills,
                pool=self._pool, traffic=self._exchange2_traffic)
            s1, s2 = build_join_exchange2_steps(
                self.mesh, self.host_topology, side.dtypes_key())
            with self._wd_section("join_ingest"):
                with flight.span("exchange.stage1"):
                    put = jax.device_put((dst, *staged),
                                         self._sharding)
                    inter = s1(put[0], put[1], tuple(put[2:]), w1)
                with flight.span("exchange.stage2"):
                    self._planes[side_idx] = s2(
                        planes, inter[0], inter[1], tuple(inter[2:]),
                        w2)
        elif self.shuffle_mode == "device":
            dst, staged, width = stage_device_exchange(
                shards, self.P, columns=cols, fills=fills,
                pool=self._pool)
            prog = build_join_exchange_put(self.mesh,
                                           side.dtypes_key())
            with self._wd_section("join_ingest"):
                put = jax.device_put((dst, *staged), self._sharding)
                self._planes[side_idx] = prog(
                    planes, put[0], put[1], tuple(put[2:]), width)
        else:
            counts, blocked = bucket_by_shard(
                shards, self.P, columns=cols, fills=fills,
                pool=self._pool)
            prog = build_join_put(self.mesh, side.dtypes_key())
            with self._wd_section("join_ingest"):
                put = jax.device_put(tuple(blocked), self._sharding)
                self._planes[side_idx] = prog(
                    planes, put[0], tuple(put[1:]))
        self._push_fence()

    # ------------------------------------------------------------- eviction

    def _make_headroom(self, side_idx: int, needed: np.ndarray) -> None:
        """Evict the coldest (oldest-ts) rows of every shard that
        cannot absorb its share of the batch — cohorts gathered in ONE
        program + ONE batched D2H across shards."""
        side = self.sides[side_idx]
        cohorts: Dict[int, np.ndarray] = {}
        for p in range(self.P):
            if side.free_headroom(p) >= int(needed[p]):
                continue
            pos = side.choose_eviction(
                p, int(needed[p]) - side.free_headroom(p))
            cohorts[p] = pos
        if not cohorts:
            return
        # host backend: shadow_values already carries every column —
        # the gather would be a duplicate copy immediately discarded
        vals = (self._gather_rows(side_idx, {
            p: side.meta[p].slot[pos] for p, pos in cohorts.items()})
            if self.backend == "device" and side.device_cols else None)
        for p, pos in cohorts.items():
            columns = side.shadow_values(p, pos)
            if vals is not None:
                for j, i in enumerate(side.device_cols):
                    columns[i] = vals[p][j]
            side.evict_rows(p, pos, columns)

    def _gather_rows(self, side_idx: int,
                     per_shard_slots: Dict[int, np.ndarray]
                     ) -> Dict[int, List[np.ndarray]]:
        """Device-column values at the given slots, per shard: one
        gather program + ONE device_get for all shards. Host backend
        reads the shadow store."""
        side = self.sides[side_idx]
        out: Dict[int, List[np.ndarray]] = {}
        if self.backend == "host" or not side.device_cols:
            for p, slots in per_shard_slots.items():
                sc = np.clip(slots, 0, None)
                out[p] = [side.shadow[i][p][sc]
                          for i in side.device_cols]
            return out
        from flink_tpu.joins.kernels import build_join_gather
        import jax

        g_max = max(len(s) for s in per_shard_slots.values())
        G = sticky_bucket(g_max, self._gather_bucket)
        self._gather_bucket = G
        block = np.zeros((self.P, G), dtype=np.int32)
        for p, slots in per_shard_slots.items():
            block[p, :len(slots)] = slots
        prog = build_join_gather(self.mesh, side.dtypes_key())
        with self._wd_section("evict_gather"):
            gathered = prog(self._planes[side_idx],
                            jax.device_put(block, self._sharding))
        host = self._harvest_get(gathered, "evict_harvest")
        for p, slots in per_shard_slots.items():
            out[p] = [h[p][:len(slots)] for h in host]
        return out

    def _grow_side(self, side_idx: int, new_capacity: int) -> None:
        side = self.sides[side_idx]
        old = side.capacity
        if new_capacity <= old:
            return
        side.grow(new_capacity)
        if self.backend == "device" and side.device_cols:
            import jax
            import jax.numpy as jnp

            host = self._harvest_get(list(self._planes[side_idx]),
                                     "grow_harvest")
            grown = []
            for h, i in zip(host, side.device_cols):
                wide = np.zeros((self.P, new_capacity),
                                dtype=side.schema[i][1])
                wide[:, :old] = h
                grown.append(jax.device_put(jnp.asarray(wide),
                                            self._sharding))
            self._planes[side_idx] = tuple(grown)

    # --------------------------------------------------------------- probes

    def _probe_banded(self, store_idx: int,
                      per_shard: Dict[int, Tuple[np.ndarray,
                                                 np.ndarray,
                                                 np.ndarray]],
                      band) -> Dict[int, dict]:
        """The banded probe: ``per_shard[p] = (orig_rows, keys, ts)``;
        ``band(meta, keys, ts) -> (lo, cnt)`` resolves each probe's
        candidate band over the shard's sorted metadata. Returns per
        shard the flattened match structure and the stored side's value
        columns (device-gathered for resident candidates, page-served
        for cold ones) — identical content and order in both backends.
        """
        side = self.sides[store_idx]
        bounds: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        w_max = 0
        b_max = 0
        s_max = 0
        total = 0
        for p, (_, pk, pt) in per_shard.items():
            m = side.meta[p]
            lo, cnt = band(m, pk, pt)
            bounds[p] = (lo, cnt)
            if len(cnt):
                w_max = max(w_max, int(cnt.max()))
                total += int(cnt.sum())
            b_max = max(b_max, len(pk))
            s_max = max(s_max, len(m))
        gathered_host = None
        W = 0
        if total and w_max:
            W = sticky_bucket(w_max, self._band_bucket[store_idx],
                              minimum=8)
            self._band_bucket[store_idx] = W
        if (self.backend == "device" and side.device_cols and total
                and W):
            gathered_host = self._dispatch_probe(
                store_idx, per_shard, bounds, W, b_max, s_max)
        out: Dict[int, dict] = {}
        for p, (orig, pk, pt) in per_shard.items():
            lo, cnt = bounds[p]
            t = int(cnt.sum()) if len(cnt) else 0
            if t == 0:
                continue
            m = side.meta[p]
            l_rep = np.repeat(np.arange(len(pk), dtype=np.int64), cnt)
            off = (np.arange(t, dtype=np.int64)
                   - np.repeat(np.cumsum(cnt) - cnt, cnt))
            cand = lo[l_rep] + off
            cslot = m.slot[cand]
            resident = cslot >= 0
            cols: List[np.ndarray] = []
            for i, (_, dt) in enumerate(side.schema):
                if i in side.shadow:
                    cols.append(side.shadow[i][p]
                                [np.clip(cslot, 0, None)].copy())
                else:
                    g = gathered_host[side.device_cols.index(i)][p]
                    cols.append(np.ascontiguousarray(
                        g[l_rep, off]).astype(dt, copy=False))
            cold = np.nonzero(~resident)[0]
            if len(cold):
                side.fill_cold(
                    p,
                    [(int(j), int(pk[l_rep[j]]), int(m.rid[cand[j]]))
                     for j in cold.tolist()],
                    cols, np.arange(t, dtype=np.int64))
            out[p] = {
                "orig": orig, "l_rep": l_rep, "cand": cand,
                "cols": cols, "ts": m.ts[cand], "resident": resident,
            }
        return out

    def _dispatch_probe(self, store_idx, per_shard, bounds, W,
                        b_max, s_max):
        """Stage the sorted-order slot mirror + band bounds and run the
        banded-probe program; ONE batched D2H for every output column."""
        import jax

        from flink_tpu.joins.kernels import build_banded_probe

        side = self.sides[store_idx]
        S = sticky_bucket(max(s_max, 1),
                          self._mirror_bucket[store_idx])
        self._mirror_bucket[store_idx] = S
        B = sticky_bucket(max(b_max, 1),
                          self._probe_bucket[store_idx], minimum=64)
        self._probe_bucket[store_idx] = B
        # NO pool flip here: the probe is synchronous — its harvest
        # (device_get below) completes before this method returns, so
        # its tagged buffers are free to rewrite next batch. Flipping
        # would advance the generation a second time per batch and
        # break the INGEST path's double-buffer (its fence drains one
        # generation behind).
        mirror = self._pool.get((self.P, S), np.int32, -1,
                                tag=("probe", "mirror", store_idx))
        lo_b = self._pool.get((self.P, B), np.int32, 0,
                              tag=("probe", "lo", store_idx))
        cnt_b = self._pool.get((self.P, B), np.int32, 0,
                               tag=("probe", "cnt", store_idx))
        for p, (_, pk, _pt) in per_shard.items():
            m = side.meta[p]
            mirror[p, :len(m)] = m.slot
            lo, cnt = bounds[p]
            lo_b[p, :len(pk)] = lo
            cnt_b[p, :len(pk)] = cnt
        prog = build_banded_probe(self.mesh, side.dtypes_key())
        with self._wd_section("join_probe"):
            put = jax.device_put((mirror, lo_b, cnt_b),
                                 self._sharding)
            outs = prog(self._planes[store_idx], put[0], put[1],
                        put[2], W)
        return self._harvest_get(outs)

    # ------------------------------------------------------ match assembly

    def _assemble(self, probe_idx: int, probe_cols: Dict[str,
                                                         np.ndarray],
                  probe_ts: np.ndarray,
                  probe_keys: np.ndarray,
                  probed: Dict[int, dict],
                  out_ts) -> Optional[RecordBatch]:
        """One output batch from the per-shard probe results:
        shard-major, probe stream order within shard, band order within
        probe — deterministic and backend-identical. ``out_ts(lt, rt)``
        computes the emitted timestamp column."""
        store_idx = 1 - probe_idx
        store = self.sides[store_idx]
        if not probed:
            return None
        store_names = [n for n, _ in store.schema]
        probe_names = sorted(probe_cols)
        # _suffixed_names takes (left, right); the probe side is left
        # only when it is input 0
        if probe_idx == 0:
            pmap_names, smap_names = _suffixed_names(
                probe_names, store_names, self.suffixes)
        else:
            smap_names, pmap_names = _suffixed_names(
                store_names, probe_names, self.suffixes)
        chunks: List[Dict[str, np.ndarray]] = []
        for p in sorted(probed):
            r = probed[p]
            rows = r["orig"][r["l_rep"]]
            cols: Dict[str, np.ndarray] = {
                KEY_ID_FIELD: probe_keys[rows]}
            for n in probe_names:
                cols[pmap_names[n]] = probe_cols[n][rows]
            for i, n in enumerate(store_names):
                cols[smap_names[n]] = r["cols"][i]
            lt = probe_ts[rows]
            rt = r["ts"]
            cols[TIMESTAMP_FIELD] = out_ts(lt, rt)
            chunks.append(cols)
        if not chunks:
            return None
        merged = {k: (np.concatenate([c[k] for c in chunks])
                      if len(chunks) > 1 else chunks[0][k])
                  for k in chunks[0]}
        return RecordBatch(merged)

    # ------------------------------------------------------------ snapshots

    def _side_snapshot(self, side_idx: int) -> Dict[str, object]:
        side = self.sides[side_idx]
        if side is None:
            return {"table": {}, "schema": []}
        device_values = None
        if self.backend == "device" and side.device_cols:
            host = self._harvest_get(list(self._planes[side_idx]),
                                     "snapshot_harvest")
            device_values = [
                {i: host[j][p]
                 for j, i in enumerate(side.device_cols)}
                for p in range(self.P)]
        else:
            device_values = [{} for _ in range(self.P)]
        return {
            "table": side.snapshot_rows(self.max_parallelism,
                                        device_values),
            "schema": [(n, dt.str) for n, dt in side.schema],
        }

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        self._drain_fences()
        return {
            "kind": self.kind,
            "left": self._side_snapshot(0),
            "right": self._side_snapshot(1),
            "next_rid": int(self._next_rid),
            **self._meta_snapshot(),
        }

    def _meta_snapshot(self) -> Dict[str, object]:
        return {}

    def _restore_meta(self, snap: Dict[str, object]) -> None:
        pass

    def restore(self, snap: Dict[str, object],
                key_group_filter=None) -> None:
        for side_idx, name in ((0, "left"), (1, "right")):
            s = snap.get(name) or {}
            table = s.get("table") or {}
            schema = [(n, np.dtype(d)) for n, d in
                      s.get("schema", [])]
            self.sides[side_idx] = None
            self._planes[side_idx] = None
            if not schema:
                continue
            self._init_side(side_idx, schema)
            self._restore_rows(side_idx, table, key_group_filter)
        self._next_rid = max(int(snap.get("next_rid", 1)),
                             self._next_rid)
        self._restore_meta(snap)

    def _restore_rows(self, side_idx: int, table: Dict[str, object],
                      key_group_filter) -> None:
        side = self.sides[side_idx]
        keys = np.asarray(table.get("key_id", ()), dtype=np.int64)
        if not len(keys):
            return
        rids = np.asarray(table["namespace"], dtype=np.int64)
        ts = np.asarray(table["ts"], dtype=np.int64)
        dirty = np.asarray(table.get("dirty",
                                     np.zeros(len(keys), bool)),
                           dtype=bool)
        leaves = [np.asarray(table[f"leaf_{i}"],
                             dtype=side.schema[i][1])
                  for i in range(len(side.schema))]
        if key_group_filter is not None:
            kg = table.get("key_group")
            kg = (np.asarray(kg, dtype=np.int64) if kg is not None
                  else assign_key_groups(keys, self.max_parallelism))
            keep = np.isin(kg, np.asarray(sorted(
                int(g) for g in key_group_filter)))
            keys, rids, ts, dirty = (keys[keep], rids[keep],
                                     ts[keep], dirty[keep])
            leaves = [lv[keep] for lv in leaves]
        if not len(keys):
            return
        self._next_rid = max(self._next_rid, int(rids.max()) + 1)
        shards = self._shards_of(keys)
        if not side.spill_active:
            # an engine that grew during the run must be able to
            # restore its own snapshot: grow exactly like ingest does
            counts = np.bincount(shards, minlength=self.P)
            need = int(counts.max())
            while any(side.free_headroom(p) < counts[p]
                      for p in range(self.P)):
                self._grow_side(side_idx, max(
                    side.capacity * 2,
                    pad_bucket_size(side.capacity + need)))
        put_slots: Dict[int, np.ndarray] = {}
        put_sel: Dict[int, np.ndarray] = {}
        for p in range(self.P):
            sel = np.nonzero(shards == p)[0]
            if not len(sel):
                continue
            # newest rows stay resident (they expire last and are the
            # likeliest band candidates); the rest re-home as pages
            order = sel[np.argsort(-ts[sel], kind="stable")]
            n_res = min(len(order), side.free_headroom(p))
            res, cold = order[:n_res], order[n_res:]
            slots = side.allocate(p, n_res)
            slot_col = np.full(len(sel), -1, dtype=np.int32)
            if len(cold):
                restore_into_pages(
                    side.spills[p], side.pmaps[p], keys[cold],
                    rids[cold], [lv[cold] for lv in leaves],
                    page_rows=max(side.capacity // 8, 256),
                    dirty=dirty[cold], append=True)
            # metadata rows for everything (cold rows carry slot -1);
            # keep (res-first) ordering irrelevant — merge sorts
            both = np.concatenate([res, cold]).astype(np.int64)
            slot_col[:n_res] = slots
            side.meta[p].merge_rows(keys[both], ts[both], rids[both],
                                    slot_col, dirty[both])
            for i in side.shadow:
                side.shadow[i][p][slots] = leaves[i][res]
            if len(res):
                put_slots[p] = slots
                put_sel[p] = res
        if self.backend == "device" and side.device_cols and put_slots:
            import jax

            from flink_tpu.joins.kernels import build_join_put

            B = sticky_bucket(max(len(s) for s in put_slots.values()),
                              self._put_bucket[side_idx])
            self._put_bucket[side_idx] = B
            slot_block = np.zeros((self.P, B), dtype=np.int32)
            val_blocks = [np.zeros((self.P, B),
                                   dtype=side.schema[i][1])
                          for i in side.device_cols]
            for p, slots in put_slots.items():
                m = len(slots)
                slot_block[p, :m] = slots
                for j, i in enumerate(side.device_cols):
                    val_blocks[j][p, :m] = leaves[i][put_sel[p]]
            prog = build_join_put(self.mesh, side.dtypes_key())
            with self._wd_section("restore_put"):
                put = jax.device_put(
                    (slot_block, *val_blocks), self._sharding)
                self._planes[side_idx] = prog(
                    self._planes[side_idx], put[0], tuple(put[1:]))

    # ---------------------------------------------- shard-granular units

    def shard_key_groups(self) -> List[Tuple[int, int]]:
        from flink_tpu.state.keygroups import shard_key_group_ranges

        return shard_key_group_ranges(self.P, self.max_parallelism,
                                      self.key_group_range)

    def snapshot_sharded(self, mode: str = "full"
                         ) -> Dict[Tuple[int, int], Dict[str, object]]:
        """One independently-restorable unit per shard's key-group
        range — both sides' rows split by their ``key_group`` column,
        scalar metadata replicated (monotonic-max / watermark-min on
        merge). The union of the units is exactly ``snapshot()``."""
        snap = self.snapshot(mode)
        units: Dict[Tuple[int, int], Dict[str, object]] = {}
        for g0, g1 in self.shard_key_groups():
            unit = {"kind": snap["kind"],
                    "next_rid": snap["next_rid"],
                    **{k: v for k, v in snap.items()
                       if k not in ("kind", "left", "right",
                                    "next_rid")}}
            for name in ("left", "right"):
                s = snap[name]
                table = s.get("table") or {}
                kg = np.asarray(table.get("key_group", ()),
                                dtype=np.int64)
                if len(kg):
                    mask = (kg >= g0) & (kg <= g1)
                    unit[name] = {
                        "table": {k: np.asarray(v)[mask]
                                  for k, v in table.items()},
                        "schema": s.get("schema", []),
                    }
                else:
                    unit[name] = {"table": dict(table),
                                  "schema": s.get("schema", [])}
            units[(int(g0), int(g1))] = unit
        return units

    def merge_unit_snapshots(self, units: List[Dict[str, object]]
                             ) -> Dict[str, object]:
        merged: Dict[str, object] = {
            "kind": self.kind,
            "next_rid": max((int(u.get("next_rid", 1))
                             for u in units), default=1),
            **self._merge_meta_units(units),
        }
        for name in ("left", "right"):
            tables = [u.get(name, {}).get("table") or {}
                      for u in units]
            tables = [t for t in tables if t and len(
                np.asarray(t.get("key_id", ())))]
            schema = next((u[name]["schema"] for u in units
                           if u.get(name, {}).get("schema")), [])
            if not tables:
                merged[name] = {"table": {}, "schema": schema}
                continue
            cols = sorted(set().union(*(set(t) for t in tables)))
            table = {k: np.concatenate([np.asarray(t[k])
                                        for t in tables])
                     for k in cols}
            order = np.argsort(table["namespace"], kind="stable")
            merged[name] = {
                "table": {k: v[order] for k, v in table.items()},
                "schema": schema,
            }
        return merged

    def _merge_meta_units(self, units) -> Dict[str, object]:
        return {}

    # ------------------------------------------------------------- reshard

    def reshard(self, new_shards: int, devices=None) -> Dict[str, object]:
        """LIVE key-group migration to a new mesh size: every logical
        row (resident + paged, dirtiness intact) lifts off the old
        plane, the mesh rebuilds, and rows land on their new owners —
        the join form of ``MeshSpillSupport.reshard``."""
        new_shards = int(new_shards)
        if new_shards < 1:
            raise ValueError("new_shards must be >= 1")
        t0 = time.perf_counter()
        self._drain_fences()
        chaos.fault_point("rescale.handoff", stage="drain",
                          shards=new_shards)
        snaps = [self._side_snapshot(i) for i in (0, 1)]
        rows_moved = sum(
            len(np.asarray((s.get("table") or {}).get("key_id", ())))
            for s in snaps)
        if self.backend == "device":
            from flink_tpu.parallel.mesh import make_mesh
            from jax.sharding import NamedSharding, PartitionSpec
            from flink_tpu.parallel.mesh import KEY_AXIS

            self.mesh = make_mesh(new_shards, devices=devices)
            self.P = int(self.mesh.devices.size)
            self._sharding = NamedSharding(self.mesh,
                                           PartitionSpec(KEY_AXIS))
        else:
            self.P = new_shards
        t = self.host_topology
        if t is not None and t.num_shards != self.P:
            # the (hosts, local) factorization no longer covers the
            # resized mesh — drop to the flat single-axis exchange
            self.host_topology = None
        if self.max_parallelism < self.P:
            raise ValueError(
                f"cannot reshard to {new_shards}: max_parallelism "
                f"{self.max_parallelism}")
        chaos.fault_point("rescale.handoff", stage="commit",
                          shards=new_shards)
        old_counters = [
            self.sides[i].spill_counters() if self.sides[i] else None
            for i in (0, 1)]
        for side_idx, s in enumerate(snaps):
            schema = [(n, np.dtype(d))
                      for n, d in s.get("schema", [])]
            self.sides[side_idx] = None
            self._planes[side_idx] = None
            if not schema:
                continue
            self._init_side(side_idx, schema)
            # job-lifetime spill counters survive the mesh resize
            if old_counters[side_idx]:
                c = old_counters[side_idx]
                pm = self.sides[side_idx].pmaps[0]
                pm.pages_evicted += c["pages_evicted"]
                pm.rows_evicted += c["rows_evicted"]
                pm.pages_reloaded += c["pages_reloaded"]
                pm.rows_reloaded += c["rows_reloaded"]
                pm.rows_compacted += c["rows_compacted"]
                self.sides[side_idx].cold_rows_served = \
                    c["cold_rows_served"]
            # lifted rows keep their dirtiness: _restore_rows carries
            # the snapshot's dirty column into metadata and pages
            self._restore_rows(side_idx, s.get("table") or {}, None)
        wd = self._watchdog
        if wd is not None and self.mesh is not None:
            wd.rebind(self.P,
                      [d.id for d in self.mesh.devices.flat])
        return {"shards": self.P, "rows_moved": rows_moved,
                "seconds": time.perf_counter() - t0}

    # ------------------------------------------------------------ counters

    def spill_counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for side_idx, name in ((0, "left"), (1, "right")):
            side = self.sides[side_idx]
            if side is None:
                continue
            for k, v in side.spill_counters().items():
                out[k] = out.get(k, 0) + v
        return out

    def shard_resident_rows(self) -> List[int]:
        totals = [0] * self.P
        for side in self.sides:
            if side is None:
                continue
            for p, n in enumerate(side.resident_rows()):
                totals[p] += n
        return totals

    # --------------------------------------------------- read replica
    # (tenancy/replica.py — publish hooks for the join side tables;
    # rows are immutable after insert, so the boundary delta is pure
    # identity churn: inserts, evictions, prunes)

    #: per-side ReplicaPlane (armed by arm_side_replica)
    _side_replicas = (None, None)

    def arm_side_replica(self, side_idx: int):
        """Arm a read replica over one side table (device backend; the
        side must have seen its first batch — the value schema is
        late-bound). Returns a
        :class:`~flink_tpu.tenancy.replica.JoinSideReplicaAdapter`."""
        from flink_tpu.tenancy.replica import (
            JoinSideReplicaAdapter,
            ReplicaPlane,
        )

        side = self.sides[side_idx]
        if side is None:
            raise RuntimeError(
                "side table not initialized yet — the value schema is "
                "observed at the side's first batch")
        if self.backend != "device":
            raise RuntimeError(
                "side replicas ride the device value planes; the host "
                "oracle backend serves reads directly")

        class _Leaf:
            def __init__(self, dtype):
                self.dtype = dtype
                self.identity = np.dtype(dtype).type(0)

        plane = ReplicaPlane(
            self.mesh, [_Leaf(side.schema[i][1])
                        for i in side.device_cols], side.capacity)
        plane.warm_tiers()
        reps = list(self._side_replicas)
        reps[side_idx] = plane
        self._side_replicas = tuple(reps)
        return JoinSideReplicaAdapter(plane, side)

    def _publish_side_replicas(self, watermark: int) -> None:
        for side_idx in (0, 1):
            rep = self._side_replicas[side_idx]
            side = self.sides[side_idx]
            if rep is None or side is None:
                continue
            from flink_tpu.observe import flight_recorder as flight

            with flight.span("serving.replica_publish",
                             watermark=int(watermark)):
                self._publish_one_side(rep, side, side_idx,
                                       int(watermark))

    def _publish_one_side(self, rep, side, side_idx: int,
                          watermark: int) -> None:
        """The join form of the boundary publish: derive per-slot
        metadata from the sorted row metadata, diff against the
        replica's shadow (rows are immutable — identity changes ARE
        the delta), split disappeared rows into cold (still mapped in
        the page tier) vs pruned, and hand the changed slots to the
        shared publish program."""
        if not hasattr(self, "_rep_last_rid"):
            self._rep_last_rid = [0, 0]
        if rep.needs_rebuild(self.P, side.capacity):
            rep.rebuild(self.mesh, side.capacity)
            rep.warm_tiers()
            # a rebuild's republish covers resident rows; resetting the
            # rid watermark makes every COLD row re-enter the index too
            self._rep_last_rid[side_idx] = 0
        last_rid = self._rep_last_rid[side_idx]
        per_shard = {}
        for p in range(self.P):
            m = side.meta[p]
            cap = side.capacity
            cur_used = np.zeros(cap, dtype=bool)
            cur_key = np.zeros(cap, dtype=np.int64)
            cur_rid = np.zeros(cap, dtype=np.int64)
            cur_ts = np.zeros(cap, dtype=np.int64)
            res = np.nonzero(m.slot >= 0)[0]
            slots_res = m.slot[res]
            cur_used[slots_res] = True
            cur_key[slots_res] = m.key[res]
            cur_rid[slots_res] = m.rid[res]
            cur_ts[slots_res] = m.ts[res]
            r_used = rep.rep_used[p]
            r_key = rep.rep_key[p]
            r_rid = rep.rep_ns[p]
            moved = (cur_key != r_key) | (cur_rid != r_rid)
            ident_change = cur_used & (~r_used | moved)
            up = np.nonzero(ident_change)[0]
            gone = np.nonzero(r_used & (~cur_used | moved))[0]
            cold: List[Tuple[int, int]] = []
            freed: List[Tuple[int, int]] = []
            if len(gone):
                from flink_tpu.joins.side_table import _rid_positions

                g_keys = r_key[gone].copy()
                g_rids = r_rid[gone].copy()
                # still resident at another slot? covered by its upsert
                found, src = _rid_positions(m.rid, g_rids)
                still = np.zeros(len(g_rids), dtype=bool)
                still[found] = m.slot[src] >= 0
                miss = ~still
                if miss.any():
                    mk, mr = g_keys[miss], g_rids[miss]
                    is_cold = side.pmaps[p].spilled_mask(
                        np.asarray(mr, dtype=np.int64))
                    for j in range(len(mk)):
                        if is_cold[j]:
                            cold.append((int(mk[j]), int(mr[j]), None))
                        else:
                            freed.append((int(mk[j]), int(mr[j])))
            # rows created AND evicted since the last publish (never
            # resident at a boundary): rids are allocation-monotonic,
            # so "new" is one vectorized compare
            new_cold = np.nonzero((m.slot < 0) & (m.rid > last_rid))[0]
            for pos in new_cold.tolist():
                cold.append((int(m.key[pos]), int(m.rid[pos]),
                             (int(m.ts[pos]), None)))
            # extra payload: (ts, host-shadow column values) per row —
            # device-ineligible columns never ride the device plane
            extra = None
            if len(up):
                host_cols = [side.shadow[i][p][up]
                             for i in side.host_cols]
                extra = [
                    (int(cur_ts[s]),
                     tuple(hc[j] for hc in host_cols))
                    for j, s in enumerate(up)]
            per_shard[p] = {
                "up_slots": up.astype(np.int32),
                "up_keys": cur_key[up].copy(),
                "up_ns": cur_rid[up].copy(),
                "up_extra": extra,
                "cold": cold,
                "freed": freed,
                "fresh": bool(ident_change.any()),
            }
            per_shard[p]["_shadow"] = (cur_used, cur_key, cur_rid)
        # shadow + rid watermark update ONLY after the publish succeeds
        # (a torn publish must leave the delta re-derivable)
        rep.publish(self._planes[side_idx] or (), per_shard, watermark)
        for p, d in per_shard.items():
            cur_used, cur_key, cur_rid = d.pop("_shadow")
            rep.rep_used[p][:] = cur_used
            rep.rep_key[p][:] = cur_key
            rep.rep_ns[p][:] = cur_rid
        self._rep_last_rid[side_idx] = self._next_rid - 1

    def query_side_batch(self, side_idx: int, key_ids
                         ) -> List[List[dict]]:
        """LIVE point lookup against one side table: per requested key,
        the side's buffered rows as ``[{"ts", "rid", <col>: v}, ...]``
        sorted by (ts, rid) — resident rows through ONE gather + ONE
        device read, cold rows from their shards' page tiers
        (``cold_rows_served`` counted). The replica staleness tests pin
        the replica path bit-identical to this at every published
        boundary (via a checkpoint round-trip)."""
        side_idx = int(side_idx)
        side = self.sides[side_idx]
        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        results: List[List[dict]] = [[] for _ in range(n)]
        if side is None or n == 0:
            return results
        shards = self._shards_of(key_ids)
        #: (request row, meta position) per matched row, per shard
        rows_of: Dict[int, List[Tuple[int, int]]] = {}
        for p in np.unique(shards).tolist():
            m = side.meta[p]
            if not len(m):
                continue
            sel = np.nonzero(shards == p)[0]
            lo = pair_lower_bound(m.key, m.ts, key_ids[sel],
                                  np.full(len(sel), -(1 << 62)))
            hi = pair_lower_bound(m.key, m.ts, key_ids[sel],
                                  np.full(len(sel), (1 << 62)))
            lanes = []
            for j, r in enumerate(sel.tolist()):
                for pos in range(int(lo[j]), int(hi[j])):
                    lanes.append((r, pos))
            if lanes:
                rows_of[int(p)] = lanes
        # resident values: one gather + one batched D2H for all shards
        gathered = self._gather_rows(side_idx, {
            p: np.clip(side.meta[p].slot[[pos for _, pos in lanes]],
                       0, None)
            for p, lanes in rows_of.items()}) if rows_of else {}
        names = [nm for nm, _ in side.schema]
        for p, lanes in rows_of.items():
            m = side.meta[p]
            cold_wants: List[Tuple[int, int, int]] = []
            sinks = [np.zeros(len(lanes), dtype=dt)
                     for _, dt in side.schema]
            rows_arr = np.arange(len(lanes))
            for j, (r, pos) in enumerate(lanes):
                if m.slot[pos] < 0:
                    cold_wants.append((j, int(m.key[pos]),
                                       int(m.rid[pos])))
                else:
                    for i in side.shadow:
                        sinks[i][j] = side.shadow[i][p][m.slot[pos]]
                    gi = 0
                    for i in side.device_cols:
                        sinks[i][j] = gathered[p][gi][j]
                        gi += 1
            if cold_wants:
                side.fill_cold(p, cold_wants, sinks, rows_arr)
            for j, (r, pos) in enumerate(lanes):
                row = {"ts": int(m.ts[pos]), "rid": int(m.rid[pos])}
                for i, nm in enumerate(names):
                    row[nm] = sinks[i][j].item()
                results[r].append(row)
        for r in range(n):
            results[r].sort(key=lambda d: (d["ts"], d["rid"]))
        return results


class MeshIntervalJoinEngine(JoinEngineBase):
    """Keyed interval join over the dual slot tables (INNER)."""

    kind = "interval"

    def __init__(self, lower: int, upper: int, **kw) -> None:
        if lower > upper:
            raise ValueError(f"lower {lower} > upper {upper}")
        super().__init__(**kw)
        self.lower = int(lower)
        self.upper = int(upper)

    # band of STORED rows matching a probe at time t: the stored side's
    # admissible window depends on which side probes —
    #   probe = left  -> stored right rows in [t+lower, t+upper]
    #   probe = right -> stored left rows with t in [lts+lower,
    #   lts+upper], i.e. lts in [t-upper, t-lower]
    def _band_for(self, probe_idx: int):
        if probe_idx == 0:
            blo, bhi = self.lower, self.upper
        else:
            blo, bhi = -self.upper, -self.lower

        def band(m, pk, pt):
            lo = pair_lower_bound(m.key, m.ts, pk, pt + blo)
            hi = pair_lower_bound(m.key, m.ts, pk, pt + bhi + 1)
            return lo, (hi - lo).astype(np.int64)

        return band

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        with self._flight_ingest():
            return self._process_batch_inner(batch, int(input_index))

    def _process_batch_inner(self, batch: RecordBatch,
                             side_idx: int) -> List[RecordBatch]:
        self._wd_boundary()
        side = self._ensure_side(side_idx, batch)
        self._check_schema(side, batch, side_idx)
        keys = np.asarray(batch.key_ids, dtype=np.int64)
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        values = [np.asarray(batch[n]) for n, _ in side.schema]
        out: List[RecordBatch] = []
        store_idx = 1 - side_idx
        store = self.sides[store_idx]
        shards = self._shards_of(keys)
        if store is not None and store.num_rows():
            per_shard: Dict[int, Tuple[np.ndarray, np.ndarray,
                                       np.ndarray]] = {}
            for p in np.unique(shards).tolist():
                sel = np.nonzero(shards == p)[0]
                per_shard[int(p)] = (sel, keys[sel], ts[sel])
            probed = self._probe_banded(store_idx, per_shard,
                                        self._band_for(side_idx))
            probe_cols = {n: np.asarray(batch[n])
                          for n in batch.names()
                          if n not in (KEY_ID_FIELD,
                                       TIMESTAMP_FIELD)}
            m = self._assemble(side_idx, probe_cols, ts, keys, probed,
                               out_ts=np.maximum)
            if m is not None and len(m):
                out.append(m)
        # insert AFTER the probe: a pair is emitted by whichever side
        # arrives second (never joins its own batch — the structural
        # dedup of the reference operator)
        self._ingest(side_idx, keys, ts, values, shards=shards)
        return out

    def on_watermark(self, watermark: int) -> List[RecordBatch]:
        """Prune expired rows: a left row at t is dead once the
        watermark passes ``t + upper``; a right row at t once it passes
        ``t - lower`` (no right-side probe can still reach it)."""
        with self._flight_fire(watermark):
            self._wd_boundary()
            if self.sides[0] is not None:
                self.sides[0].prune(int(watermark) - self.upper)
            if self.sides[1] is not None:
                self.sides[1].prune(int(watermark) + self.lower)
        # replica publish AFTER the prunes of this boundary
        self._publish_side_replicas(int(watermark))
        return []

    def _meta_snapshot(self) -> Dict[str, object]:
        return {"lower": self.lower, "upper": self.upper}

    def _merge_meta_units(self, units) -> Dict[str, object]:
        return {"lower": self.lower, "upper": self.upper}


class MeshTemporalJoinEngine(JoinEngineBase):
    """Event-time temporal join against the versioned right plane."""

    kind = "temporal"

    def __init__(self, **kw) -> None:
        super().__init__(**kw)
        #: pending left rows (host columnar, drained per watermark:
        #: they are transient ordering state, not keyed state — the
        #: versioned RIGHT side is the device-resident plane)
        self._pending: List[RecordBatch] = []
        self._emitted_wm = _NEG
        self.late_left_dropped = 0

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        if len(batch) == 0:
            return []
        with self._flight_ingest():
            return self._process_batch_inner(batch, int(input_index))

    def _process_batch_inner(self, batch: RecordBatch,
                             input_index: int) -> List[RecordBatch]:
        self._wd_boundary()
        if int(input_index) == 0:
            late = np.asarray(batch.timestamps,
                              dtype=np.int64) <= self._emitted_wm
            if late.any():
                self.late_left_dropped += int(late.sum())
                batch = batch.filter(~late)
            if len(batch):
                self._pending.append(batch)
            return []
        side = self._ensure_side(1, batch)
        self._check_schema(side, batch, 1)
        self._ingest(1, np.asarray(batch.key_ids, dtype=np.int64),
                     np.asarray(batch.timestamps, dtype=np.int64),
                     [np.asarray(batch[n]) for n, _ in side.schema])
        return []

    @staticmethod
    def _version_band(m, pk, pt):
        """Latest version at-or-before each probe time: the ``W == 1``
        band ``[ub(k, t) - 1]`` where the candidate's key matches."""
        hi = pair_lower_bound(m.key, m.ts, pk, pt + 1)
        pos = hi - 1
        ok = pos >= 0
        ok[ok] &= m.key[pos[ok]] == pk[ok]
        return np.maximum(pos, 0), ok.astype(np.int64)

    def on_watermark(self, watermark: int) -> List[RecordBatch]:
        with self._flight_fire(watermark):
            out = self._on_watermark_inner(int(watermark))
        # replica publish AFTER this boundary's probes/compaction
        self._publish_side_replicas(int(watermark))
        return out

    def _on_watermark_inner(self, watermark: int) -> List[RecordBatch]:
        self._wd_boundary()
        out: List[RecordBatch] = []
        if self._pending:
            left = (self._pending[0] if len(self._pending) == 1
                    else RecordBatch.concat(self._pending))
            ready_mask = left.timestamps <= watermark
            ready = left.filter(ready_mask)
            if len(ready) and self.sides[1] is not None \
                    and self.sides[1].num_rows():
                # sort once by (key, ts): the reference's per-key
                # ordered probe, vectorized — and the left side must
                # know its schema even when it never stores rows
                self._ensure_side(0, ready)
                order = np.lexsort((ready.timestamps, ready.key_ids))
                ready = ready.take(order)
                keys = np.asarray(ready.key_ids, dtype=np.int64)
                ts = np.asarray(ready.timestamps, dtype=np.int64)
                shards = self._shards_of(keys)
                per_shard = {}
                for p in np.unique(shards).tolist():
                    sel = np.nonzero(shards == p)[0]
                    per_shard[int(p)] = (sel, keys[sel], ts[sel])
                # a crash/stall at the versioned-plane lookup: the
                # probe is read-only and the pending left buffer is
                # still intact, so recovery replays this watermark
                # consistently
                chaos.fault_point("join.versioned_lookup",
                                  probes=len(ready))
                probed = self._probe_banded(1, per_shard,
                                            self._version_band)
                probe_cols = {n: np.asarray(ready[n])
                              for n in ready.names()
                              if n not in (KEY_ID_FIELD,
                                           TIMESTAMP_FIELD)}
                m = self._assemble(0, probe_cols, ts, keys, probed,
                                   out_ts=lambda lt, rt: lt)
                if m is not None and len(m):
                    out.append(m)
            elif len(ready) and self.sides[0] is None:
                self._ensure_side(0, ready)
            # buffer mutation AFTER the probe: a crash mid-probe
            # replays with the pending rows intact
            keep = ~ready_mask
            self._pending = ([left.filter(keep)] if keep.any()
                             else [])
        self._emitted_wm = max(self._emitted_wm, watermark)
        self._compact_versions(watermark)
        return out

    def _compact_versions(self, watermark: int) -> None:
        """Keep versions newer than the watermark plus each key's
        single latest at-or-before it (the cleanupState contract)."""
        side = self.sides[1]
        if side is None:
            return
        for p in range(self.P):
            m = side.meta[p]
            if not len(m):
                continue
            future = m.ts > watermark
            last_of_prefix = np.r_[
                (m.key[1:] != m.key[:-1]) | future[1:], True] & ~future
            dead = ~(future | last_of_prefix)
            if dead.any():
                side.drop_positions(p, np.nonzero(dead)[0])

    def _meta_snapshot(self) -> Dict[str, object]:
        pend = (RecordBatch.concat(self._pending)
                if self._pending else None)
        return {
            "emitted_wm": int(self._emitted_wm),
            "late_left_dropped": int(self.late_left_dropped),
            "pending": (dict(pend.columns) if pend is not None
                        else None),
        }

    def _restore_meta(self, snap: Dict[str, object]) -> None:
        self._emitted_wm = int(snap.get("emitted_wm", _NEG))
        self.late_left_dropped = int(snap.get("late_left_dropped", 0))
        pend = snap.get("pending")
        self._pending = (
            [RecordBatch({k: np.asarray(v) for k, v in pend.items()})]
            if pend else [])

    def _merge_meta_units(self, units) -> Dict[str, object]:
        pend_tabs = [u.get("pending") for u in units
                     if u.get("pending")]
        pending = None
        if pend_tabs:
            merged = {
                k: np.concatenate([np.asarray(t[k])
                                   for t in pend_tabs])
                for k in pend_tabs[0]}
            pending = merged
        return {
            # the OLDEST unit's horizon: its range replays from its
            # position and must not be judged late
            "emitted_wm": min((int(u.get("emitted_wm", _NEG))
                               for u in units), default=_NEG),
            "late_left_dropped": max(
                (int(u.get("late_left_dropped", 0)) for u in units),
                default=0),
            "pending": pending,
        }

    def snapshot_sharded(self, mode: str = "full"):
        units = super().snapshot_sharded(mode)
        # pending left rows split by key group like table rows — each
        # unit replays only its own range
        for (g0, g1), unit in units.items():
            pend = unit.get("pending")
            if not pend:
                continue
            kid = np.asarray(pend[KEY_ID_FIELD], dtype=np.int64)
            kg = assign_key_groups(kid, self.max_parallelism)
            mask = (kg >= g0) & (kg <= g1)
            unit["pending"] = {k: np.asarray(v)[mask]
                               for k, v in pend.items()}
        return units
