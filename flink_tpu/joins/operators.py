"""Two-input operator wrappers for the device-native join engines.

These plug the :mod:`flink_tpu.joins.engine` mesh engines into the
DataStream/job-graph runtime exactly like ``WindowAggOperator`` plugs
the mesh window engines in: the operator opens its engine over the
task's mesh (parallelism-clamped to the device count), rides the
configured keyBy data plane (``shuffle.mode``), attaches the job
watchdog, and speaks the checkpoint protocol
(``snapshot_state``/``restore_state(key_group_filter=...)``).

Selected by ``join.mode=device`` (``DeploymentOptions.JOIN_MODE``);
the default host
operators (``runtime/join_operators.py``) remain both the fallback and
the semantics oracle.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.joins.engine import (
    MeshIntervalJoinEngine,
    MeshTemporalJoinEngine,
)
from flink_tpu.runtime.operators import Operator


def _engine_kwargs(ctx, capacity: int, max_device_slots: int,
                   spill_dir: Optional[str],
                   spill_host_max_bytes: int = 0):
    import jax

    effective = max(min(getattr(ctx, "parallelism", 1),
                        len(jax.devices())), 1)
    from flink_tpu.parallel.mesh import make_mesh

    mesh = getattr(ctx, "mesh", None) or make_mesh(effective)
    return dict(
        mesh=mesh,
        capacity_per_shard=capacity,
        max_parallelism=getattr(ctx, "max_parallelism", 128),
        max_device_slots=max_device_slots,
        spill_dir=spill_dir,
        spill_host_max_bytes=spill_host_max_bytes,
        key_group_range=getattr(ctx, "key_group_range", None),
        backend="device",
        shuffle_mode=getattr(ctx, "shuffle_mode", "device"),
    )


class DeviceIntervalJoinOperator(Operator):
    """Keyed interval join on the device state plane (INNER).

    Same stream contract as ``IntervalJoinOperator``: matches emit when
    the second side arrives; watermark advances prune both buffers."""

    name = "device_interval_join"

    def __init__(self, lower: int, upper: int,
                 suffixes: Tuple[str, str] = ("_l", "_r"),
                 capacity: int = 1 << 16,
                 max_device_slots: int = 0,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0) -> None:
        if lower > upper:
            raise ValueError(f"lower {lower} > upper {upper}")
        self.lower = int(lower)
        self.upper = int(upper)
        self.suffixes = tuple(suffixes)
        self._capacity = int(capacity)
        self._max_device_slots = int(max_device_slots)
        self._spill_dir = spill_dir
        self._spill_host_max_bytes = int(spill_host_max_bytes)
        self.engine: Optional[MeshIntervalJoinEngine] = None

    def open(self, ctx) -> None:
        self.engine = MeshIntervalJoinEngine(
            self.lower, self.upper, suffixes=self.suffixes,
            **_engine_kwargs(ctx, self._capacity,
                             self._max_device_slots, self._spill_dir,
                             self._spill_host_max_bytes))
        wd = getattr(ctx, "watchdog", None)
        if wd is not None:
            self.engine.attach_watchdog(wd)

    def process_batch(self, batch, input_index=0) -> List[RecordBatch]:
        return self.engine.process_batch(batch, input_index)

    def process_watermark(self, watermark, input_index=0
                          ) -> List[RecordBatch]:
        return self.engine.on_watermark(int(watermark))

    def close(self) -> List[RecordBatch]:
        from flink_tpu.runtime.elements import MAX_WATERMARK

        return self.engine.on_watermark(MAX_WATERMARK)

    def snapshot_state(self):
        return self.engine.snapshot()

    def restore_state(self, state, key_group_filter=None):
        self.engine.restore(state, key_group_filter=key_group_filter)

    def supports_live_rescale(self) -> bool:
        return True

    def reshard(self, new_shards: int):
        return self.engine.reshard(new_shards)

    def spill_counters(self):
        return self.engine.spill_counters()


class DeviceTemporalJoinOperator(Operator):
    """Event-time temporal join against the versioned device plane."""

    name = "device_temporal_join"

    def __init__(self, suffixes: Tuple[str, str] = ("_l", "_r"),
                 capacity: int = 1 << 16,
                 max_device_slots: int = 0,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0) -> None:
        self.suffixes = tuple(suffixes)
        self._capacity = int(capacity)
        self._max_device_slots = int(max_device_slots)
        self._spill_dir = spill_dir
        self._spill_host_max_bytes = int(spill_host_max_bytes)
        self.engine: Optional[MeshTemporalJoinEngine] = None

    def open(self, ctx) -> None:
        self.engine = MeshTemporalJoinEngine(
            suffixes=self.suffixes,
            **_engine_kwargs(ctx, self._capacity,
                             self._max_device_slots, self._spill_dir,
                             self._spill_host_max_bytes))
        wd = getattr(ctx, "watchdog", None)
        if wd is not None:
            self.engine.attach_watchdog(wd)

    def process_batch(self, batch, input_index=0) -> List[RecordBatch]:
        return self.engine.process_batch(batch, input_index)

    def process_watermark(self, watermark, input_index=0
                          ) -> List[RecordBatch]:
        return self.engine.on_watermark(int(watermark))

    @property
    def late_left_dropped(self) -> int:
        return self.engine.late_left_dropped if self.engine else 0

    def close(self) -> List[RecordBatch]:
        from flink_tpu.runtime.elements import MAX_WATERMARK

        return self.engine.on_watermark(MAX_WATERMARK)

    def snapshot_state(self):
        return self.engine.snapshot()

    def restore_state(self, state, key_group_filter=None):
        self.engine.restore(state, key_group_filter=key_group_filter)

    def supports_live_rescale(self) -> bool:
        return True

    def reshard(self, new_shards: int):
        return self.engine.reshard(new_shards)

    def spill_counters(self):
        return self.engine.spill_counters()
