"""Compiled device programs for the two-input join engines.

Four program families, all cached in the shared
:data:`~flink_tpu.tenancy.program_cache.PROGRAM_CACHE` keyed on
``(device ids, plane dtype layout)`` — never on an engine or job
identity — so rebuilt engines, restarted jobs and concurrent tenants
share the executables (the multi-tenant zero-recompile contract), with
shapes handled one level down by jit + the ``pad_bucket_size`` /
``sticky_bucket`` tier discipline:

- **join-put**: scatter staged ``[P, B]`` row blocks (slot + value
  columns) into the side's ``[P, capacity]`` plane — the host-bucketed
  ingest path (``shuffle.mode=host``).
- **join-exchange-put**: the device-mode ingest: flat staged columns go
  up in ONE ``device_put``, and a single program segment-sorts each
  shard's chunk into per-destination buckets (the stateplane
  ``exchange-rank`` combinator, xla or pallas backend —
  stream order preserved per destination, same as the host path),
  ``all_to_all``-exchanges them over the mesh axis and scatters the
  received rows into the plane — keyBy exchange + state write as one
  XLA program, the join form of
  ``parallel/shuffle.build_exchange_scatter``.
- **join-gather**: plane rows at ``[P, G]`` slot blocks (eviction
  cohorts, snapshots, reshard lifts) — ONE batched D2H per harvest.
- **join-banded-probe**: the banded segment-intersection step. The host
  metadata (sorted ``(key, ts)`` per shard — int64 lives on the host,
  the x32 device plane never sees a key) resolves each probe's band
  ``[lo, lo+cnt)`` over the sorted row order; the program walks every
  probe's band positions, gathers the banded candidates' slots from the
  per-shard sorted-order mirror, masks out-of-band and non-resident
  (spilled, ``slot < 0``) lanes, and gathers the surviving candidates'
  value columns from the slot plane — emitting ``[P, B, W]`` joined
  value columns in band order. The temporal join is the ``W == 1``
  degenerate band (the latest version at-or-before the probe time).

Value columns ride the device plane only when their dtype survives the
x32 backend bit-exactly (float32/int32/bool — see
``side_table.DEVICE_ELIGIBLE``); wider columns stay in the host shadow
store so device and host modes remain bit-identical.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flink_tpu.parallel.mesh import KEY_AXIS, shard_map
from flink_tpu.stateplane.backends import backend_of
from flink_tpu.stateplane.rank import exchange_rank_flat
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE


def _mesh_key(mesh: Mesh) -> Tuple[int, ...]:
    return tuple(d.id for d in mesh.devices.flat)


def build_join_put(mesh: Mesh, dtypes: Tuple[str, ...]):
    """``plane[p, slots] = values`` for [P, B] staged blocks. Padded
    lanes carry slot 0 (the reserved scratch slot) — writes there are
    structurally dead."""
    key = (_mesh_key(mesh), tuple(dtypes))
    return PROGRAM_CACHE.get_or_build(
        "join-put", key, lambda: _build_join_put(mesh, len(dtypes)))


def _build_join_put(mesh: Mesh, n_cols: int):
    @partial(jax.jit, donate_argnums=(0,))
    def put(planes, slots, values):
        def local(*args):
            planes_l = args[:n_cols]
            s = args[n_cols][0]
            vs = args[n_cols + 1:]
            return tuple(pl.at[0, s].set(v[0])
                         for pl, v in zip(planes_l, vs))

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (2 * n_cols + 1),
            out_specs=(P(KEY_AXIS),) * n_cols,
        )(*planes, slots, *values)

    return put


def build_join_exchange_put(mesh: Mesh, dtypes: Tuple[str, ...]):
    """The fused device-mode ingest: segment-sort each shard's flat
    chunk into per-destination buckets, ``all_to_all`` them over the
    mesh axis, scatter the received (slot, values) rows into the plane
    — one compiled program from staged columns to state write."""
    rank_backend = backend_of("exchange-rank")
    key = (_mesh_key(mesh), tuple(dtypes), rank_backend)
    return PROGRAM_CACHE.get_or_build(
        "join-exchange-put", key,
        lambda: _build_join_exchange_put(mesh, len(dtypes), rank_backend))


def _build_join_exchange_put(mesh: Mesh, n_cols: int,
                             rank_backend: str = "xla"):
    num_shards = int(mesh.devices.size)
    sm_kwargs = {"check_rep": False} if rank_backend == "pallas" else {}

    def _exchange(block):
        if num_shards == 1:
            return block
        return jax.lax.all_to_all(block, KEY_AXIS,
                                  split_axis=0, concat_axis=0)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(0,))
    def exchange_put(planes, dst, slots, values, bucket_width):
        W = int(bucket_width)

        def local(*args):
            planes_l = args[:n_cols]
            d = args[n_cols]          # [C] destination shard
            s = args[n_cols + 1]      # [C] destination slot
            vs = args[n_cols + 2:]
            # rank within destination preserves stream order per
            # destination — the same (source, rank) flattening the
            # host bucketing produces (see build_exchange_scatter)
            flat = exchange_rank_flat(d, num_shards, W, rank_backend)
            recv_s = _exchange(
                jnp.zeros((num_shards * W,), jnp.int32)
                .at[flat].set(s, mode="drop")
                .reshape(num_shards, W)).reshape(-1)
            out = []
            for pl, v in zip(planes_l, vs):
                rv = _exchange(
                    jnp.zeros((num_shards * W,), pl.dtype)
                    .at[flat].set(v, mode="drop")
                    .reshape(num_shards, W)).reshape(-1)
                # empty bucket lanes carry recv_s == 0: the reserved
                # scratch slot absorbs them
                out.append(pl.at[0, recv_s].set(rv))
            return tuple(out)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (2 * n_cols + 2),
            out_specs=(P(KEY_AXIS),) * n_cols,
            **sm_kwargs,
        )(*planes, dst, slots, *values)

    return exchange_put


def build_join_gather(mesh: Mesh, dtypes: Tuple[str, ...]):
    """Plane rows at [P, G] slot blocks (evictions, snapshots, reshard
    lifts) — the caller does ONE batched ``device_get`` on the result."""
    key = (_mesh_key(mesh), tuple(dtypes))
    return PROGRAM_CACHE.get_or_build(
        "join-gather", key, lambda: _build_join_gather(mesh, len(dtypes)))


def _build_join_gather(mesh: Mesh, n_cols: int):
    @jax.jit
    def gather(planes, slots):
        def local(*args):
            planes_l = args[:n_cols]
            s = args[n_cols][0]
            return tuple(pl[0][s][None, :] for pl in planes_l)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_cols + 1),
            out_specs=(P(KEY_AXIS),) * n_cols,
        )(*planes, slots)

    return gather


def build_banded_probe(mesh: Mesh, dtypes: Tuple[str, ...]):
    """The banded segment-intersection program: for each probe, gather
    the band's candidate slots from the sorted-order mirror, intersect
    (in-band AND resident) and emit the candidates' value columns as
    ``[P, B, W]`` blocks in band order. Non-resident lanes emit zero;
    the host serves them from the paged spill tier and the in-band
    structure (``lo``/``cnt``) is identical on both sides by
    construction — the host computed it."""
    key = (_mesh_key(mesh), tuple(dtypes))
    return PROGRAM_CACHE.get_or_build(
        "join-banded-probe", key,
        lambda: _build_banded_probe(mesh, len(dtypes)))


def _build_banded_probe(mesh: Mesh, n_cols: int):
    @partial(jax.jit, static_argnums=(4,))
    def probe(planes, sorted_slots, lo, cnt, band_width):
        W = int(band_width)

        def local(*args):
            planes_l = args[:n_cols]
            ss = args[n_cols][0]       # [S] sorted-order slot mirror
            lo_l = args[n_cols + 1][0]  # [B]
            cnt_l = args[n_cols + 2][0]  # [B]
            S = ss.shape[0]
            j = jax.lax.broadcasted_iota(jnp.int32, (lo_l.shape[0], W), 1)
            pos = lo_l[:, None] + j                    # [B, W]
            inband = (j < cnt_l[:, None]) & (pos < S)
            cslot = ss[jnp.clip(pos, 0, S - 1)]        # [B, W]
            ok = inband & (cslot >= 0)
            sc = jnp.clip(cslot, 0, None)
            outs = []
            for pl in planes_l:
                g = pl[0][sc]                          # [B, W]
                outs.append(jnp.where(ok, g,
                                      jnp.zeros((), dtype=pl.dtype))
                            [None, :, :])
            return tuple(outs)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(KEY_AXIS),) * (n_cols + 3),
            out_specs=(P(KEY_AXIS),) * n_cols,
        )(*planes, sorted_slots, lo, cnt)

    return probe
