"""Device-native streaming joins: interval + temporal join kernels
over dual keyed slot tables (see joins/engine.py for the design)."""

from flink_tpu.joins.engine import (  # noqa: F401
    JoinEngineBase,
    MeshIntervalJoinEngine,
    MeshTemporalJoinEngine,
)
from flink_tpu.joins.operators import (  # noqa: F401
    DeviceIntervalJoinOperator,
    DeviceTemporalJoinOperator,
)
from flink_tpu.joins.side_table import (  # noqa: F401
    JoinSideTable,
    pair_lower_bound,
)
