"""One side of a two-input join: a keyed row table over the mesh.

Join state is append-only rows (a buffered left/right record, or one
version of a temporal right side), not merge-on-write accumulators — so
the state plane here is a ROW table: value columns live in ``[P,
capacity]`` device arrays sharded over the key-group axis, while the
row *metadata* (key, event/version time, row id, device slot) stays on
the host, kept sorted by ``(key, ts, rid)`` per shard. That sort order
IS the index both join kernels probe: an interval band or a temporal
version lookup is a pair of lexicographic binary searches over it, and
the banded-probe program gathers candidate slots through a device
mirror of the same order.

Both sides of one join share this class — and share the key routing
(``parallel.shuffle.shard_records``), so a key's left rows and right
rows always land on the same shard and every probe is shard-local (the
keyed-state locality the reference's join operators get from keyed
streams).

Cold rows: when the per-shard device budget fills, the OLDEST rows (by
event time — the ones closest to watermark expiry, hence the least
likely to be probed again) evict as a page cohort through the shared
``state.paged_spill`` machinery, exactly like session state. They are
never reloaded: probes serve them straight from page storage (the
hybrid-fire discipline — join rows are immutable after insert, so a
reload would buy nothing), and watermark pruning drops them from the
membership map.

``backend="host"`` keeps the value columns in host numpy arrays and is
the bit-identical oracle: every metadata decision (sort order, slot
allocation, eviction cohorts, pruning) is shared code, and the value
path is pure movement — no arithmetic — so device and host modes agree
bit-for-bit, including emission order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.paged_spill import (
    PagedSpillMap,
    drop_spilled_sessions,
    read_spilled_rows,
    spill_page,
)
from flink_tpu.state.slot_table import SlotTableFullError, SpillTier

#: dtypes that survive the x32 device backend bit-exactly; anything
#: else (int64 ids, float64, strings/objects) is carried in the host
#: shadow store in BOTH modes so device/host stay bit-identical
DEVICE_ELIGIBLE = ("float32", "int32", "bool")


def pair_lower_bound(sk: np.ndarray, st: np.ndarray,
                     qk: np.ndarray, qt: np.ndarray) -> np.ndarray:
    """Vectorized lexicographic lower bound: for each query ``(qk[i],
    qt[i])``, the first position ``p`` with ``(sk[p], st[p]) >= (qk[i],
    qt[i])`` over the lexicographically sorted pair ``(sk, st)``. The
    branchless binary search the device kernel would run — kept on the
    host because int64 keys cannot ride the x32 device plane."""
    n = len(sk)
    m = len(qk)
    lo = np.zeros(m, dtype=np.int64)
    if n == 0 or m == 0:
        return lo
    hi = np.full(m, n, dtype=np.int64)
    for _ in range(int(n).bit_length()):
        mid = (lo + hi) >> 1
        mid_c = np.minimum(mid, n - 1)  # settled lanes have mid == n
        mk = sk[mid_c]
        mt = st[mid_c]
        less = ((mk < qk) | ((mk == qk) & (mt < qt))) & (lo < hi)
        lo = np.where(less, mid + 1, lo)
        hi = np.where(less, hi, mid)
    return lo


class _ShardMeta:
    """One shard's row metadata, sorted by ``(key, ts, rid)``."""

    __slots__ = ("key", "ts", "rid", "slot", "dirty")

    def __init__(self) -> None:
        self.key = np.empty(0, dtype=np.int64)
        self.ts = np.empty(0, dtype=np.int64)
        self.rid = np.empty(0, dtype=np.int64)
        #: device slot; -1 = spilled (page membership in the pmap)
        self.slot = np.empty(0, dtype=np.int32)
        self.dirty = np.empty(0, dtype=bool)

    def __len__(self) -> int:
        return len(self.key)

    def merge_rows(self, key, ts, rid, slot, dirty) -> None:
        k2 = np.concatenate([self.key, key])
        t2 = np.concatenate([self.ts, ts])
        r2 = np.concatenate([self.rid, rid])
        s2 = np.concatenate([self.slot, slot])
        d2 = np.concatenate([self.dirty, dirty])
        # rid is allocation-monotonic, so the (key, ts, rid) order is a
        # total order and every backend sorts rows identically
        o = np.lexsort((r2, t2, k2))
        self.key, self.ts, self.rid = k2[o], t2[o], r2[o]
        self.slot, self.dirty = s2[o], d2[o]

    def compress(self, keep: np.ndarray) -> None:
        self.key = self.key[keep]
        self.ts = self.ts[keep]
        self.rid = self.rid[keep]
        self.slot = self.slot[keep]
        self.dirty = self.dirty[keep]


class JoinSideTable:
    """Per-side keyed row table: device (or host-oracle) value plane +
    sorted host metadata + paged spill tier, one of each per shard."""

    def __init__(self, num_shards: int, capacity: int,
                 schema: Sequence[Tuple[str, np.dtype]],
                 max_device_slots: int = 0,
                 spill_dir: Optional[str] = None,
                 spill_host_max_bytes: int = 0,
                 backend: str = "device") -> None:
        if backend not in ("device", "host"):
            raise ValueError(
                f"backend must be 'device' or 'host', got {backend!r}")
        self.P = int(num_shards)
        self.backend = backend
        self.max_device_slots = int(max_device_slots or 0)
        self.capacity = max(int(capacity), 256)
        if self.max_device_slots:
            self.max_device_slots = max(self.max_device_slots, 256)
            self.capacity = min(self.capacity, self.max_device_slots)
        #: (name, numpy dtype) per value column, sorted by name — the
        #: one canonical column order shared by planes, page entries
        #: and snapshots
        self.schema: List[Tuple[str, np.dtype]] = [
            (str(n), np.dtype(dt)) for n, dt in schema]
        self.device_cols: List[int] = [
            i for i, (_, dt) in enumerate(self.schema)
            if dt.name in DEVICE_ELIGIBLE]
        self.host_cols: List[int] = [
            i for i in range(len(self.schema))
            if i not in self.device_cols]
        self.meta: List[_ShardMeta] = [_ShardMeta()
                                       for _ in range(self.P)]
        #: per-shard free slots, slot 0 reserved as scratch (padding
        #: lanes of every staged block write there)
        self._free: List[np.ndarray] = [
            np.arange(self.capacity - 1, 0, -1, dtype=np.int32)
            for _ in range(self.P)]
        #: host shadow store for device-ineligible columns (and the
        #: whole store in host mode): one [P, capacity] array per col
        self.shadow: Dict[int, np.ndarray] = {}
        shadow_idx = (range(len(self.schema))
                      if backend == "host" else self.host_cols)
        for i in shadow_idx:
            self.shadow[i] = np.zeros((self.P, self.capacity),
                                      dtype=self.schema[i][1])
        self._spill_dir = spill_dir
        # host page-memory budget per SHARD (the engine already split
        # the operator budget across sides): pages past it overflow to
        # the filesystem tier, like every other keyed-state operator
        self.spills: List[SpillTier] = [
            SpillTier(f"{spill_dir.rstrip('/')}/shard-{p}"
                      if spill_dir else None,
                      spill_host_max_bytes // self.P
                      if spill_host_max_bytes else 0)
            for p in range(self.P)]
        self.pmaps: List[PagedSpillMap] = [PagedSpillMap()
                                           for _ in range(self.P)]
        #: probe rows answered from page storage (the no-vacuous-spill
        #: gate in tools/join_smoke.py reads this)
        self.cold_rows_served = 0

    # ------------------------------------------------------------ accounting

    @property
    def spill_active(self) -> bool:
        return self.max_device_slots > 0

    def num_rows(self) -> int:
        return sum(len(m) for m in self.meta) + sum(
            len(pm) for pm in self.pmaps)

    def resident_rows(self) -> List[int]:
        return [int((m.slot >= 0).sum()) for m in self.meta]

    def spill_counters(self) -> Dict[str, int]:
        out = PagedSpillMap.zero_counters()
        for pm in self.pmaps:
            for k, v in pm.counters().items():
                out[k] += v
        out["cold_rows_served"] = int(self.cold_rows_served)
        return out

    def dtypes_key(self) -> Tuple[str, ...]:
        """The device-plane dtype layout — the program-cache key part."""
        return tuple(self.schema[i][1].name for i in self.device_cols)

    # ------------------------------------------------------------ allocation

    def free_headroom(self, p: int) -> int:
        return len(self._free[p])

    def allocate(self, p: int, n: int) -> np.ndarray:
        """``n`` fresh slots on shard ``p`` — the caller made headroom
        (eviction happens engine-side: it dispatches a device gather)."""
        free = self._free[p]
        if len(free) < n:
            raise SlotTableFullError(
                f"join side table shard {p}: {n} slots needed, "
                f"{len(free)} free — eviction failed to make headroom")
        slots, self._free[p] = free[-n:][::-1].copy(), free[:-n]
        return slots

    def release(self, p: int, slots: np.ndarray) -> None:
        if len(slots):
            self._free[p] = np.concatenate(
                [self._free[p], np.asarray(slots, dtype=np.int32)])

    def grow(self, new_capacity: int) -> None:
        """Widen the shadow store (the engine widens the device plane —
        uniform across shards, like the mesh engines' grow)."""
        old = self.capacity
        if new_capacity <= old:
            return
        self.capacity = new_capacity
        for i, arr in list(self.shadow.items()):
            wide = np.zeros((self.P, new_capacity), dtype=arr.dtype)
            wide[:, :old] = arr
            self.shadow[i] = wide
        for p in range(self.P):
            self._free[p] = np.concatenate([
                self._free[p],
                np.arange(new_capacity - 1, old - 1, -1,
                          dtype=np.int32)])

    # ------------------------------------------------------------- eviction

    def choose_eviction(self, p: int, needed: int) -> np.ndarray:
        """Metadata positions of the eviction cohort on shard ``p``:
        the OLDEST resident rows (stable by metadata order), enough to
        free ``needed`` slots plus workable headroom. Pure metadata —
        both backends choose identically."""
        m = self.meta[p]
        res = np.nonzero(m.slot >= 0)[0]
        if not len(res):
            raise SlotTableFullError(
                f"join side table shard {p}: device budget exhausted "
                "with no resident rows to evict — raise the budget or "
                "reduce batch size")
        target = min(len(res),
                     max(needed, self.capacity // 8, 256))
        order = np.argsort(m.ts[res], kind="stable")
        return res[order[:target]]

    def evict_rows(self, p: int, pos: np.ndarray,
                   values: List[np.ndarray]) -> np.ndarray:
        """Move the cohort at metadata positions ``pos`` (values
        already gathered by the engine, schema order) into one page;
        returns the freed slots."""
        m = self.meta[p]
        slots = m.slot[pos].copy()
        entry = {
            "key_id": m.key[pos].copy(),
            "ns": m.rid[pos].copy(),
            "dirty": m.dirty[pos].copy(),
            **{f"leaf_{i}": np.asarray(values[i])
               for i in range(len(self.schema))},
        }
        spill_page(self.spills[p], self.pmaps[p], entry)
        m.slot[pos] = -1
        self.release(p, slots)
        return slots

    def shadow_values(self, p: int, pos: np.ndarray
                      ) -> List[np.ndarray]:
        """Host-readable value columns at metadata positions (host
        backend: every column; device backend: only shadow columns —
        the engine fills the device columns from its gather)."""
        m = self.meta[p]
        slots = np.clip(m.slot[pos], 0, None)
        out: List[np.ndarray] = []
        for i, (_, dt) in enumerate(self.schema):
            if i in self.shadow:
                out.append(self.shadow[i][p][slots].copy())
            else:
                out.append(np.zeros(len(pos), dtype=dt))
        return out

    # ------------------------------------------------------------- pruning

    def prune(self, min_ts: int) -> int:
        """Drop rows with ``ts < min_ts`` (watermark expiry): resident
        slots free, cold rows unmap from their pages (fully-dead pages
        reap, mostly-dead ones compact). Returns rows dropped."""
        dropped = 0
        for p in range(self.P):
            m = self.meta[p]
            if not len(m):
                continue
            dead = m.ts < min_ts
            if not dead.any():
                continue
            dropped += int(dead.sum())
            res = dead & (m.slot >= 0)
            if res.any():
                self.release(p, m.slot[res])
            cold = dead & (m.slot < 0)
            if cold.any():
                drop_spilled_sessions(self.spills[p], self.pmaps[p],
                                      m.rid[cold])
            m.compress(~dead)
        return dropped

    def drop_positions(self, p: int, pos: np.ndarray) -> None:
        """Drop specific metadata positions (temporal compaction)."""
        if not len(pos):
            return
        m = self.meta[p]
        dead = np.zeros(len(m), dtype=bool)
        dead[pos] = True
        res = dead & (m.slot >= 0)
        if res.any():
            self.release(p, m.slot[res])
        cold = dead & (m.slot < 0)
        if cold.any():
            drop_spilled_sessions(self.spills[p], self.pmaps[p],
                                  m.rid[cold])
        m.compress(~dead)

    # ------------------------------------------------------------ cold reads

    def fill_cold(self, p: int, wants: List[Tuple[int, int, int]],
                  sinks: List[np.ndarray],
                  rows: np.ndarray) -> None:
        """Serve spilled rows into output columns: ``wants`` is
        ``(out_row, key_id, rid)``; ``sinks[i][rows[out_row]]`` receives
        column ``i``. One page peek per touched page
        (``read_spilled_rows`` — the serving-plane discipline)."""
        if not wants:
            return

        def on_row(tag, entry, src):
            for i in range(len(self.schema)):
                sinks[i][rows[tag]] = entry[f"leaf_{i}"][src]
            self.cold_rows_served += 1

        read_spilled_rows(self.spills[p], self.pmaps[p], True,
                          wants, on_row)

    # ------------------------------------------------------------- snapshot

    def snapshot_rows(self, max_parallelism: int,
                      device_values) -> Dict[str, np.ndarray]:
        """Logical rows (resident + spilled), canonically ordered by
        rid so snapshot -> restore -> snapshot round-trips bit-exactly
        whatever the residency split. ``device_values``: per-shard
        ``{col_index: [capacity] host array}`` for the device columns
        (the engine did ONE batched device_get); host mode passes the
        shadow store through."""
        keys, tss, rids, dirties = [], [], [], []
        leaf_chunks: List[List[np.ndarray]] = [
            [] for _ in self.schema]
        for p in range(self.P):
            m = self.meta[p]
            res = np.nonzero(m.slot >= 0)[0]
            if len(res):
                keys.append(m.key[res])
                tss.append(m.ts[res])
                rids.append(m.rid[res])
                dirties.append(m.dirty[res])
                slots = m.slot[res]
                for i in range(len(self.schema)):
                    src = (self.shadow[i][p] if i in self.shadow
                           else device_values[p][i])
                    leaf_chunks[i].append(np.asarray(src)[slots])
            pm = self.pmaps[p]
            sp = self.spills[p]
            for page in sorted(pm.page_rows):
                entry = sp.peek(int(page))
                if entry is None:
                    continue
                rns = np.asarray(entry["ns"], dtype=np.int64)
                alive = pm.live_row_mask(int(page), rns)
                if not alive.any():
                    continue
                keys.append(np.asarray(entry["key_id"],
                                       dtype=np.int64)[alive])
                rids.append(rns[alive])
                dirties.append(np.asarray(entry["dirty"],
                                          dtype=bool)[alive])
                # cold ts from the metadata? cold rows left the
                # metadata arrays' SLOT but not the arrays themselves
                # — find their ts by rid
                mk, mpos = _rid_positions(m.rid, rns[alive])
                ts_cold = np.zeros(int(alive.sum()), dtype=np.int64)
                ts_cold[mk] = m.ts[mpos]
                tss.append(ts_cold)
                for i in range(len(self.schema)):
                    leaf_chunks[i].append(
                        np.asarray(entry[f"leaf_{i}"])[alive])
        if not keys:
            return {
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "ts": np.empty(0, dtype=np.int64),
                "dirty": np.empty(0, dtype=bool),
                "key_group": np.empty(0, dtype=np.int32),
                **{f"leaf_{i}": np.empty(0, dtype=dt)
                   for i, (_, dt) in enumerate(self.schema)},
            }
        key_id = np.concatenate(keys)
        rid = np.concatenate(rids)
        order = np.argsort(rid, kind="stable")
        out = {
            "key_id": key_id[order],
            "namespace": rid[order],
            "ts": np.concatenate(tss)[order],
            "dirty": np.concatenate(dirties)[order],
            "key_group": assign_key_groups(
                key_id[order], max_parallelism),
        }
        for i in range(len(self.schema)):
            out[f"leaf_{i}"] = np.concatenate(leaf_chunks[i])[order]
        return out


def _rid_positions(sorted_source: np.ndarray, queries: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Positions of ``queries`` in an UNSORTED rid array (rids are
    unique): returns (found_mask_over_queries, source_positions)."""
    order = np.argsort(sorted_source, kind="stable")
    srt = sorted_source[order]
    if not len(srt):
        return (np.zeros(len(queries), dtype=bool),
                np.empty(0, dtype=np.int64))
    pos = np.minimum(np.searchsorted(srt, queries), len(srt) - 1)
    found = srt[pos] == queries
    return found, order[pos[found]]
