"""State Processor API: offline read / transform / bootstrap of snapshots.

reference: flink-libraries/flink-state-processing-api —
SavepointReader.java (read keyed state of an operator as a DataSet) and
SavepointWriter.java (bootstrap new state / withOperator / removeOperator /
write). The reference runs these as batch jobs; here snapshots are logical
columnar tables already (key_id / namespace / key_group / leaf arrays — the
SlotTable.snapshot format), so reading is a direct columnar load and
bootstrapping is building those columns — no cluster needed.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.checkpoint.savepoint import write_savepoint
from flink_tpu.checkpoint.storage import (
    read_checkpoint_chain,
    read_manifest,
    resolve_snapshot_dir,
)
from flink_tpu.core.records import RecordBatch
from flink_tpu.state.keygroups import assign_key_groups

__all__ = [
    "SavepointReader",
    "SavepointWriter",
    "KeyedStateBootstrap",
]


def _find_table(state: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Locate the logical keyed-state table inside an operator state dict
    (depth-first: operators nest their windower/table state under their own
    keys, e.g. {"windower": {"table": {...}}})."""
    if "key_id" in state:
        return state
    for v in state.values():
        if isinstance(v, dict):
            t = _find_table(v)
            if t is not None:
                return t
    return None


class SavepointReader:
    """Read an existing savepoint / checkpoint.

    reference: state/api/SavepointReader.java (readKeyedState et al.).
    """

    def __init__(self, snapshot_dir: str, manifest: Dict[str, Any],
                 states: Dict[str, Dict[str, Any]]):
        self.path = snapshot_dir
        self.manifest = manifest
        self._states = states

    @staticmethod
    def load(path: str) -> "SavepointReader":
        """``path`` may be a savepoint dir, a single checkpoint dir, or a
        checkpoint root (newest chk-N wins)."""
        d = resolve_snapshot_dir(path)
        return SavepointReader(d, read_manifest(d), read_checkpoint_chain(d))

    # -- inspection ----------------------------------------------------------

    @property
    def job_name(self) -> str:
        return self.manifest["job_name"]

    @property
    def checkpoint_id(self) -> int:
        return int(self.manifest["checkpoint_id"])

    def operators(self) -> List[str]:
        return list(self._states)

    def read_state(self, uid: str) -> Dict[str, Any]:
        """The operator's raw state dict (keyed table + host metadata)."""
        if uid not in self._states:
            raise KeyError(
                f"no state for operator {uid!r}; available: "
                f"{sorted(self._states)}")
        return self._states[uid]

    def has_keyed_state(self, uid: str) -> bool:
        return _find_table(self.read_state(uid)) is not None

    def read_keyed_state(self, uid: str) -> RecordBatch:
        """The operator's keyed state as a columnar batch with key_id /
        namespace / key_group / leaf_i columns."""
        table = _find_table(self.read_state(uid))
        if table is None:
            raise ValueError(f"operator {uid!r} has no keyed state table")
        cols = {k: np.asarray(v) for k, v in table.items()
                if isinstance(v, np.ndarray)}
        return RecordBatch(cols)

    def read_source_position(self, uid: str) -> Any:
        state = self.read_state(uid)
        if "source" not in state:
            raise ValueError(f"operator {uid!r} is not a source")
        return state["source"]


class KeyedStateBootstrap:
    """Build a keyed-state table for one operator from raw columns.

    reference: state/api/KeyedStateBootstrapFunction — here vectorized:
    pass whole arrays instead of a per-record callback.
    """

    def __init__(self, key_ids: Sequence[int], namespaces: Sequence[int],
                 leaves: Sequence[np.ndarray], max_parallelism: int = 128,
                 extra_state: Optional[Dict[str, Any]] = None):
        key_ids = np.asarray(key_ids, dtype=np.int64)
        namespaces = np.asarray(namespaces, dtype=np.int64)
        if len(key_ids) != len(namespaces):
            raise ValueError("key_ids and namespaces must align")
        for leaf in leaves:
            if len(leaf) != len(key_ids):
                raise ValueError("every leaf must align with key_ids")
        self.table: Dict[str, Any] = {
            "key_id": key_ids,
            "namespace": namespaces,
            "key_group": assign_key_groups(key_ids, max_parallelism),
            **{f"leaf_{i}": np.asarray(leaf)
               for i, leaf in enumerate(leaves)},
        }
        self.extra_state = extra_state or {}

    def to_state(self) -> Dict[str, Any]:
        return {"table": self.table, **self.extra_state}


class SavepointWriter:
    """Create or derive a savepoint.

    reference: state/api/SavepointWriter.java — newSavepoint /
    fromExistingSavepoint + withOperator / removeOperator / write.
    """

    def __init__(self, states: Optional[Dict[str, Dict[str, Any]]] = None,
                 job_name: str = "bootstrap", checkpoint_id: int = 0):
        self._states: Dict[str, Dict[str, Any]] = dict(states or {})
        self.job_name = job_name
        self.checkpoint_id = checkpoint_id

    @staticmethod
    def new_savepoint(job_name: str = "bootstrap") -> "SavepointWriter":
        return SavepointWriter(job_name=job_name)

    @staticmethod
    def from_existing(path: str) -> "SavepointWriter":
        reader = SavepointReader.load(path)
        return SavepointWriter(dict(reader._states), reader.job_name,
                               reader.checkpoint_id)

    # -- mutation ------------------------------------------------------------

    def with_operator(self, uid: str, bootstrap) -> "SavepointWriter":
        """Attach state for ``uid`` (a KeyedStateBootstrap or raw dict)."""
        state = (bootstrap.to_state()
                 if isinstance(bootstrap, KeyedStateBootstrap)
                 else dict(bootstrap))
        self._states[uid] = state
        return self

    def transform_operator(
            self, uid: str,
            fn: Callable[[Dict[str, Any]], Dict[str, Any]]
    ) -> "SavepointWriter":
        """Rewrite an operator's state dict through ``fn`` (schema
        migration, filtering, rescaling prep...)."""
        if uid not in self._states:
            raise KeyError(f"no operator {uid!r} to transform")
        self._states[uid] = fn(self._states[uid])
        return self

    def remove_operator(self, uid: str) -> "SavepointWriter":
        self._states.pop(uid, None)
        return self

    # -- output --------------------------------------------------------------

    def write(self, path: str) -> str:
        if os.path.exists(os.path.join(path, "manifest.json")):
            raise FileExistsError(
                f"refusing to overwrite existing snapshot at {path!r}")
        return write_savepoint(path, self.job_name, self._states,
                               checkpoint_id=self.checkpoint_id)
