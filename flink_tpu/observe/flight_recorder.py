"""Flight recorder: always-on, bounded-overhead per-batch pipeline
tracing.

Every perf round so far rediscovered WHERE the time went through ad-hoc
bench counters (``host_prep_fraction``, ``native_sweep_s``,
``pipeline_wait_s``); the reference dedicates a whole layer to making
that a standing capability (SURVEY/PAPER §5 — spans, flame graphs,
latency markers, the webmonitor). This module is that layer for the
micro-batch mesh engines: a process-global recorder the hot paths write
into unconditionally, cheap enough to leave on (the tier-1 trace smoke
gates recorder-on throughput at <=3% of recorder-off).

Design constraints, in order:

- **No allocation on the hot path.** Each thread owns preallocated
  parallel numpy arrays (a ring: drop-oldest by cursor wraparound) and
  a reusable stack of span context managers — recording one span is a
  handful of scalar stores, no objects, no locks (per-thread rings;
  the registry lock is taken once per thread lifetime).
- **Monotonic clock.** Spans time with ``time.perf_counter``; one
  ``(wall, perf)`` anchor pair taken at recorder creation maps records
  onto the wall clock for export.
- **Correlated attribution.** Every record carries ``(job, shard,
  batch_id, watermark)``. Call sites pass what they know; the rest is
  inherited from an ambient per-thread context (``set_job`` /
  ``set_batch`` / ``set_watermark``) so the executor names the job
  once, the engine names the batch once, and a harvest three layers
  down still lands attributed.
- **One timeline.** Durations (batch lifecycle, fires, harvests,
  checkpoints, serving lookups) and instants (XLA backend compiles,
  D2H materializations, watchdog deadline misses, armed chaos
  injections) interleave in the same ring, so a mystery fire-p99 spike
  reads directly as "compile under fire span on shard 3" in Perfetto.

Span kinds are a closed registry (:data:`flink_tpu.observe.
KNOWN_SPAN_KINDS`): an unregistered kind raises at the call site, and
flint's REG03 cross-checks every literal producer statically — the
recorder, the exporter schema and the trace smoke cannot drift.

Usage::

    from flink_tpu.observe import flight_recorder as flight

    flight.set_job("pipeline-a")
    with flight.span("batch.ingest", shard=-1, batch=seq):
        ...
    flight.instant("watchdog.miss", shard=3)

Disable with ``FLINK_TPU_FLIGHT_RECORDER=0`` (spans become no-ops that
cost one module-global check), or per-region with :func:`disabled`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterator, List, NamedTuple, Optional

import numpy as np

#: sentinel for "no watermark attribution" (int64 min would render as
#: a plausible timestamp; this is unmistakably absent)
WM_NONE = -(1 << 62)

#: per-thread ring capacity (records); power of two so the drop-oldest
#: wraparound is a mask, not a modulo
_CAPACITY = 1 << int(os.environ.get(
    "FLINK_TPU_FLIGHT_RECORDER_CAPACITY_POW2", "16"))
#: per-kind duration reservoir depth (overwritten modulo — a cheap
#: recent-window sample, not a full history)
_RESERVOIR = 256

_enabled = os.environ.get("FLINK_TPU_FLIGHT_RECORDER", "1") != "0"


class SpanRecord(NamedTuple):
    """One decoded record (``snapshot()`` output)."""

    kind: str
    instant: bool
    t0: float          # perf_counter seconds
    t1: float
    job: Optional[str]
    shard: int
    batch_id: int
    watermark: Optional[int]
    thread: str

    @property
    def duration_s(self) -> float:
        return self.t1 - self.t0


class _SpanCtx:
    """Reusable span context manager (pooled per thread — entering a
    span allocates nothing once the pool is warm)."""

    __slots__ = ("_ring", "_kind", "_shard", "_batch", "_wm", "_job",
                 "_t0")

    def __init__(self, ring: "_ThreadRing") -> None:
        self._ring = ring

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        r = self._ring
        r.write(self._kind, 0, self._t0, time.perf_counter(),
                self._job, self._shard, self._batch, self._wm)
        r.pool.append(self)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_SPAN = _NullSpan()


class _ThreadRing:
    """One thread's preallocated record ring + per-kind aggregates +
    span-context pool. Single-writer (the owning thread); snapshot
    readers copy the arrays, which is safe because writes are
    monotonic scalar stores and a torn read costs at most one
    half-written record at the cursor."""

    def __init__(self, n_kinds: int, name: str) -> None:
        self.name = name
        cap = _CAPACITY
        self.mask = cap - 1
        self.cursor = 0
        self.kind = np.zeros(cap, dtype=np.int16)
        self.flags = np.zeros(cap, dtype=np.int8)
        self.t0 = np.zeros(cap, dtype=np.float64)
        self.t1 = np.zeros(cap, dtype=np.float64)
        self.job = np.full(cap, -1, dtype=np.int32)
        self.shard = np.full(cap, -1, dtype=np.int32)
        self.batch = np.full(cap, -1, dtype=np.int64)
        self.wm = np.full(cap, WM_NONE, dtype=np.int64)
        # per-kind duration aggregates (merged across threads on read)
        self.k_count = np.zeros(n_kinds, dtype=np.int64)
        self.k_total = np.zeros(n_kinds, dtype=np.float64)
        self.k_max = np.zeros(n_kinds, dtype=np.float64)
        self.k_res = np.zeros((n_kinds, _RESERVOIR), dtype=np.float32)
        self.k_cursor = np.zeros(n_kinds, dtype=np.int64)
        # ambient attribution context (set by the layer that knows)
        self.ctx_job = -1
        self.ctx_batch = -1
        self.ctx_wm = WM_NONE
        self.pool: List[_SpanCtx] = [_SpanCtx(self) for _ in range(8)]

    def write(self, kind_id: int, flags: int, t0: float, t1: float,
              job: int, shard: int, batch: int, wm: int) -> None:
        i = self.cursor & self.mask
        self.cursor += 1
        self.kind[i] = kind_id
        self.flags[i] = flags
        self.t0[i] = t0
        self.t1[i] = t1
        self.job[i] = job
        self.shard[i] = shard
        self.batch[i] = batch
        self.wm[i] = wm
        # counts aggregate for EVERY record (an operator reading
        # flight.chaos_inject_count must see armed injections);
        # durations only for spans — instants' quantiles stay 0
        self.k_count[kind_id] += 1
        if not flags:
            d = t1 - t0
            self.k_total[kind_id] += d
            if d > self.k_max[kind_id]:
                self.k_max[kind_id] = d
            self.k_res[kind_id, self.k_cursor[kind_id] % _RESERVOIR] = d
            self.k_cursor[kind_id] += 1


class FlightRecorder:
    """The process-global span plane (see module docstring). Normally
    used through the module-level :func:`span` / :func:`instant`;
    constructing private instances is for tests."""

    def __init__(self, kinds) -> None:
        self.kinds = tuple(kinds)
        self._kind_id = {k: i for i, k in enumerate(self.kinds)}
        if len(self._kind_id) != len(self.kinds):
            raise ValueError("duplicate span kinds")
        self._lock = threading.Lock()
        self._rings: List[_ThreadRing] = []
        self._tl = threading.local()
        self._jobs: List[str] = []
        self._job_id: Dict[str, int] = {}
        #: (wall, perf) anchor: wall = anchor[0] + (t - anchor[1])
        self.anchor = (time.time(), time.perf_counter())

    # ------------------------------------------------------------ hot path

    def _ring(self) -> _ThreadRing:
        ring = getattr(self._tl, "ring", None)
        if ring is None:
            ring = _ThreadRing(len(self.kinds),
                               threading.current_thread().name)
            with self._lock:
                self._rings.append(ring)
            self._tl.ring = ring
        return ring

    def span(self, kind: str, shard: int = -1, batch: int = -1,
             watermark: int = WM_NONE, job: Optional[str] = None):
        """Context manager timing one lifecycle section. Unspecified
        attribution falls back to the thread's ambient context."""
        if not _enabled:
            return _NULL_SPAN
        ring = self._ring()
        pool = ring.pool
        ctx = pool.pop() if pool else _SpanCtx(ring)
        ctx._kind = self._kind_id[kind]
        ctx._shard = shard
        ctx._batch = batch if batch >= 0 else ring.ctx_batch
        ctx._wm = watermark if watermark != WM_NONE else ring.ctx_wm
        ctx._job = self.job_id(job) if job is not None else ring.ctx_job
        return ctx

    def instant(self, kind: str, shard: int = -1, batch: int = -1,
                watermark: int = WM_NONE, job: Optional[str] = None,
                t0: Optional[float] = None,
                duration_s: float = 0.0) -> None:
        """Record an instant event (or a short externally-timed span,
        e.g. an XLA compile whose duration arrives via monitoring:
        pass ``duration_s`` and it lands as ``[now - d, now]``)."""
        if not _enabled:
            return
        ring = self._ring()
        now = time.perf_counter() if t0 is None else t0
        ring.write(
            self._kind_id[kind], 0 if duration_s > 0.0 else 1,
            now - duration_s, now,
            self.job_id(job) if job is not None else ring.ctx_job,
            shard,
            batch if batch >= 0 else ring.ctx_batch,
            watermark if watermark != WM_NONE else ring.ctx_wm)

    # ------------------------------------------------------ ambient context

    def job_id(self, name: str) -> int:
        # flint: disable=LCK01 -- deliberate double-checked fast path
        # on the per-span hot path: entries are insert-only and the
        # slow path re-checks under the lock before assigning
        jid = self._job_id.get(name)
        if jid is None:
            with self._lock:
                jid = self._job_id.get(name)
                if jid is None:
                    jid = len(self._jobs)
                    self._jobs.append(name)
                    self._job_id[name] = jid
        return jid

    def set_job(self, name: Optional[str]) -> None:
        self._ring().ctx_job = -1 if name is None else self.job_id(name)

    def set_batch(self, batch_id: int) -> None:
        self._ring().ctx_batch = int(batch_id)

    def set_watermark(self, wm: int) -> None:
        self._ring().ctx_wm = int(wm)

    # ------------------------------------------------------------- reading

    def _iter_rings(self) -> Iterator[_ThreadRing]:
        with self._lock:
            rings = list(self._rings)
        return iter(rings)

    def snapshot(self) -> List[SpanRecord]:
        """Decode every thread's ring, merged and sorted by start time.
        Half-open rings decode their written prefix; full rings decode
        all records (oldest first is not guaranteed across the wrap —
        the sort restores global time order)."""
        out: List[SpanRecord] = []
        with self._lock:
            jobs = list(self._jobs)
        for ring in self._iter_rings():
            n = min(ring.cursor, ring.mask + 1)
            if n == 0:
                continue
            for i in range(n):
                jid = int(ring.job[i])
                wm = int(ring.wm[i])
                out.append(SpanRecord(
                    kind=self.kinds[int(ring.kind[i])],
                    instant=bool(ring.flags[i]),
                    t0=float(ring.t0[i]), t1=float(ring.t1[i]),
                    job=jobs[jid] if 0 <= jid < len(jobs) else None,
                    shard=int(ring.shard[i]),
                    batch_id=int(ring.batch[i]),
                    watermark=None if wm == WM_NONE else wm,
                    thread=ring.name))
        out.sort(key=lambda r: r.t0)
        return out

    def dropped(self) -> int:
        """Records overwritten by the drop-oldest policy so far."""
        return sum(max(0, r.cursor - (r.mask + 1))
                   for r in self._iter_rings())

    def kind_totals(self) -> Dict[str, Dict[str, float]]:
        """Per-kind aggregates merged across threads: ``{kind: {count,
        total_s, max_s, p50_ms, p99_ms}}`` (quantiles over the bounded
        recent-window reservoirs; instants contribute counts only).
        Memoized on the rings' cursors: a metrics scrape reading many
        gauges pays ONE merge, not one per gauge."""
        from flink_tpu.metrics.core import quantile_sorted

        version = tuple(r.cursor for r in self._iter_rings())
        cached = getattr(self, "_kt_cache", None)
        if cached is not None and cached[0] == version:
            return cached[1]
        n = len(self.kinds)
        count = np.zeros(n, dtype=np.int64)
        total = np.zeros(n, dtype=np.float64)
        kmax = np.zeros(n, dtype=np.float64)
        samples: List[List[float]] = [[] for _ in range(n)]
        for ring in self._iter_rings():
            count += ring.k_count
            total += ring.k_total
            kmax = np.maximum(kmax, ring.k_max)
            for k in range(n):
                m = int(min(ring.k_cursor[k], _RESERVOIR))
                if m:
                    samples[k].extend(ring.k_res[k, :m].tolist())
        out: Dict[str, Dict[str, float]] = {}
        for k, kind in enumerate(self.kinds):
            if not count[k]:
                continue
            data = sorted(samples[k])
            out[kind] = {
                "count": int(count[k]),
                "total_s": float(total[k]),
                "max_s": float(kmax[k]),
                "p50_ms": quantile_sorted(data, 0.5) * 1e3,
                "p99_ms": quantile_sorted(data, 0.99) * 1e3,
            }
        self._kt_cache = (version, out)
        return out

    def clear(self) -> None:
        """Reset every ring and aggregate (keeps thread registrations
        and job interning — cheap, called between bench reps)."""
        # cursors reset below, and a later refill can land on the same
        # cursor tuple a cached merge was keyed on — drop it explicitly
        self._kt_cache = None
        for ring in self._iter_rings():
            ring.cursor = 0
            ring.k_count[:] = 0
            ring.k_total[:] = 0.0
            ring.k_max[:] = 0.0
            ring.k_cursor[:] = 0


# ------------------------------------------------------------- module API

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-global recorder (created on first use)."""
    global _recorder
    # flint: disable=LCK01 -- double-checked publish of an immutable
    # singleton slot; the slow path re-checks under the lock
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                from flink_tpu.observe import KNOWN_SPAN_KINDS

                _recorder = FlightRecorder(KNOWN_SPAN_KINDS)
    # flint: disable=LCK01 -- read of the published immutable singleton
    return _recorder


def span(kind: str, shard: int = -1, batch: int = -1,
         watermark: int = WM_NONE, job: Optional[str] = None):
    if not _enabled:
        return _NULL_SPAN
    return recorder().span(kind, shard=shard, batch=batch,
                           watermark=watermark, job=job)


def instant(kind: str, shard: int = -1, batch: int = -1,
            watermark: int = WM_NONE, job: Optional[str] = None,
            t0: Optional[float] = None, duration_s: float = 0.0) -> None:
    if not _enabled:
        return
    recorder().instant(kind, shard=shard, batch=batch,
                       watermark=watermark, job=job, t0=t0,
                       duration_s=duration_s)


def set_job(name: Optional[str]) -> None:
    if _enabled:
        recorder().set_job(name)


def set_batch(batch_id: int) -> None:
    if _enabled:
        recorder().set_batch(batch_id)


def set_watermark(wm: int) -> None:
    if _enabled:
        recorder().set_watermark(wm)


def ingest_span(seq: int):
    """THE ingest-span contract, in one place for every engine base
    (mesh window/session, joins): name the batch in the ambient
    context, then open ``batch.ingest`` carrying it."""
    set_batch(seq)
    return span("batch.ingest", batch=seq)


def fire_span(watermark: int):
    """THE fire-span contract: note the watermark in the ambient
    context, then open ``fire.dispatch`` carrying it."""
    set_watermark(int(watermark))
    return span("fire.dispatch", watermark=int(watermark))


def enabled() -> bool:
    return _enabled


class disabled:
    """Context manager suppressing recording (the trace smoke's A/B
    lever; also usable to exclude a noisy region)."""

    def __enter__(self):
        global _enabled
        self._prev = _enabled
        _enabled = False
        return self

    def __exit__(self, *exc):
        global _enabled
        _enabled = self._prev
        return None


def _probe_compile(duration_s: float) -> None:
    """recompile-sentinel subscriber: one real XLA backend compile
    lands as a duration span ending now (jax.monitoring reports the
    compile's length, not its start)."""
    if _enabled:
        recorder().instant("xla.compile", duration_s=duration_s)


def _probe_transfer() -> None:
    """recompile-sentinel subscriber: one device->host materialization
    (``ArrayImpl.__array__``) lands as an instant."""
    if _enabled:
        recorder().instant("d2h.transfer")


def install_probes() -> None:
    """Wire the jax-level probes (backend compiles, D2H
    materializations) into the flight recorder — idempotent, shares
    the recompile sentinel's one-time ``jax.monitoring`` +
    ``__array__`` hook installation. Safe to call before jax is
    otherwise touched; costs nothing after the first call. A
    recorder disabled at process level (FLINK_TPU_FLIGHT_RECORDER=0)
    skips the installation entirely — opting out must not
    monkey-patch ``__array__`` (the sentinel still installs its own
    hooks when explicitly used)."""
    if not _enabled:
        return
    from flink_tpu.observe import recompile_sentinel as rs

    rs.add_compile_listener(_probe_compile)
    rs.add_transfer_listener(_probe_transfer)
    rs.install()
