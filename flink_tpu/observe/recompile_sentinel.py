"""Zero-recompile sentinel: assert the steady state stays compiled.

The framework's throughput rests on every per-batch step being a cache
hit on an already-compiled XLA program. A regression that varies a jit
cache key per step (a fresh lambda identity, an unpadded shape, a
cache key missing a device id) does not fail any correctness test — it
just recompiles every batch and quietly erases the pipelining wins.
This module counts *actual backend compiles* via :mod:`jax.monitoring`
(the ``/jax/core/compile/backend_compile_duration`` event fires once
per real XLA compilation, cache hits do not emit it) and
*device->host materializations* (every ``ArrayImpl.__array__``
invocation — the choke point ``jax.device_get`` and friends funnel
through), and exposes a context manager that raises when a guarded
region exceeds its budget::

    with RecompileSentinel(max_compiles=0, label="steady state") as s:
        for batch in stream:           # post-warmup reps
            engine.process_batch(batch)
    print(s.compiles, s.transfers)

Counting is process-global and installed once (jax.monitoring has no
listener deregistration); the sentinel reads deltas. The transfer
count is a *lower bound* on host reads: on the CPU backend NumPy can
consume jax arrays zero-copy through the buffer protocol without
calling ``__array__`` — on a real TPU every host materialization goes
through it. Budgets on transfers are therefore best-effort bounds,
while the compile count is exact on every backend.
"""

from __future__ import annotations

from typing import Callable, List, Optional

#: the monitoring event emitted once per real XLA backend compilation
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_probe_counts = {"compiles": 0, "transfers": 0}
_installed = False
#: subscriber fan-out: the hooks are installed ONCE process-wide
#: (jax.monitoring has no listener deregistration), so other consumers
#: of the same signals — the flight recorder correlates compiles and
#: D2H materializations into its span timeline — subscribe here
#: instead of double-wrapping __array__
_compile_listeners: List[Callable[[float], None]] = []
_transfer_listeners: List[Callable[[], None]] = []


class SteadyStateViolation(AssertionError):
    """A guarded region compiled or transferred past its budget."""


def add_compile_listener(cb: Callable[[float], None]) -> None:
    """Subscribe ``cb(duration_secs)`` to real XLA backend compiles
    (idempotent per callback)."""
    if cb not in _compile_listeners:
        _compile_listeners.append(cb)


def add_transfer_listener(cb: Callable[[], None]) -> None:
    """Subscribe ``cb()`` to device->host materializations (idempotent
    per callback; best-effort, see module docstring)."""
    if cb not in _transfer_listeners:
        _transfer_listeners.append(cb)


def _on_duration_event(name: str, secs: float, **kwargs) -> None:
    if name == _COMPILE_EVENT:
        _probe_counts["compiles"] += 1
        for cb in _compile_listeners:
            cb(secs)


def install() -> None:
    """Idempotent one-time hook installation (listener + __array__
    wrapper). Deferred so importing flink_tpu never forces jax init."""
    global _installed
    if _installed:
        return
    import jax

    jax.monitoring.register_event_duration_secs_listener(_on_duration_event)
    try:
        import jaxlib.xla_extension as _xe

        orig_array = _xe.ArrayImpl.__array__

        def _counting_array(self, *args, **kwargs):
            _probe_counts["transfers"] += 1
            for cb in _transfer_listeners:
                cb()
            return orig_array(self, *args, **kwargs)

        _xe.ArrayImpl.__array__ = _counting_array
    except (ImportError, AttributeError, TypeError):  # pragma: no cover
        # transfer counting is best-effort; compile counting (the exact
        # signal) installed above regardless
        pass
    _installed = True


#: original (pre-rename) spelling, kept for existing callers
_install = install


def compile_count() -> int:
    """Process-lifetime XLA backend compiles observed so far (0 until
    the first sentinel installs the hooks)."""
    return _probe_counts["compiles"]


def transfer_count() -> int:
    """Process-lifetime device->host materializations observed so far
    (lower bound; see module docstring)."""
    return _probe_counts["transfers"]


class RecompileSentinel:
    """Context manager asserting compile/transfer budgets over a region.

    ``max_compiles`` — hard budget of XLA backend compiles inside the
    region (0 = the steady-state contract); ``None`` disarms the check
    (observe-only). ``max_transfers`` — optional budget of D2H
    materializations. On exit past a budget the sentinel raises
    :class:`SteadyStateViolation` (unless the region is already
    unwinding another exception). Nesting is fine — each sentinel reads
    its own deltas of the shared process counters.
    """

    def __init__(self, max_compiles: Optional[int] = 0,
                 max_transfers: Optional[int] = None,
                 label: str = "") -> None:
        self.max_compiles = max_compiles
        self.max_transfers = max_transfers
        self.label = label
        self.compiles = 0
        self.transfers = 0
        self._c0 = 0
        self._t0 = 0

    def __enter__(self) -> "RecompileSentinel":
        _install()
        self._c0 = _probe_counts["compiles"]
        self._t0 = _probe_counts["transfers"]
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.compiles = _probe_counts["compiles"] - self._c0
        self.transfers = _probe_counts["transfers"] - self._t0
        if exc_type is not None:
            return False  # never mask the region's own failure
        tag = f" [{self.label}]" if self.label else ""
        if self.max_compiles is not None \
                and self.compiles > self.max_compiles:
            raise SteadyStateViolation(
                f"recompile sentinel{tag}: {self.compiles} XLA "
                f"compilation(s) in a region budgeted for "
                f"{self.max_compiles} — a jit identity or shape is "
                "varying per step (new lambda per call, unpadded "
                "bucket, cache key missing a device id?)")
        if self.max_transfers is not None \
                and self.transfers > self.max_transfers:
            raise SteadyStateViolation(
                f"recompile sentinel{tag}: {self.transfers} device->"
                f"host transfer(s) exceed the budget of "
                f"{self.max_transfers} — an unbatched host read crept "
                "onto the guarded path")
        return False
