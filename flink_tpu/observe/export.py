"""Flight-recorder exporters: Perfetto traces, Prometheus histograms,
event-time latency markers.

Three consumers of the one span plane (:mod:`flink_tpu.observe.
flight_recorder`), so the attribution the recorder captures is also
what every surface shows — the bench breakdowns, the dashboard and a
Perfetto timeline can never disagree about where the time went:

- :func:`chrome_trace` / :func:`write_chrome_trace` — the Chrome trace
  event JSON format (load the file at https://ui.perfetto.dev or
  chrome://tracing). One *pid* per job, one *tid* per shard (shard -1
  lands on the per-thread "host" track), durations as complete
  (``ph=X``) events, compiles/misses/injections as instants on the
  same clock.
- :func:`register_flight_metrics` — per-span-kind duration aggregates
  (count / total ms / p50 / p99) as gauges on a ``flight`` metric
  group, rendered by the existing PrometheusReporter.
- :class:`LatencyMarkerPlane` — the Flink LatencyMarker shape for the
  micro-batch design: each source batch is the marker (stamped with
  its ingest wall time), every operator it flows through records
  ``now - marker`` into a per-operator histogram, and per-operator
  watermark-lag gauges report how far each operator's event-time
  frontier trails the sources'.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from flink_tpu.observe.flight_recorder import FlightRecorder, SpanRecord

#: first tid of the per-thread host tracks (shard-less spans; shard
#: spans use tid = shard + 1, well below this)
HOST_TID_BASE = 1000


def _sanitize(kind: str) -> str:
    return kind.replace(".", "_")


def chrome_trace(records: List[SpanRecord],
                 anchor=None) -> Dict[str, Any]:
    """Encode decoded records as a Chrome trace event object
    (``{"traceEvents": [...]}``, ts/dur in microseconds). ``anchor`` —
    the recorder's ``(wall, perf)`` pair; when given, timestamps are
    wall-clock microseconds (Perfetto shows real times), else they are
    relative to the earliest record."""
    events: List[Dict[str, Any]] = []
    if anchor is not None:
        wall0, perf0 = anchor
        base = perf0 - wall0  # t_us = (t - base) * 1e6
    else:
        base = min((r.t0 for r in records), default=0.0)
    jobs: Dict[Optional[str], int] = {}
    host_tids: Dict[str, int] = {}
    seen_tids = {}
    for r in records:
        pid = jobs.setdefault(r.job, len(jobs) + 1)
        if r.shard >= 0:
            tid = r.shard + 1
            seen_tids[(pid, tid)] = f"shard-{r.shard}"
        else:
            # shard-less spans get one HOST track PER THREAD: two
            # concurrent threads (task loop vs a serving client) must
            # not interleave complete events on one track — Perfetto
            # would render bogus nesting
            tid = host_tids.setdefault(
                r.thread, HOST_TID_BASE + len(host_tids))
            seen_tids[(pid, tid)] = f"host:{r.thread}"
        args: Dict[str, Any] = {"batch": r.batch_id, "thread": r.thread}
        if r.watermark is not None:
            args["watermark"] = r.watermark
        if r.shard >= 0:
            args["shard"] = r.shard
        ev: Dict[str, Any] = {
            "name": r.kind,
            "cat": r.kind.split(".", 1)[0],
            "pid": pid,
            "tid": tid,
            "ts": round((r.t0 - base) * 1e6, 3),
            "args": args,
        }
        if r.instant:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant marker
        else:
            ev["ph"] = "X"
            ev["dur"] = round((r.t1 - r.t0) * 1e6, 3)
        events.append(ev)
    for job, pid in jobs.items():
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": job or "(unattributed)"}})
    for (pid, tid), name in sorted(seen_tids.items()):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": name}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       rec: Optional[FlightRecorder] = None) -> int:
    """Dump the recorder's current rings as a Perfetto-loadable JSON
    file; returns the number of events written."""
    from flink_tpu.observe.flight_recorder import recorder

    rec = rec or recorder()
    trace = chrome_trace(rec.snapshot(), anchor=rec.anchor)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])


def validate_trace_schema(trace: Dict[str, Any],
                          known_kinds) -> List[str]:
    """Schema check the trace smoke gates on: every duration/instant
    event's name is a registered span kind, batch-lifecycle events
    carry batch attribution, and fire events carry watermark
    attribution. Returns a list of violations (empty = valid)."""
    known = set(known_kinds)
    problems: List[str] = []
    for ev in trace.get("traceEvents", []):
        ph = ev.get("ph")
        if ph == "M":
            continue
        name = ev.get("name")
        if name not in known:
            problems.append(f"unregistered span kind {name!r}")
            continue
        args = ev.get("args", {})
        if name == "batch.ingest" and args.get("batch", -1) < 0:
            problems.append("batch.ingest without batch attribution")
        if name == "fire.dispatch" and "watermark" not in args:
            problems.append("fire.dispatch without watermark")
        if ph == "X" and ev.get("dur", 0) < 0:
            problems.append(f"negative duration on {name!r}")
    return problems


def span_rollup(kind_totals: Dict[str, Dict[str, float]],
                wall_s: float,
                buckets: Dict[str, Any]) -> Dict[str, float]:
    """Sum per-kind span totals into named wall-time buckets — THE
    bench drivers' rollup primitive. ``buckets`` maps an output field
    to one span kind or a sequence of kinds; absent kinds contribute
    0.0 (a driver may name kinds its engine doesn't emit yet). Always
    appends ``total_s`` (the measured wall clock) so every driver's
    breakdown dict carries the same denominator. Buckets may overlap
    (a kind can appear in several) and are not guaranteed to sum to
    ``total_s`` — they attribute, they don't partition."""

    def total(kind: str) -> float:
        return kind_totals.get(kind, {}).get("total_s", 0.0)

    out: Dict[str, float] = {}
    for name, kinds in buckets.items():
        if isinstance(kinds, str):
            kinds = (kinds,)
        out[name] = round(sum(total(k) for k in kinds), 3)
    out["total_s"] = round(wall_s, 3)
    return out


def breakdown_from_kind_totals(kind_totals: Dict[str, Dict[str, float]],
                               wall_s: float) -> Dict[str, float]:
    """The canonical host-prep / device / harvest wall-time breakdown,
    derived from flight-recorder span aggregates — the bench drivers
    report THIS dict, so their gates and a captured trace read the same
    numbers from the same spans by construction.

    ``host_prep_s`` approximates genuine host work on the ingest path:
    ``batch.ingest`` total minus ALL inline device interactions
    (``device.dispatch``) and fence blocks (``device.fence_wait``).
    The subtraction uses the process totals, and some device spans
    open on the FIRE path (cold-page reloads, eviction gathers), so
    host prep can be slightly UNDER-stated at spill-heavy shapes —
    the same approximation the pre-recorder engine counters
    (``device_inline_s`` accumulated on both paths, subtracted from
    an ingest-only timer) made, so gate budgets calibrated against
    them carry over unchanged. ``device_step_s`` is the device spans
    plus the fire dispatches; ``harvest_s`` is ALL D2H
    materializations (``fire.harvest``), including ones nested inside
    device interactions or synchronous fires — buckets may overlap
    and are not guaranteed to sum to ``total_s``."""

    def total(kind: str) -> float:
        return kind_totals.get(kind, {}).get("total_s", 0.0)

    host_prep = max(total("batch.ingest") - total("device.dispatch")
                    - total("device.fence_wait"), 0.0)
    out = {"host_prep_s": round(host_prep, 3)}
    out.update(span_rollup(kind_totals, wall_s, {
        "meta_sweep_s": "prep.meta_sweep",
        "stage_s": "prep.stage",
        "device_step_s": ("fire.dispatch", "device.dispatch",
                          "device.fence_wait"),
        "harvest_s": "fire.harvest",
        "device_in_prep_s": ("device.dispatch", "device.fence_wait"),
    }))
    out["host_prep_fraction"] = round(host_prep / wall_s, 4) \
        if wall_s > 0 else 0.0
    return out


def register_flight_metrics(group,
                            rec: Optional[FlightRecorder] = None):
    """Per-span-kind duration aggregates as gauges under
    ``<scope>.flight`` (count / total_ms / p50_ms / p99_ms per kind,
    names Prometheus-safe). Suppliers read the recorder's merged
    per-thread aggregates at scrape time — nothing is added to the
    hot path, and ``kind_totals`` is memoized so a scrape of all the
    gauges pays one merge. The aggregates are PROCESS-GLOBAL (the
    recorder is shared by every job in the process): register them at
    a registry root or cluster scope, not under one job's — per-job
    attribution lives on the records themselves (trace export), not
    in these rollups."""
    from flink_tpu.observe.flight_recorder import recorder

    rec = rec or recorder()
    fg = group.add_group("flight")

    def _stat(kind: str, field: str):
        def read() -> float:
            return rec.kind_totals().get(kind, {}).get(field, 0.0)

        return read

    for kind in rec.kinds:
        base = _sanitize(kind)
        fg.gauge(f"{base}_count", _stat(kind, "count"))
        fg.gauge(f"{base}_total_s", _stat(kind, "total_s"))
        fg.gauge(f"{base}_p50_ms", _stat(kind, "p50_ms"))
        fg.gauge(f"{base}_p99_ms", _stat(kind, "p99_ms"))
    fg.gauge("records_dropped", lambda: rec.dropped())
    return fg


class LatencyMarkerPlane:
    """Per-operator event-time latency markers (the Flink LatencyMarker
    shape, re-designed for micro-batches).

    The reference injects LatencyMarker records at sources (stamped
    with wall time) and each operator reports ``now - marker`` — here
    the *source batch* is the marker: :meth:`stamp_source` notes the
    wall instant a batch left its source, and :meth:`observe` (called
    by the executor after each operator's hooks ran on the depth-first
    push of that batch) records the elapsed wall time into the
    operator's ``markerLatencyMs`` histogram. Watermark lag is the
    event-time counterpart: per operator, how far its combined input
    watermark trails the sources' frontier (held-back watermarks from
    in-flight async fires surface here first)."""

    def __init__(self) -> None:
        self._hists: Dict[str, Any] = {}
        self._marker_t0 = 0.0
        #: a marker is LIVE only during the depth-first push of the
        #: source batch that stamped it — operator work that runs
        #: outside it (async-fire drains, the end-of-source flush,
        #: restored-window fires) carries no marker and records no
        #: sample, instead of charging the drain interval to the last
        #: batch (or perf_counter's whole epoch on a restore-only run)
        self._marker_live = False
        #: per-source emitted watermarks; the job frontier is their
        #: MIN — operators combine inputs with min (WatermarkValve),
        #: so a max here would report steady inter-source skew as
        #: permanent operator lag
        self._source_wms: Dict[Any, int] = {}

    def operator_group(self, group, name: str, input_watermark_fn):
        """Register one operator's latency surface under
        ``<scope>.latency``: the marker histogram + the watermark-lag
        gauge. Returns the histogram (the executor holds it)."""
        lg = group.add_group("latency")
        hist = lg.histogram("markerLatencyMs", reservoir_size=2048)
        self._hists[name] = hist

        def lag() -> float:
            src = self.source_watermark
            wm = input_watermark_fn()
            if src is None or wm is None or wm < -(1 << 60):
                # the operator has not seen a watermark yet (valve at
                # its negative sentinel) — no meaningful lag to report
                return 0.0
            return float(max(src - wm, 0))

        lg.gauge("watermarkLagMs", lag)
        return hist

    def stamp_source(self) -> None:
        """A source batch enters the dataflow NOW — it is the marker."""
        self._marker_t0 = time.perf_counter()
        self._marker_live = True

    def end_marker(self) -> None:
        """The stamped batch's synchronous push finished — work after
        this point (drains, flushes) is not that batch's latency."""
        self._marker_live = False

    def note_source_watermark(self, wm: int, source=None) -> None:
        prev = self._source_wms.get(source)
        if prev is None or wm > prev:
            self._source_wms[source] = int(wm)

    @property
    def source_watermark(self) -> Optional[int]:
        """The sources' combined frontier: MIN over every source that
        has emitted a watermark (matching the valves' min-combine)."""
        return min(self._source_wms.values()) \
            if self._source_wms else None

    def observe(self, hist) -> None:
        """One operator finished its hooks for the marked batch (no-op
        when no marker is live)."""
        if self._marker_live:
            hist.update((time.perf_counter() - self._marker_t0) * 1e3)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        return {name: h.snapshot() for name, h in self._hists.items()}
