"""Runtime observability probes for the compiled hot path.

Static analysis (tools/flint) proves the *source* cannot host-sync or
destabilize jit identities; this package proves the *running program*
behaves: :mod:`~flink_tpu.observe.recompile_sentinel` counts actual XLA
backend compiles and device->host materializations around an engine
run and turns "the steady state recompiles" into an exception instead
of a silent 2-5x throughput loss, and
:mod:`~flink_tpu.observe.flight_recorder` is the always-on span plane
the whole batch lifecycle reports into (exported to Perfetto/Chrome
traces, Prometheus histograms and event-time latency markers by
:mod:`~flink_tpu.observe.export`).
"""

#: Canonical span-kind inventory — THE single source of truth shared by
#: the flight recorder (an unregistered kind raises at the call site),
#: the exporters (category mapping derives from this tuple) and flint's
#: REG03 registry check (tools/flint). Adding an instrumentation point
#: means adding its kind here; a typo in either direction — a call site
#: not listed, or a listed kind with no call site — fails both gates.
#: Keep this a plain literal tuple: flint parses it statically.
KNOWN_SPAN_KINDS = (
    # per-batch lifecycle (the engines' ingest -> emit pipeline)
    "batch.ingest",        # one engine process_batch (host prep + dispatch)
    "prep.meta_sweep",     # session-metadata absorb (native C or Python)
    "prep.stage",          # shuffle staging / bucketing into [P, B] blocks
    "device.dispatch",     # inline device interactions on the ingest path
    "device.fence_wait",   # host blocked on dispatch-ahead fences
    "exchange.stage1",     # two-level exchange: intra-host (ICI) route
    "exchange.stage2",     # two-level exchange: cross-host (DCN) hop +
                           # the stream-order scatter
    "fire.dispatch",       # watermark advance -> fire programs enqueued
    "fire.shard",          # one shard's fire-path host work (resolve,
                           # cold page extraction) — the per-shard track
    "fire.harvest",        # D2H materialization of fire/query results
    "op.process",          # executor: one operator's process_batch
    "op.watermark",        # executor: one operator's process_watermark
    "emit",                # executor: one output left its operator
                           # (instant — durations belong to op.process)
    # control plane
    "checkpoint.write",
    "checkpoint.restore",
    "failover.replay",     # partial-failover bounded replay of one range
    "reshard.handoff",     # live key-group migration between mesh sizes
    "serving.lookup",      # one coalesced queryable-state flush
    "serving.replica_publish",  # boundary publish of the read replica
                           # (batch field carries the sealed generation)
    "serving.cache_hit",   # hot-row cache served a lookup batch without
                           # touching the device (instant; batch field
                           # carries the generation the hits were tagged)
    # instants correlated into the same timeline
    "xla.compile",         # real XLA backend compile (jax.monitoring)
    "d2h.transfer",        # device->host materialization (__array__)
    "watchdog.miss",       # a deadline-tracked section ran past budget
    "chaos.inject",        # an armed fault plan fired at a fault point
)

from flink_tpu.observe.recompile_sentinel import (  # noqa: E402,F401
    RecompileSentinel,
    SteadyStateViolation,
    compile_count,
    transfer_count,
)
from flink_tpu.observe.flight_recorder import (  # noqa: E402,F401
    FlightRecorder,
    SpanRecord,
    install_probes,
    recorder,
)
from flink_tpu.observe.lock_sentinel import (  # noqa: E402,F401
    LockOrderViolation,
    LockSentinel,
    NamedLock,
    named_lock,
)
