"""Runtime observability probes for the compiled hot path.

Static analysis (tools/flint) proves the *source* cannot host-sync or
destabilize jit identities; this package proves the *running program*
behaves: :mod:`~flink_tpu.observe.recompile_sentinel` counts actual XLA
backend compiles and device->host materializations around an engine
run and turns "the steady state recompiles" into an exception instead
of a silent 2-5x throughput loss.
"""

from flink_tpu.observe.recompile_sentinel import (  # noqa: F401
    RecompileSentinel,
    SteadyStateViolation,
    compile_count,
    transfer_count,
)
