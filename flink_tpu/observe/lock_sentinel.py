"""Named locks and a runtime lock-order sentinel.

The static side of the concurrency contract lives in
``tools/flint/rules_conc.py`` (LCK01..LCK03, SHM01). This module is the
runtime complement: the package's hot classes create their locks
through :func:`named_lock`, and a :class:`LockSentinel` — installed
only by tests and ``tools/lock_smoke.py`` — observes every acquisition
through those wrappers:

- the **acquisition-order graph** (edge ``A -> B`` whenever a thread
  acquires B while holding A), with a first-witness site per edge;
  an observed cycle raises :class:`LockOrderViolation` in the
  acquiring thread AND is recorded, so a cycle in a daemon thread
  still fails the smoke's final :meth:`LockSentinel.check`;
- per-lock **hold and contention** accounting (acquisitions, contended
  acquires, total wait, total/max hold) that the smoke gates on — a
  lock held across a slow path shows up as a hold-time budget failure
  before it shows up as tail latency.

With no sentinel installed a named lock is one attribute load away
from the bare ``threading`` primitive — the wrapper checks one module
global per acquire — so production paths pay (almost) nothing.

Locks are aggregated BY NAME: every ``LookupCoalescer`` instance's
lock is one ``serving.coalescer`` node. Two *different* objects with
the same name acquired nested therefore record a ``name -> name``
self-edge and trip the cycle check — deliberate: instances of one
class locked in no defined order are exactly the ABBA hazard the
"locks staggered, never nested" discipline exists to prevent.
Reentrant re-acquisition of the SAME object never records an edge.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockOrderViolation",
    "LockSentinel",
    "NamedLock",
    "named_lock",
    "current_sentinel",
]


class LockOrderViolation(RuntimeError):
    """Two lock names were observed acquired in both orders."""


#: the one active sentinel (None in production — the fast path)
_SENTINEL: Optional["LockSentinel"] = None


def current_sentinel() -> Optional["LockSentinel"]:
    return _SENTINEL


def _site(depth: int = 2) -> str:
    """caller file:line, best effort (witness strings only)."""
    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except Exception:
        return "?"


class NamedLock:
    """A ``threading.Lock``/``RLock`` with a stable name, observable by
    the installed :class:`LockSentinel`. Context-manager protocol plus
    ``acquire(blocking, timeout)``/``release``/``locked`` — a drop-in
    for the bare primitive at the call sites the package uses."""

    __slots__ = ("name", "reentrant", "_inner")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = _SENTINEL
        if s is None:
            return self._inner.acquire(blocking, timeout)
        return s._acquire(self, blocking, timeout, _site())

    def release(self) -> None:
        s = _SENTINEL
        if s is None:
            self._inner.release()
            return
        s._release(self)

    def locked(self) -> bool:
        # RLock has no .locked() before 3.12; its _is_owned covers the
        # calling thread (a non-blocking probe would reentrantly
        # succeed and report False while held), and a failed probe
        # covers other threads' holds
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        if hasattr(inner, "_is_owned") and inner._is_owned():
            return True
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        s = _SENTINEL
        if s is None:
            return self._inner.acquire()
        return s._acquire(self, True, -1, _site())

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self.reentrant else "Lock"
        return f"NamedLock({self.name!r}, {kind})"


def named_lock(name: str, reentrant: bool = False) -> NamedLock:
    """The factory the hot classes use instead of ``threading.Lock()``.

    Always returns the wrapper (not conditionally the bare primitive):
    module-scope locks are created at import time, long before any
    sentinel exists, and must still become observable when one is
    installed later.
    """
    return NamedLock(name, reentrant=reentrant)


class _LockStats:
    __slots__ = ("acquisitions", "contended", "wait_s", "hold_s",
                 "hold_max_s")

    def __init__(self):
        self.acquisitions = 0
        self.contended = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.hold_max_s = 0.0


class _Held:
    __slots__ = ("lock", "depth", "t0", "site")

    def __init__(self, lock: NamedLock, t0: float, site: str):
        self.lock = lock
        self.depth = 1
        self.t0 = t0
        self.site = site


class LockSentinel:
    """Observes every :class:`NamedLock` while installed.

    Use as a context manager (``with LockSentinel() as s: ...``) or via
    :meth:`install`/:meth:`uninstall`. :meth:`check` raises on any
    recorded order cycle; :meth:`report` returns the full accounting.
    """

    def __init__(self):
        self._mu = threading.Lock()       # guards graph + stats (leaf)
        self._tls = threading.local()     # per-thread held-lock stack
        self.stats: Dict[str, _LockStats] = {}
        #: name -> {successor name}
        self.edges: Dict[str, set] = {}
        #: (a, b) -> first-witness string
        self.witness: Dict[Tuple[str, str], str] = {}
        #: recorded cycles: (path tuple, human message)
        self.cycles: List[Tuple[Tuple[str, ...], str]] = []

    # ------------------------------------------------------------ install

    def install(self) -> "LockSentinel":
        global _SENTINEL
        if _SENTINEL is not None and _SENTINEL is not self:
            raise RuntimeError("another LockSentinel is already installed")
        _SENTINEL = self
        return self

    def uninstall(self) -> None:
        global _SENTINEL
        if _SENTINEL is self:
            _SENTINEL = None

    def __enter__(self) -> "LockSentinel":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------- observe

    def _stack(self) -> List[_Held]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _acquire(self, lock: NamedLock, blocking: bool, timeout: float,
                 site: str) -> bool:
        stack = self._stack()
        for h in stack:
            if h.lock is lock:       # reentrant re-acquire: no edge,
                ok = lock._inner.acquire(blocking, timeout)  # no wait
                if ok:
                    h.depth += 1
                return ok
        held = [(h.lock.name, h.site) for h in stack]
        if held:
            self._note_edges(held, lock.name, site)
        # contention probe: a failed non-blocking try IS contention
        t0 = time.monotonic()
        ok = lock._inner.acquire(False)
        contended = not ok
        if not ok:
            if not blocking:
                with self._mu:
                    st = self.stats.setdefault(lock.name, _LockStats())
                    st.contended += 1
                return False
            ok = lock._inner.acquire(True, timeout)
        wait = time.monotonic() - t0
        if not ok:
            with self._mu:
                st = self.stats.setdefault(lock.name, _LockStats())
                st.contended += 1
                st.wait_s += wait
            return False
        stack.append(_Held(lock, time.monotonic(), site))
        with self._mu:
            st = self.stats.setdefault(lock.name, _LockStats())
            st.acquisitions += 1
            if contended:
                st.contended += 1
                st.wait_s += wait
        return True

    def _release(self, lock: NamedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            h = stack[i]
            if h.lock is lock:
                h.depth -= 1
                if h.depth == 0:
                    hold = time.monotonic() - h.t0
                    del stack[i]
                    with self._mu:
                        st = self.stats.setdefault(lock.name, _LockStats())
                        st.hold_s += hold
                        st.hold_max_s = max(st.hold_max_s, hold)
                lock._inner.release()
                return
        # not tracked (acquired before install): release pass-through
        lock._inner.release()

    def _note_edges(self, held: List[Tuple[str, str]], dst: str,
                    dst_site: str) -> None:
        cycle_msg = None
        with self._mu:
            for src, src_site in held:
                if src == dst:
                    # same NAME, different object (same object returned
                    # above): undefined intra-name order — a cycle
                    path = (src, dst)
                    msg = (f"lock order cycle: {src} (held at "
                           f"{src_site}) -> {dst} (acquiring at "
                           f"{dst_site}): two instances named "
                           f"{dst!r} nested")
                    self.cycles.append((path, msg))
                    cycle_msg = msg
                    continue
                fresh = dst not in self.edges.get(src, ())
                self.edges.setdefault(src, set()).add(dst)
                self.witness.setdefault(
                    (src, dst),
                    f"{src}@{src_site} -> {dst}@{dst_site} "
                    f"[{threading.current_thread().name}]")
                if fresh:
                    back = self._find_path(dst, src)
                    if back is not None:
                        path = (src,) + tuple(back)
                        msg = self._cycle_message(path)
                        self.cycles.append((path, msg))
                        cycle_msg = msg
        if cycle_msg is not None:
            raise LockOrderViolation(cycle_msg)

    def _find_path(self, a: str, b: str) -> Optional[List[str]]:
        """A path a..b in the edge graph (caller holds _mu)."""
        seen = {a}
        frontier = [[a]]
        while frontier:
            path = frontier.pop()
            last = path[-1]
            if last == b:
                return path
            for nxt in self.edges.get(last, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None

    def _cycle_message(self, path: Tuple[str, ...]) -> str:
        legs = []
        for a, b in zip(path, path[1:]):
            legs.append(self.witness.get((a, b), f"{a} -> {b}"))
        legs.append(self.witness.get((path[-1], path[0]),
                                     f"{path[-1]} -> {path[0]}"))
        return ("lock order cycle: " + " / ".join(legs))

    # -------------------------------------------------------------- report

    def contended_locks(self) -> List[str]:
        with self._mu:
            return sorted(n for n, st in self.stats.items()
                          if st.contended > 0)

    def check(self, hold_budget_s: Optional[float] = None) -> None:
        """Raise :class:`LockOrderViolation` on any recorded cycle;
        with ``hold_budget_s``, also raise when any single hold
        exceeded the budget."""
        with self._mu:
            if self.cycles:
                raise LockOrderViolation(self.cycles[0][1])
            if hold_budget_s is not None:
                over = [(n, st.hold_max_s) for n, st in self.stats.items()
                        if st.hold_max_s > hold_budget_s]
                if over:
                    worst = max(over, key=lambda p: p[1])
                    raise LockOrderViolation(
                        f"lock hold budget {hold_budget_s:.3f}s exceeded: "
                        f"{worst[0]} held {worst[1]:.3f}s "
                        f"(all over-budget: {sorted(over)})")

    def report(self) -> Dict[str, object]:
        with self._mu:
            return {
                "locks": {
                    n: {"acquisitions": st.acquisitions,
                        "contended": st.contended,
                        "wait_s": round(st.wait_s, 6),
                        "hold_s": round(st.hold_s, 6),
                        "hold_max_s": round(st.hold_max_s, 6)}
                    for n, st in sorted(self.stats.items())},
                "edges": sorted(
                    [a, b, self.witness.get((a, b), "")]
                    for a, dsts in self.edges.items() for b in dsts),
                "cycles": [{"path": list(p), "message": m}
                           for p, m in self.cycles],
            }
