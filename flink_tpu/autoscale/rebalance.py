"""Load-driven key-group rebalancing — the *rebalance* and *split*
stages of the skew ladder (detect -> rebalance -> split).

The scaling policy's skew guard refuses to act on imbalance (a hot
shard is not spare capacity), and changing the shard COUNT cannot fix
skew at all: the contiguous key-group formula re-concentrates the same
hot groups on whatever shard inherits them. The fix is to change the
*assignment*:

- :class:`RebalancePolicy` scores a proposed move set against the
  :class:`~flink_tpu.parallel.load.ShardLoadAccountant`'s per-group
  load estimates — greedy hottest-group-to-coldest-shard with
  hysteresis (a move must improve imbalance by a real margin) and a
  cooldown (handoffs are cheap, not free);
- when one KEY dominates its group, no group move can help (a group is
  the atomic routing unit) — the policy flags it as a split candidate
  instead;
- :class:`SkewResponder` glues both to a live mesh engine: hang its
  :meth:`~SkewResponder.maybe_respond` off
  ``AutoscaleController(on_imbalance=...)`` (or call it from the task
  loop) and imbalance turns into ``engine.reassign_key_groups(...)``
  moves and ``engine.register_hot_key(...)`` splits instead of a
  refusal counter ticking up.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from flink_tpu.parallel.load import ShardLoadAccountant
from flink_tpu.state.keygroups import KeyGroupAssignment

__all__ = ["RebalancePlan", "RebalancePolicy", "SkewResponder"]


@dataclasses.dataclass
class RebalancePlan:
    """What the policy wants done; ``assignment`` is None when no move
    set clears the hysteresis bar."""

    assignment: Optional[KeyGroupAssignment]
    moves: List[Tuple[int, int, int]]  # (global group, src, dst)
    imbalance_before: float
    imbalance_after: float
    #: keys whose single-key load dominates their group — moving the
    #: group cannot help; split these instead
    split_candidates: List[int]
    reason: str


class RebalancePolicy:
    """Greedy move planner over per-group load estimates.

    - **imbalance_trigger**: plan only while measured imbalance
      (max-shard-load * P / total) exceeds this.
    - **hysteresis**: a plan must cut imbalance by at least this
      relative margin (plan.after <= before * (1 - hysteresis)) or it
      is discarded — churn guard, same role as the scaling policy's
      band.
    - **cooldown_s**: minimum time between applied plans; call
      :meth:`mark_rebalanced` after actually applying one.
    - **max_moves**: cap on groups moved per plan (each moved group is
      handoff traffic at the batch boundary).
    - **dominance_share**: a key carrying more than this fraction of
      its group's load makes the group unsplittable by moves — the key
      is reported as a split candidate instead.
    """

    def __init__(self, imbalance_trigger: float = 1.5,
                 hysteresis: float = 0.1, cooldown_s: float = 10.0,
                 max_moves: int = 8, dominance_share: float = 0.5,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if imbalance_trigger < 1.0:
            raise ValueError(
                f"imbalance_trigger must be >= 1.0, got "
                f"{imbalance_trigger}")
        self.imbalance_trigger = float(imbalance_trigger)
        self.hysteresis = max(float(hysteresis), 0.0)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.max_moves = max(int(max_moves), 1)
        self.dominance_share = float(dominance_share)
        self._clock = clock if clock is not None else time.monotonic
        self._last_rebalance: Optional[float] = None

    def in_cooldown(self, now: Optional[float] = None) -> bool:
        if self._last_rebalance is None:
            return False
        now = self._clock() if now is None else now
        return (now - self._last_rebalance) < self.cooldown_s

    def mark_rebalanced(self, now: Optional[float] = None) -> None:
        self._last_rebalance = self._clock() if now is None else now

    def plan(self, accountant: ShardLoadAccountant,
             current: KeyGroupAssignment,
             now: Optional[float] = None) -> RebalancePlan:
        """Propose a better assignment (or None). Pure scoring — the
        caller applies ``plan.assignment`` via
        ``engine.reassign_key_groups`` and then calls
        :meth:`mark_rebalanced`."""
        now = self._clock() if now is None else now
        before = accountant.imbalance(current)
        split = [k for k, _g, share in accountant.hot_key_candidates()
                 if share >= self.dominance_share]
        if before <= self.imbalance_trigger:
            return RebalancePlan(None, [], before, before, split,
                                 "balanced")
        if self.in_cooldown(now):
            return RebalancePlan(None, [], before, before, split,
                                 "cooldown")
        loads = accountant.group_load()
        table = current.table.copy()
        P = current.num_shards
        shard_load = np.bincount(table, weights=loads, minlength=P)
        total = float(shard_load.sum())
        if total <= 0.0:
            return RebalancePlan(None, [], before, before, split,
                                 "no-signal")
        moves: List[Tuple[int, int, int]] = []
        # greedy: repeatedly move the hottest shard's hottest movable
        # group to the coldest shard, while each move improves the max
        for _ in range(self.max_moves):
            src = int(np.argmax(shard_load))
            dst = int(np.argmin(shard_load))
            if src == dst:
                break
            local = np.nonzero(table == src)[0]
            if len(local) <= 1:
                break  # a one-group shard is skew moves cannot fix
            cand = local[np.argsort(-loads[local])]
            applied = False
            for g in cand.tolist():
                w = float(loads[g])
                if w <= 0.0:
                    break  # remaining candidates are colder still
                # only move if it lowers the CURRENT max (src load);
                # never just swap the hot spot onto dst
                if shard_load[dst] + w >= shard_load[src]:
                    continue
                table[g] = dst
                shard_load[src] -= w
                shard_load[dst] += w
                moves.append((int(g) + current.first, src, dst))
                applied = True
                break
            if not applied:
                break
        if not moves:
            return RebalancePlan(None, [], before, before, split,
                                 "no-improving-move")
        proposed = KeyGroupAssignment(current.first, P, table)
        after = accountant.imbalance(proposed)
        if after > before * (1.0 - self.hysteresis):
            return RebalancePlan(None, moves, before, after, split,
                                 "hysteresis")
        return RebalancePlan(proposed, moves, before, after, split,
                             "rebalance")


class SkewResponder:
    """Wires detect -> rebalance -> split onto one live mesh engine.

    Feed it routed key columns (:meth:`note_batch`, cheap) and call
    :meth:`maybe_respond` at batch boundaries — or pass
    ``responder.on_imbalance`` as the
    :class:`~flink_tpu.autoscale.controller.AutoscaleController`'s
    ``on_imbalance`` hook so the skew guard's refusal drives it. It
    ticks the accountant, asks the policy for a plan, applies group
    moves via ``engine.reassign_key_groups`` and splits dominant keys
    via ``engine.register_hot_key``.

    ``salts``/``hot_key_share``/``allow_inexact`` parameterize the
    split stage; ``max_hot_keys`` bounds how many keys may be split at
    once (each costs fold work at every fire).
    """

    def __init__(self, engine, accountant: ShardLoadAccountant,
                 policy: Optional[RebalancePolicy] = None,
                 salts: int = 8, hot_key_share: float = 0.5,
                 allow_inexact: bool = False,
                 max_hot_keys: int = 4) -> None:
        if not hasattr(engine, "reassign_key_groups"):
            raise TypeError(
                f"{type(engine).__name__} has no reassign_key_groups() "
                "— the responder needs a live mesh engine")
        self.engine = engine
        self.accountant = accountant
        self.policy = policy if policy is not None else RebalancePolicy()
        self.policy.dominance_share = float(hot_key_share)
        self.salts = int(salts)
        self.allow_inexact = bool(allow_inexact)
        self.max_hot_keys = int(max_hot_keys)
        self.rebalances = 0
        self.groups_moved = 0
        self.keys_split = 0
        self.last_plan: Optional[RebalancePlan] = None

    # ------------------------------------------------------------ feed

    def note_batch(self, key_ids) -> None:
        self.accountant.note_batch(key_ids)

    def on_imbalance(self, _policy_input) -> None:
        """AutoscaleController ``on_imbalance`` adapter (the sampled
        PolicyInput is redundant — the accountant holds finer state)."""
        self.maybe_respond()

    # ------------------------------------------------------------ act

    def maybe_respond(self, now: Optional[float] = None) -> Optional[dict]:
        """Tick, plan, apply. Returns the engine's handoff report when
        a rebalance ran (None otherwise). Split registration happens
        independently of group moves — a dominant key needs splitting
        even when no move clears the bar."""
        self.accountant.tick(
            shard_resident_rows=self.engine.shard_resident_rows())
        plan = self.policy.plan(self.accountant,
                                self.engine.key_group_assignment,
                                now=now)
        self.last_plan = plan
        can_split = getattr(self.engine, "register_hot_key", None)
        if can_split is not None and plan.imbalance_before \
                > self.policy.imbalance_trigger:
            already = getattr(self.engine, "_hot_keys", {})
            for key in plan.split_candidates:
                if len(already) >= self.max_hot_keys:
                    break
                if key not in already:
                    can_split(key, salts=self.salts,
                              allow_inexact=self.allow_inexact)
                    self.keys_split += 1
        if plan.assignment is None:
            return None
        report = self.engine.reassign_key_groups(plan.assignment)
        self.policy.mark_rebalanced(now)
        self.rebalances += 1
        self.groups_moved += int(report.get("groups_moved", 0))
        return report
