"""Autoscale controller: drive policy decisions into a rescale path.

Two execution paths, one decision loop:

- **live** — a mesh engine (``MeshWindowEngine`` / ``MeshSessionEngine``)
  migrates its key groups in place via ``engine.reshard(target)``: no
  stop-and-redeploy, no checkpoint round-trip, handoff measured in the
  tens of milliseconds (BENCHMARKS.md "rescale handoff" row).
- **cold** — a minicluster job redeploys at the new parallelism from its
  latest checkpoint via ``JobMaster.request_rescale(target)`` (the
  reactive-rescale path, reference: AdaptiveScheduler Executing ->
  Restarting on resource change + key-group-range filtered restore).

The controller differentiates cumulative signal samples into the rates
the :class:`~flink_tpu.autoscale.policy.ScalingPolicy` consumes, applies
decisions, starts the policy cooldown, and surfaces everything through
an ``autoscale`` metric group.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

from flink_tpu.autoscale.policy import Decision, PolicyInput, ScalingPolicy


@dataclasses.dataclass
class SignalSample:
    """Raw CUMULATIVE counters + instantaneous gauges; the controller
    differentiates successive samples into rates."""

    records_total: float = 0.0
    busy_ms_total: float = 0.0
    backlog: float = 0.0
    shard_resident_rows: Sequence[int] = ()
    #: recent window-fire p99 (ms) — instantaneous like backlog, passed
    #: through to the policy's fire-latency signal (0 = no fires yet)
    fire_latency_p99_ms: float = 0.0


@dataclasses.dataclass
class RescaleEvent:
    at: float
    source: int
    target: int
    reason: str
    mode: str  # "live" | "cold"
    handoff_s: float = 0.0
    rows_moved: int = 0


class AutoscaleController:
    """One controller per elastic operator (or per job on the cold path).

    ``sample_fn`` returns a :class:`SignalSample`;
    ``current_shards_fn`` reads the operator's live shard count;
    exactly one of ``engine`` / ``job`` / ``apply_fn`` provides the
    rescale mechanism. ``clock`` is injectable for deterministic tests
    and shared with the policy's cooldown tracking.
    """

    def __init__(
        self,
        policy: ScalingPolicy,
        sample_fn: Callable[[], SignalSample],
        engine=None,
        job=None,
        apply_fn: Optional[Callable[[int], Optional[dict]]] = None,
        current_shards_fn: Optional[Callable[[], int]] = None,
        interval_s: float = 1.0,
        clock=None,
        metrics_group=None,
        on_imbalance: Optional[Callable[[PolicyInput], None]] = None,
    ) -> None:
        import time as _time

        mechanisms = sum(x is not None for x in (engine, job, apply_fn))
        if mechanisms != 1:
            raise ValueError(
                "exactly one of engine / job / apply_fn must be given "
                f"(got {mechanisms})")
        if engine is not None and not hasattr(engine, "reshard"):
            raise TypeError(
                f"{type(engine).__name__} has no reshard() — the live "
                "path needs a mesh engine; use job= for the "
                "checkpoint-redeploy path")
        self.policy = policy
        self.sample_fn = sample_fn
        self.engine = engine
        self.job = job
        self.apply_fn = apply_fn
        self._shards_fn = current_shards_fn
        self.interval_s = max(float(interval_s), 0.0)
        self._clock = clock or _time.monotonic
        self.events: List[RescaleEvent] = []
        self.last_decision: Optional[Decision] = None
        self._last_sample: Optional[SignalSample] = None
        self._last_sample_t: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._handoff_hist = None
        #: called (with the PolicyInput) whenever the skew guard refuses
        #: a scale-down — the hand-off hook a rebalancer (e.g.
        #: autoscale.rebalance.SkewResponder) hangs off so "imbalance"
        #: triggers a key-group MOVE instead of merely holding P
        self.on_imbalance = on_imbalance
        if metrics_group is not None:
            self.register_metrics(metrics_group)

    # --------------------------------------------------------------- metrics

    def register_metrics(self, group) -> None:
        """Expose the decision loop on the job metric tree
        (job.<name>.autoscale.*)."""
        g = group.add_group("autoscale")
        g.gauge("current_shards", self.current_shards)
        g.gauge("rescales", lambda: len(self.events))
        g.gauge("live_handoffs",
                lambda: sum(1 for e in self.events if e.mode == "live"))
        g.gauge("last_target",
                lambda: self.events[-1].target if self.events else 0)
        g.gauge("last_decision",
                lambda: (self.last_decision.reason
                         if self.last_decision else ""))
        g.gauge("skew_guard_refusals",
                lambda: self.policy.skew_guard_refusals)
        g.gauge("key_imbalance", lambda: self.policy.last_imbalance)
        self._handoff_hist = g.histogram("handoff_ms")

    # ---------------------------------------------------------------- state

    def current_shards(self) -> int:
        if self._shards_fn is not None:
            return int(self._shards_fn())
        if self.engine is not None:
            return int(self.engine.P)
        if self.job is not None:
            return int(getattr(self.job, "current_parallelism", 1))
        return 1

    @property
    def live_handoffs(self) -> int:
        return sum(1 for e in self.events if e.mode == "live")

    # ----------------------------------------------------------------- tick

    def _differentiate(self, now: float) -> Optional[PolicyInput]:
        sample = self.sample_fn()
        prev, prev_t = self._last_sample, self._last_sample_t
        self._last_sample, self._last_sample_t = sample, now
        if prev is None or prev_t is None or now <= prev_t:
            return None
        dt = now - prev_t
        return PolicyInput(
            current_shards=self.current_shards(),
            processing_rate=max(
                sample.records_total - prev.records_total, 0.0) / dt,
            busy_fraction=max(
                sample.busy_ms_total - prev.busy_ms_total, 0.0)
            / 1000.0 / dt,
            backlog=sample.backlog,
            backlog_growth=(sample.backlog - prev.backlog) / dt,
            shard_resident_rows=sample.shard_resident_rows,
            fire_latency_p99_ms=sample.fire_latency_p99_ms,
        )

    def tick(self, now: Optional[float] = None) -> Optional[RescaleEvent]:
        """Sample -> decide -> (maybe) rescale. Returns the event when a
        rescale was applied, else None. Call from the owning task loop —
        the live path mutates engine state and MUST run single-owner."""
        now = self._clock() if now is None else now
        if self._last_tick is not None and \
                now - self._last_tick < self.interval_s:
            return None
        self._last_tick = now
        inp = self._differentiate(now)
        if inp is None:
            return None
        decision = self.policy.decide(inp, now=now)
        self.last_decision = decision
        if decision.reason == "imbalance" and self.on_imbalance is not None:
            # the guard refused a scale-down because one shard is hot:
            # hand the sample to the rebalancer — moving hot key groups
            # is the fix a shard-count change cannot provide
            self.on_imbalance(inp)
        if not decision.rescale or decision.target == inp.current_shards:
            return None
        return self._apply(decision, inp.current_shards, now)

    def _apply(self, decision: Decision, source: int,
               now: float) -> Optional[RescaleEvent]:
        handoff_s = 0.0
        rows_moved = 0
        if self.engine is not None:
            report = self.engine.reshard(decision.target)
            mode = "live"
            handoff_s = float(report.get("seconds", 0.0))
            rows_moved = int(report.get("rows_moved", 0))
        elif self.job is not None:
            accepted = self.job.request_rescale(decision.target)
            if not accepted:
                # the job cannot rescale right now (no checkpointing /
                # not running) — do not burn the cooldown on a no-op
                return None
            mode = "cold"
        else:
            report = self.apply_fn(decision.target) or {}
            mode = report.get("mode", "live")
            handoff_s = float(report.get("seconds", 0.0))
            rows_moved = int(report.get("rows_moved", 0))
        self.policy.mark_rescaled(now)
        event = RescaleEvent(at=now, source=source,
                             target=decision.target,
                             reason=decision.reason, mode=mode,
                             handoff_s=handoff_s, rows_moved=rows_moved)
        self.events.append(event)
        if self._handoff_hist is not None and mode == "live":
            self._handoff_hist.update(handoff_s * 1000.0)
        return event
