"""Autoscaler: metrics-driven elastic rescaling of the keyed plane.

``policy`` decides *how many* shards a keyed operator should have
(DS2-style true-rate estimation with hysteresis, cooldown and bounds);
``controller`` decides *how to get there* — live key-group migration
for the mesh engines (``MeshWindowEngine.reshard`` /
``MeshSessionEngine.reshard``), the minicluster's reactive redeploy
(checkpoint-restore-at-new-parallelism) as the cold fallback.
``rebalance`` handles what shard-count changes cannot: skew. It moves
hot key groups between shards (``engine.reassign_key_groups``) and
splits single dominant keys (``engine.register_hot_key``) when the
scaling policy's skew guard refuses to act.
"""

from flink_tpu.autoscale.policy import (  # noqa: F401
    Decision,
    PolicyInput,
    ScalingPolicy,
)
from flink_tpu.autoscale.controller import (  # noqa: F401
    AutoscaleController,
    RescaleEvent,
)
from flink_tpu.autoscale.rebalance import (  # noqa: F401
    RebalancePlan,
    RebalancePolicy,
    SkewResponder,
)
