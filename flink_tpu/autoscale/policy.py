"""DS2-style scaling policy: target shard count from observed rates.

reference: the reactive/adaptive scheduler decides *when* to rescale
(AdaptiveScheduler.java — on resource change); *how far* is the job of
an external autoscaler. This policy re-implements the core of DS2
("Three steps is all you need", OSDI'18 — the algorithm behind Flink's
Kubernetes autoscaler, reference:
flink-kubernetes-operator/.../autoscaler/ScalingMetricEvaluator.java
semantics): estimate each operator's TRUE processing rate (observed
throughput divided by the fraction of time it was busy — what it
*could* process at 100% busy), then size the operator so the incoming
rate plus backlog drain fits under a utilization target.

Everything here is pure arithmetic over a :class:`PolicyInput` sample
with an injectable clock — no I/O, no engine references — so the unit
suite drives hysteresis/cooldown/bounds deterministically.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence


def key_imbalance(shard_resident_rows: Sequence[int]) -> float:
    """max/mean resident rows per shard (1.0 = perfectly balanced) —
    THE skew definition, shared by the engines' gauge and the policy's
    scale-down guard so the number the operator exports is exactly the
    number the guard acts on."""
    rows = list(shard_resident_rows)
    total = sum(rows)
    if not rows or total <= 0:
        return 1.0
    return max(rows) * len(rows) / total


@dataclasses.dataclass
class PolicyInput:
    """One signal sample, pre-aggregated over the sampling window."""

    current_shards: int
    #: records/s actually processed over the window
    processing_rate: float = 0.0
    #: fraction of wall time the operator was busy (0..1) — the DS2
    #: "useful time" denominator
    busy_fraction: float = 0.0
    #: instantaneous backlog (records queued upstream of the operator)
    backlog: float = 0.0
    #: records/s the backlog GREW over the window (negative = draining)
    backlog_growth: float = 0.0
    #: device-resident rows per shard (the key-imbalance signal)
    shard_resident_rows: Sequence[int] = ()
    #: recent window-fire p99 in wall-clock ms (0 = no fires observed)
    #: — the SECOND signal next to backlog: sustained misses of the
    #: fire deadline are a capacity problem even when throughput keeps
    #: up (the latency tier's autoscale hook, ROADMAP item 1)
    fire_latency_p99_ms: float = 0.0


@dataclasses.dataclass(frozen=True)
class Decision:
    """target == current means "stay"; ``reason`` says why."""

    target: int
    reason: str

    @property
    def rescale(self) -> bool:
        return self.reason not in _STAY_REASONS


_STAY_REASONS = ("no-signal", "steady", "hysteresis", "cooldown",
                 "imbalance", "fire-latency-hold")


class ScalingPolicy:
    """Target shard count under a utilization target, with hysteresis,
    cooldown, min/max bounds and a skew guard.

    - **utilization_target**: size the operator so busy fraction lands
      here (0.7 default — headroom absorbs bursts without rescaling).
    - **hysteresis**: ignore targets within this relative band of the
      current size (a 10%% rate wobble must not flap the mesh).
    - **cooldown_s**: minimum time between rescales (state migration is
      cheap but not free; reference: the k8s autoscaler's
      scaling-interval).
    - **min/max_shards**: hard bounds; enforced immediately (out-of-
      bounds current size rescales on the next tick regardless of
      rates — the operator may have been deployed before the bounds).
    - **imbalance_limit**: refuse to scale DOWN while
      max/mean resident rows per shard exceeds it — a hot shard under
      skew is not spare capacity, and fewer shards concentrate the same
      keys harder.
    - **backlog_drain_s**: extra capacity is provisioned to drain the
      standing backlog within this horizon.
    - **fire_deadline_ms / fire_breach_ticks**: the fire-latency
      signal. When a deadline is set (> 0) and the sampled window-fire
      p99 exceeds it for ``fire_breach_ticks`` CONSECUTIVE decisions,
      scale up by half the current size even though the rate signal
      says steady — a sustained deadline miss means fires are queueing
      behind ingest, which more shards (smaller per-shard deltas)
      relieve. While any breach streak is active, scale-DOWN decisions
      are vetoed (a deadline-missing operator is not overprovisioned).

    ``clock`` is injectable (unit tests pass a fake); cooldown tracking
    is internal — call :meth:`mark_rescaled` after actually applying a
    decision.
    """

    def __init__(
        self,
        utilization_target: float = 0.7,
        hysteresis: float = 0.25,
        cooldown_s: float = 30.0,
        min_shards: int = 1,
        max_shards: int = 0,
        imbalance_limit: float = 2.0,
        backlog_drain_s: float = 60.0,
        backlog_threshold: float = 0.0,
        fire_deadline_ms: float = 0.0,
        fire_breach_ticks: int = 3,
        clock=None,
    ) -> None:
        import time as _time

        if not (0.0 < utilization_target <= 1.0):
            raise ValueError(
                f"utilization_target must be in (0, 1], got "
                f"{utilization_target}")
        self.utilization_target = float(utilization_target)
        self.hysteresis = max(float(hysteresis), 0.0)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.min_shards = max(int(min_shards), 1)
        self.max_shards = int(max_shards or 0)  # 0 = unbounded
        if self.max_shards and self.max_shards < self.min_shards:
            raise ValueError(
                f"max_shards {self.max_shards} < min_shards "
                f"{self.min_shards}")
        self.imbalance_limit = float(imbalance_limit)
        self.backlog_drain_s = max(float(backlog_drain_s), 1.0)
        self.backlog_threshold = float(backlog_threshold)
        self.fire_deadline_ms = max(float(fire_deadline_ms), 0.0)
        self.fire_breach_ticks = max(int(fire_breach_ticks), 1)
        self._clock = clock or _time.monotonic
        self._last_rescale: Optional[float] = None
        #: consecutive decisions whose fire p99 exceeded the deadline
        self._fire_breaches = 0
        #: times the skew guard vetoed a scale-down — previously the
        #: guard refused SILENTLY; a rebalancer (autoscale.rebalance)
        #: keys off this signal instead of a log line
        self.skew_guard_refusals = 0
        #: imbalance measured at the most recent decide() with resident
        #: rows in the sample (1.0 = balanced)
        self.last_imbalance = 1.0

    # --------------------------------------------------------------- helpers

    def _clamp(self, target: int) -> int:
        target = max(target, self.min_shards)
        if self.max_shards:
            target = min(target, self.max_shards)
        return target

    #: the module-level shared definition (see key_imbalance)
    imbalance = staticmethod(key_imbalance)

    def in_cooldown(self, now: Optional[float] = None) -> bool:
        if self._last_rescale is None:
            return False
        now = self._clock() if now is None else now
        return (now - self._last_rescale) < self.cooldown_s

    def mark_rescaled(self, now: Optional[float] = None) -> None:
        """The controller APPLIED a rescale — start the cooldown."""
        self._last_rescale = self._clock() if now is None else now

    # ---------------------------------------------------------------- decide

    def decide(self, inp: PolicyInput,
               now: Optional[float] = None) -> Decision:
        now = self._clock() if now is None else now
        cur = max(int(inp.current_shards), 1)
        if len(inp.shard_resident_rows):
            self.last_imbalance = self.imbalance(inp.shard_resident_rows)

        # hard bounds win over everything except cooldown: a job
        # deployed outside [min, max] converges on the next tick
        bounded = self._clamp(cur)
        if bounded != cur:
            if self.in_cooldown(now):
                return Decision(cur, "cooldown")
            return Decision(bounded, "bounds")

        # fire-latency signal: independent of the rate signal (fires can
        # miss their deadline while throughput keeps up — the queueing
        # problem the latency tier exists for)
        if self.fire_deadline_ms > 0.0:
            if inp.fire_latency_p99_ms > self.fire_deadline_ms:
                self._fire_breaches += 1
            else:
                self._fire_breaches = 0
            if self._fire_breaches >= self.fire_breach_ticks:
                target = self._clamp(cur + max(cur // 2, 1))
                if target > cur:
                    if self.in_cooldown(now):
                        return Decision(cur, "cooldown")
                    self._fire_breaches = 0
                    return Decision(target, "fire-latency")

        if inp.processing_rate <= 0.0 or inp.busy_fraction <= 0.0:
            return Decision(cur, "no-signal")

        # DS2 core: true rate = what the operator COULD process at 100%
        # busy; required rate = what is arriving, plus enough to drain
        # the standing backlog within the horizon
        busy = min(max(inp.busy_fraction, 1e-6), 1.0)
        true_rate = inp.processing_rate / busy
        per_shard_rate = true_rate / cur
        required = inp.processing_rate + max(inp.backlog_growth, 0.0)
        if inp.backlog > self.backlog_threshold:
            required += inp.backlog / self.backlog_drain_s
        raw_target = math.ceil(
            required / (per_shard_rate * self.utilization_target))
        target = self._clamp(max(raw_target, 1))

        if target == cur:
            return Decision(cur, "steady")
        # hysteresis band: a relative change this small is noise
        if abs(target - cur) / cur <= self.hysteresis:
            return Decision(cur, "hysteresis")
        if self.in_cooldown(now):
            return Decision(cur, "cooldown")
        if target < cur:
            if self._fire_breaches > 0:
                # fires are missing their deadline: the operator is not
                # overprovisioned, whatever the rate math says
                return Decision(cur, "fire-latency-hold")
            imb = self.imbalance(inp.shard_resident_rows)
            if imb > self.imbalance_limit:
                # the hot shard explains the load: scaling down would
                # concentrate the skew, not shed capacity — counted (not
                # silent) so the rebalance hand-off can observe it
                self.skew_guard_refusals += 1
                return Decision(cur, "imbalance")
            return Decision(target, "scale-down")
        return Decision(target, "scale-up")
