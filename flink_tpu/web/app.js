/* flink_tpu dashboard SPA (reference: flink-runtime-web/web-dashboard).
   Hash-routed views over the REST surface; no dependencies. */
"use strict";

const $view = document.getElementById("view");
let timer = null;          // per-view auto-refresh
let navSeq = 0;            // navigation token: stale renders must not land
const sparkHistory = {};   // metric -> ring of recent values (client-side)

function renderGate() {
  // capture at render start; check before writing $view — an await that
  // resolves after the user navigated away must not clobber the new view
  const seq = navSeq;
  return () => seq === navSeq;
}
function editingInView() {
  const el = document.activeElement;
  return el && $view.contains(el) &&
    /INPUT|TEXTAREA|SELECT/.test(el.tagName);
}

function esc(x) {
  return String(x).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  }[c]));
}
async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
  return r.json();
}
async function postJSON(path, body) {
  const r = await fetch(path, { method: "POST", body: JSON.stringify(body || {}) });
  return r.json().catch(() => ({}));
}
function fmt(n) {
  if (typeof n !== "number" || !isFinite(n)) return esc(n);
  if (Number.isInteger(n)) return n.toLocaleString("en-US");
  return n.toLocaleString("en-US", { maximumFractionDigits: 3 });
}
function pill(status) {
  return `<span class="pill ${esc(status)}">${esc(status)}</span>`;
}
function setNav(name) {
  document.querySelectorAll("[data-nav]").forEach(a =>
    a.classList.toggle("active", a.dataset.nav === name));
}
function refreshEvery(ms, fn) {
  clearInterval(timer);
  timer = setInterval(fn, ms);
}

/* ---------------------------------------------------------- overview */

async function viewOverview() {
  setNav("overview");
  const render = async () => {
    const live = renderGate();
    const [ov, jobs] = await Promise.all([
      getJSON("/overview"), getJSON("/jobs")]);
    if (!live()) return;
    document.getElementById("version").textContent =
      "v" + (ov.flink_tpu_version || "?");
    const counts = ov.jobs || {};
    $view.innerHTML = `
      <h1>Cluster overview</h1>
      <div class="tiles">
        <div class="tile"><div class="label">Task executors</div>
          <div class="value">${fmt(ov.taskexecutors)}</div></div>
        <div class="tile"><div class="label">Slots</div>
          <div class="value">${fmt(ov.slots_total)}</div></div>
        <div class="tile"><div class="label">Running jobs</div>
          <div class="value">${fmt(counts.RUNNING || 0)}</div>
          <div class="sub">${fmt(counts.FINISHED || 0)} finished ·
            ${fmt(counts.FAILED || 0)} failed</div></div>
      </div>
      <h2>Jobs</h2>
      ${jobsTable(jobs.jobs || [])}`;
    bindJobRows();
  };
  await render();
  refreshEvery(2000, render);
}

function jobsTable(jobs) {
  if (!jobs.length) return `<p class="hint">No jobs submitted yet.</p>`;
  const rows = jobs.map(j => `
    <tr class="click" data-job="${esc(j.job_id)}">
      <td><code>${esc(j.job_id)}</code></td>
      <td>${esc(j.name || "")}</td>
      <td>${pill(j.status)}</td>
      <td class="num">${fmt(j.attempt ?? 0)}</td>
      <td>${esc(j.error || "")}</td>
    </tr>`).join("");
  return `<table><thead><tr><th>ID</th><th>Name</th><th>Status</th>
    <th class="num">Attempt</th><th>Error</th></tr></thead>
    <tbody>${rows}</tbody></table>`;
}
function bindJobRows() {
  document.querySelectorAll("tr[data-job]").forEach(tr =>
    tr.addEventListener("click",
      () => { location.hash = `#/jobs/${tr.dataset.job}`; }));
}

/* --------------------------------------------------------- executors */

async function viewExecutors() {
  setNav("executors");
  const render = async () => {
    const live = renderGate();
    const data = await getJSON("/taskexecutors");
    if (!live()) return;
    // in-process executors seed from heartbeat() ({id, slots_total,
    // slots_free}); remote ones from the RM registry ({executor_id,
    // slots, allocated, address}) — accept both shapes
    const rows = (data.executors || []).map(e => `
      <tr><td><code>${esc(e.executor_id || e.id || "")}</code></td>
        <td>${esc(e.address || "in-process")}</td>
        <td class="num">${fmt(e.slots ?? e.slots_total ?? "")}</td>
        <td class="num">${fmt(e.allocated ??
          (e.slots_total !== undefined
            ? e.slots_total - e.slots_free : ""))}</td>
        <td class="num">${fmt(e.heartbeat_age_s ?? "")}</td></tr>`).join("");
    $view.innerHTML = `
      <h1>Task executors</h1>
      <table><thead><tr><th>ID</th><th>Address</th><th class="num">Slots</th>
        <th class="num">Allocated</th><th class="num">Heartbeat age (s)</th>
        </tr></thead><tbody>${rows}</tbody></table>`;
  };
  await render();
  refreshEvery(3000, render);
}

/* --------------------------------------------------------- job detail */

async function viewJob(jobId) {
  setNav("");
  const render = async () => {
    const live = renderGate();
    if (editingInView()) return;  // don't destroy a focused form
    let job, plan, metrics;
    try {
      [job, plan, metrics] = await Promise.all([
        getJSON(`/jobs/${jobId}`),
        getJSON(`/jobs/${jobId}/plan`).catch(() => null),
        getJSON(`/jobs/${jobId}/metrics`).catch(() => null)]);
    } catch (e) {
      if (live()) $view.innerHTML =
        `<p class="error">${esc(e.message)}</p>`;
      return;
    }
    if (!live() || editingInView()) return;
    // flatten the metrics payload ONCE; both panels read the same map
    const flatMetrics = flattenMetrics(metrics);
    const hist = job.state_history || [];
    const started = hist.length ? hist[0].ts : null;
    const uptime = started ? ((Date.now() / 1000) - started) : null;
    $view.innerHTML = `
      <h1><code>${esc(jobId)}</code> ${esc(job.name || "")}
          ${pill(job.status)}</h1>
      <div class="tiles">
        <div class="tile"><div class="label">Attempt</div>
          <div class="value">${fmt(job.attempt ?? 0)}</div></div>
        ${uptime !== null && job.status === "RUNNING" ? `
        <div class="tile"><div class="label">Uptime</div>
          <div class="value">${fmt(Math.round(uptime))}s</div></div>` : ""}
      </div>
      <div class="formrow">
        <a href="#/jobs/${esc(jobId)}/flamegraph"><button>Flame graph</button></a>
        <a href="#/jobs/${esc(jobId)}/state"><button>Queryable state</button></a>
        <button id="do-savepoint">Trigger savepoint</button>
        <input id="savepoint-path" placeholder="savepoint path"
               value="/tmp/flink-tpu-savepoints/${esc(jobId)}">
        <button class="danger" id="do-cancel">Cancel job</button>
        <span id="action-out" class="hint"></span>
      </div>
      <h2>Job plan</h2>
      ${plan && plan.plan ? renderDag(plan.plan) :
        `<p class="hint">plan unavailable</p>`}
      ${renderLatencyPanel(flatMetrics)}
      <h2>Metrics</h2>
      ${renderMetrics(jobId, metrics, flatMetrics)}
      ${job.error ? `<h2>Error</h2>
        <pre class="block error">${esc(job.error)}</pre>` : ""}
      <h2>State history</h2>
      <table><thead><tr><th>State</th><th>At</th></tr></thead><tbody>
      ${hist.map(h => `<tr><td>${pill(h.state)}</td>
        <td>${new Date(h.ts * 1000).toISOString()}</td></tr>`).join("")}
      </tbody></table>`;
    document.getElementById("do-cancel").onclick = async () => {
      const out = await postJSON(`/jobs/${jobId}/cancel`);
      document.getElementById("action-out").textContent =
        JSON.stringify(out);
    };
    document.getElementById("do-savepoint").onclick = async () => {
      const target = document.getElementById("savepoint-path").value;
      const out = await postJSON(`/jobs/${jobId}/savepoints`, { target });
      document.getElementById("action-out").textContent =
        JSON.stringify(out);
    };
  };
  await render();
  refreshEvery(3000, render);
}

/* job plan: layered DAG in SVG (longest-path layering, per-layer rows) */
function renderDag(plan) {
  const nodes = plan.nodes || [], edges = plan.edges || [];
  if (!nodes.length) return `<p class="hint">empty plan</p>`;
  const layer = {};
  const incoming = {};
  edges.forEach(e => { (incoming[e.target] ||= []).push(e.source); });
  const depth = id => {
    if (layer[id] !== undefined) return layer[id];
    layer[id] = 0; // cycle guard
    const ins = incoming[id] || [];
    layer[id] = ins.length ? 1 + Math.max(...ins.map(depth)) : 0;
    return layer[id];
  };
  nodes.forEach(n => depth(n.id));
  const cols = {};
  nodes.forEach(n => { (cols[layer[n.id]] ||= []).push(n); });
  const W = 190, H = 64, GX = 80, GY = 22;
  const pos = {};
  Object.entries(cols).forEach(([l, ns]) => ns.forEach((n, i) => {
    pos[n.id] = { x: l * (W + GX) + 10, y: i * (H + GY) + 28 };
  }));
  const width = (Math.max(...nodes.map(n => layer[n.id])) + 1) * (W + GX);
  const height = Math.max(...Object.values(pos).map(p => p.y)) + H + 20;
  const boxes = nodes.map(n => {
    const p = pos[n.id];
    const ops = (n.operators || []).join(" → ");
    return `<g>
      <rect class="vertex" x="${p.x}" y="${p.y}" width="${W}" height="${H}"/>
      <text x="${p.x + 9}" y="${p.y + 20}">${esc(trunc(n.description, 24))}</text>
      <text class="sub" x="${p.x + 9}" y="${p.y + 37}">${esc(trunc(ops, 30))}</text>
      <text class="sub" x="${p.x + 9}" y="${p.y + 53}">parallelism ${fmt(n.parallelism)}</text>
    </g>`;
  }).join("");
  const lines = edges.map(e => {
    const a = pos[e.source], b = pos[e.target];
    if (!a || !b) return "";
    const x1 = a.x + W, y1 = a.y + H / 2, x2 = b.x, y2 = b.y + H / 2;
    const mx = (x1 + x2) / 2;
    const label = e.ship_strategy +
      (e.key_field ? `(${e.key_field})` : "");
    return `<path class="edge" marker-end="url(#arrow)"
        d="M${x1},${y1} C${mx},${y1} ${mx},${y2} ${x2},${y2}"/>
      <text class="ship" x="${mx}" y="${Math.min(y1, y2) - 5}"
        text-anchor="middle">${esc(label)}</text>`;
  }).join("");
  return `<div class="dag"><svg width="${width}" height="${height}">
    <defs><marker id="arrow" viewBox="0 0 8 8" refX="7" refY="4"
      markerWidth="7" markerHeight="7" orient="auto">
      <path d="M0,0 L8,4 L0,8 z" fill="currentColor" opacity=".55"/>
    </marker></defs>${lines}${boxes}</svg></div>`;
}
function trunc(s, n) { s = String(s || ""); return s.length > n ? s.slice(0, n - 1) + "…" : s; }

function flattenMetrics(payload) {
  const flat = {};
  if (!payload || !payload.metrics) return flat;
  (function walk(obj, prefix) {
    Object.entries(obj).forEach(([k, v]) => {
      const name = prefix ? `${prefix}.${k}` : k;
      if (v && typeof v === "object" && !Array.isArray(v)) walk(v, name);
      else flat[name] = v;
    });
  })(payload.metrics, "");
  return flat;
}

/* latency panel: per-operator fire p50/p99 (the latency-tier signal),
   watermark lag vs the sources' frontier and LatencyMarker p99 —
   pulled from the .window / .latency metric groups the executor
   registers, same reservoirs the tier-1 fire-p99 gate reads */
function renderLatencyPanel(flat) {
  const ops = {};
  Object.entries(flat).forEach(([k, v]) => {
    let m = k.match(/^(.*)\.window\.(fireLatencyP50Ms|fireLatencyP99Ms|fireCount)$/);
    if (m) { (ops[m[1]] ||= {})[m[2]] = v; return; }
    m = k.match(/^(.*)\.latency\.(watermarkLagMs)$/);
    if (m) { (ops[m[1]] ||= {})[m[2]] = v; return; }
    m = k.match(/^(.*)\.latency\.markerLatencyMs\.(p99)$/);
    if (m) (ops[m[1]] ||= {})["markerP99"] = v;
  });
  const names = Object.keys(ops).filter(op =>
    Object.keys(ops[op]).length);
  if (!names.length) return "";
  const rows = names.sort().map(op => {
    const d = ops[op];
    const short = op.split(".").pop();
    return `<tr><td title="${esc(op)}">${esc(short)}</td>
      <td class="num">${fmt(d.fireLatencyP50Ms ?? "")}</td>
      <td class="num">${fmt(d.fireLatencyP99Ms ?? "")}</td>
      <td class="num">${fmt(d.fireCount ?? "")}</td>
      <td class="num">${fmt(d.watermarkLagMs ?? "")}</td>
      <td class="num">${fmt(d.markerP99 ?? "")}</td></tr>`;
  }).join("");
  return `<h2>Latency</h2>
    <table><thead><tr><th>Operator</th>
      <th class="num">Fire p50 (ms)</th><th class="num">Fire p99 (ms)</th>
      <th class="num">Fires</th><th class="num">Watermark lag (ms)</th>
      <th class="num">Marker p99 (ms)</th></tr></thead>
    <tbody>${rows}</tbody></table>`;
}

/* metrics: numeric leaves as sparkline cards (history accumulates while
   the view is open), non-numeric in a table */
function renderMetrics(jobId, payload, flat) {
  if (!payload || !payload.metrics ||
      !Object.keys(payload.metrics).length) {
    return `<p class="hint">${esc(payload && payload.note ||
      "no metrics yet")}</p>`;
  }
  flat = flat || flattenMetrics(payload);
  const numeric = [], other = [];
  Object.entries(flat).forEach(([k, v]) =>
    (typeof v === "number" ? numeric : other).push([k, v]));
  numeric.forEach(([k, v]) => {
    const key = `${jobId}:${k}`;
    const ring = sparkHistory[key] ||= [];
    if (!ring.length || ring[ring.length - 1] !== v) ring.push(v);
    if (ring.length > 60) ring.shift();
  });
  const cards = numeric.slice(0, 24).map(([k, v]) => {
    const ring = sparkHistory[`${jobId}:${k}`] || [v];
    return `<div class="spark"><div class="label"
      title="${esc(k)}">${esc(k)}</div>
      <div class="value">${fmt(v)}</div>${sparkline(ring)}</div>`;
  }).join("");
  const rows = other.map(([k, v]) => `<tr><td>${esc(k)}</td>
    <td>${esc(JSON.stringify(v))}</td></tr>`).join("");
  return `<div class="sparkgrid">${cards}</div>
    ${rows ? `<h2>Other metrics</h2><table><tbody>${rows}</tbody></table>` : ""}`;
}
function sparkline(values) {
  if (values.length < 2) return `<svg viewBox="0 0 100 34"></svg>`;
  const lo = Math.min(...values), hi = Math.max(...values);
  const span = hi - lo || 1;
  const pts = values.map((v, i) =>
    `${(i / (values.length - 1)) * 98 + 1},${31 - ((v - lo) / span) * 28}`);
  return `<svg viewBox="0 0 100 34" preserveAspectRatio="none">
    <polyline points="${pts.join(" ")}"/></svg>`;
}

/* --------------------------------------------------------- flamegraph */

async function viewFlame(jobId) {
  setNav(jobId ? "" : "flamegraph");
  const path = jobId ? `/jobs/${jobId}/flamegraph?duration_ms=400`
                     : `/flamegraph?duration_ms=400&all=1`;
  $view.innerHTML = `<h1>Flame graph${jobId ?
    ` — <code>${esc(jobId)}</code>` : " — cluster"}</h1>
    <p class="hint">sampling 400 ms…</p>`;
  const live = renderGate();
  let data;
  try { data = await getJSON(path); }
  catch (e) {
    if (live()) $view.innerHTML += `<p class="error">${esc(e.message)}</p>`;
    return;
  }
  if (!live()) return;
  const total = data.samples || (data.root && data.root.value) || 1;
  const root = data.root || data;
  $view.innerHTML = `
    <h1>Flame graph${jobId ? ` — <code>${esc(jobId)}</code>` : " — cluster"}</h1>
    <p class="hint">${fmt(data.samples || 0)} samples ·
      widths are sample share · hover for counts</p>
    <div class="flame">${flameRow(root, total, 0)}</div>`;
}
function flameRow(node, total, depth) {
  const kids = node.children || [];
  const width = Math.max((node.value / total) * 100, 0.4);
  const ramp = ["--seq-1", "--seq-2", "--seq-3", "--seq-4", "--seq-5"];
  const color = `var(${ramp[Math.min(depth, ramp.length - 1)]})`;
  const ink = depth >= 3 ? "color: var(--surface-1);" : "";
  const self = depth === 0 ? "" :
    `<div class="frame" style="width:${width}%;background:${color};${ink}"
      title="${esc(node.name)} — ${fmt(node.value)} samples">
      ${esc(node.name)}</div>`;
  const childBlobs = kids
    .slice().sort((a, b) => b.value - a.value)
    .map(c => `<div style="display:inline-block;vertical-align:top;
       width:${(c.value / Math.max(node.value, 1)) * 100}%">
       ${flameRow(c, total, depth + 1)}</div>`).join("");
  return `${self}<div class="row">${childBlobs}</div>`;
}

/* ------------------------------------------------------ queryable state */

async function viewState(jobId) {
  setNav("");
  $view.innerHTML = `
    <h1>Queryable state — <code>${esc(jobId)}</code></h1>
    <div class="formrow">
      <input id="qs-op" placeholder="operator name">
      <input id="qs-key" placeholder="key">
      <input id="qs-ns" placeholder="namespace (optional)">
      <button id="qs-go">Look up</button>
    </div>
    <pre class="block" id="qs-out">results appear here</pre>`;
  document.getElementById("qs-go").onclick = async () => {
    const op = document.getElementById("qs-op").value;
    const key = encodeURIComponent(document.getElementById("qs-key").value);
    const ns = document.getElementById("qs-ns").value;
    const url = `/jobs/${jobId}/state/${encodeURIComponent(op)}?key=${key}` +
      (ns ? `&namespace=${encodeURIComponent(ns)}` : "");
    try {
      const out = await getJSON(url);
      document.getElementById("qs-out").textContent =
        JSON.stringify(out, null, 2);
    } catch (e) {
      document.getElementById("qs-out").textContent = e.message;
    }
  };
}

/* ------------------------------------------------------------- router */

function route() {
  navSeq += 1;
  clearInterval(timer);
  const h = location.hash.replace(/^#\/?/, "");
  const parts = h.split("/").filter(Boolean);
  if (!parts.length || parts[0] === "overview") return viewOverview();
  if (parts[0] === "executors") return viewExecutors();
  if (parts[0] === "flamegraph") return viewFlame(null);
  if (parts[0] === "jobs" && parts.length >= 2) {
    const jobId = parts[1];
    if (parts[2] === "flamegraph") return viewFlame(jobId);
    if (parts[2] === "state") return viewState(jobId);
    return viewJob(jobId);
  }
  $view.innerHTML = `<p class="error">unknown route: ${esc(h)}</p>`;
}
window.addEventListener("hashchange", route);
route();
