"""Chaos engine: deterministic fault injection + crash-restore-verify.

The subsystem that makes the failure story EXECUTABLE: named fault
points threaded through the shuffle, spill, checkpoint, mesh-engine and
cluster layers (``injection``), and a harness that kills a pipeline at
those points, restores from the latest complete checkpoint and diffs
the final output against a fault-free oracle (``harness``) — the same
crash/preemption-tolerance contract the reference proves with its
checkpoint/failover ITCases (flink-runtime checkpoint + failover
layers), rebuilt for the micro-batch mesh engines.

Everything is reproducible from ``(FaultPlan, seed)``: schedules are
hit-counted, randomness comes from a dedicated PRNG, and the controller
is a strict no-op while disarmed (the hot path pays one module-global
None check).
"""

#: Canonical fault-point inventory — THE single source of truth shared
#: by the test suite's "every fault point reachable" ledger
#: (tests/test_chaos.py) and flint's REG01 registry check (tools/flint).
#: Adding an injection site means adding its name here (and its row to
#: the NOTES inventory table); a typo in either direction — a call site
#: not listed, or a listed name with no call site — fails both gates.
#: Keep this a plain literal tuple: flint parses it statically.
KNOWN_FAULT_POINTS = (
    "shuffle.bucket_prep",
    "shuffle.bucket_send",
    "shuffle.device_exchange",
    "exchange.dcn_send",
    "spill.page_reload",
    "spill.page_compact",
    "checkpoint.write",
    "checkpoint.write.torn",
    "checkpoint.read",
    "mesh.dispatch_fence",
    "mesh.session_fire",
    "mesh.window_fire",
    "rescale.handoff",
    "rebalance.handoff",
    "join.exchange",
    "join.versioned_lookup",
    "cep.advance",
    "cep.match_fire",
    "serving.lookup",
    "serving.replica_publish",
    "serving.cache_probe",
    "serving.frontend",
    "harvest.pending_fire",
    "task.batch",
    "task.subtask_batch",
    "device.lost",
    "host.lost",
    "watchdog.deadline",
)

from flink_tpu.chaos.injection import (  # noqa: E402,F401
    ChaosController,
    FaultPlan,
    FaultRule,
    InjectedFault,
    RetryBudgetExhaustedError,
    arm,
    armed,
    chaos_active,
    controller,
    disarm,
    fault_point,
    io_point,
    payload_action,
    register_chaos_metrics,
    run_recoverable,
)
from flink_tpu.chaos.harness import (  # noqa: E402,F401
    ChaosDivergenceError,
    ChaosReport,
    run_crash_restore_verify,
    run_crash_restore_verify_multi,
    run_shard_loss_verify,
)
