"""Chaos engine: deterministic fault injection + crash-restore-verify.

The subsystem that makes the failure story EXECUTABLE: named fault
points threaded through the shuffle, spill, checkpoint, mesh-engine and
cluster layers (``injection``), and a harness that kills a pipeline at
those points, restores from the latest complete checkpoint and diffs
the final output against a fault-free oracle (``harness``) — the same
crash/preemption-tolerance contract the reference proves with its
checkpoint/failover ITCases (flink-runtime checkpoint + failover
layers), rebuilt for the micro-batch mesh engines.

Everything is reproducible from ``(FaultPlan, seed)``: schedules are
hit-counted, randomness comes from a dedicated PRNG, and the controller
is a strict no-op while disarmed (the hot path pays one module-global
None check).
"""

from flink_tpu.chaos.injection import (  # noqa: F401
    ChaosController,
    FaultPlan,
    FaultRule,
    InjectedFault,
    arm,
    armed,
    chaos_active,
    controller,
    disarm,
    fault_point,
    io_point,
    payload_action,
    register_chaos_metrics,
    run_recoverable,
)
from flink_tpu.chaos.harness import (  # noqa: F401
    ChaosDivergenceError,
    ChaosReport,
    run_crash_restore_verify,
)
