"""Crash-restore-verify: the executable exactly-once claim.

Drives a keyed-window engine (mesh or single-device) through a seeded
event stream with periodic checkpoints while a :class:`FaultPlan` is
armed. Every injected crash KILLS the engine (the object is discarded,
like a preempted worker), a fresh engine restores from the latest
*complete* checkpoint (``latest_checkpoint_id(verify=True)`` skips
torn/corrupt snapshots via the manifest CRCs), and the source replays
from the position recorded in that checkpoint's manifest. The final
output is diffed window-by-window against a fault-free single-device
oracle run — zero divergence is the exactly-once claim, executed.

Sink model: a keyed idempotent upsert committed per checkpoint epoch
(the two-phase-commit shape of ``connectors/two_phase.py`` collapsed
onto a host dict). Output produced since the last completed checkpoint
is buffered and DISCARDED on crash; replay re-produces it. A replayed
fire lands on the same ``(key, window_start, window_end)`` cell, so the
diff catches any lost, duplicated or corrupted record as a wrong final
value — duplicates are not silently absorbed, they change the sum.

reference: the recovery ITCases + savepoint ITCases of flink-tests,
which assert exactly-once counts after induced failures; here the
induction is deterministic (plan, seed) instead of scripted process
kills, so a failure is replayable bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.chaos.injection import FaultPlan, InjectedFault
from flink_tpu.chaos import injection as chaos
from flink_tpu.metrics.traces import default_collector
from flink_tpu.observe import flight_recorder as flight

#: end-of-stream watermark (matches the test-suite flush convention)
FINAL_WATERMARK = 1 << 60

_WindowKey = Tuple[int, int, int]


class ChaosDivergenceError(AssertionError):
    """Committed output diverged from the fault-free oracle."""


@dataclasses.dataclass
class ChaosReport:
    events: int = 0
    windows: int = 0
    crashes: int = 0
    restores: int = 0
    cold_restarts: int = 0
    checkpoints_written: int = 0
    corrupt_checkpoints_skipped: int = 0
    faults_injected: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    points_hit: Dict[str, int] = dataclasses.field(default_factory=dict)
    retries: int = 0
    recoveries: int = 0
    #: completed LIVE key-group migrations (engine.reshard) — replays
    #: past an already-applied rescale position do not re-count
    live_handoffs: int = 0
    #: shard-granular failovers (run_shard_loss_verify): shards
    #: declared dead, key-group ranges restored from their checkpoint
    #: units, and records re-absorbed to rebuild those ranges — the
    #: bounded-replay claim is ``records_replayed <= events/shards +
    #: padding``, gated in tools/chaos_smoke.py
    shards_lost: int = 0
    shard_restores: int = 0
    records_replayed: int = 0
    shard_loss_recovery_ms: float = 0.0
    #: HOST-granular failovers: a HostFailedError took a whole
    #: process's contiguous slice of shards in one evacuation (each
    #: also counts its member shards into shards_lost)
    hosts_lost: int = 0
    divergences: List[str] = dataclasses.field(default_factory=list)

    @property
    def diverged(self) -> bool:
        return bool(self.divergences)

    def signature(self) -> Dict[str, Any]:
        """The determinism fingerprint: two runs with the same
        (plan, seed, steps) must produce identical signatures."""
        return {
            "crashes": self.crashes,
            "restores": self.restores,
            "cold_restarts": self.cold_restarts,
            "checkpoints_written": self.checkpoints_written,
            "faults_injected": dict(self.faults_injected),
            "windows": self.windows,
            "live_handoffs": self.live_handoffs,
            "shards_lost": self.shards_lost,
            "hosts_lost": self.hosts_lost,
            "shard_restores": self.shard_restores,
            "records_replayed": self.records_replayed,
            "diverged": self.diverged,
        }

    def register_metrics(self, group) -> None:
        """Surface the restore-path counters through a job metric tree
        (``<scope>.chaos.*``): gauges read the LIVE report, so a group
        registered before the run sees every later restore. The
        harnesses call this when given ``metric_group=``; today only
        harness reports carried these numbers."""
        g = group.add_group("chaos")
        for name in ("restores", "cold_restarts",
                     "corrupt_checkpoints_skipped", "crashes",
                     "shards_lost", "hosts_lost", "shard_restores",
                     "records_replayed", "checkpoints_written"):
            g.gauge(name, lambda self=self, n=name: getattr(self, n))


def _keyed_batch(keys, values, ts):
    from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch

    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=np.asarray(ts, dtype=np.int64))


def _collect(fired, out: Dict[_WindowKey, Dict[str, float]]) -> None:
    """Fold fired batches (or PendingFire handles) into the keyed
    upsert store."""
    from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD
    from flink_tpu.windowing.windower import (
        WINDOW_END_FIELD,
        WINDOW_START_FIELD,
    )

    for b in fired:
        if b is None:
            continue
        if hasattr(b, "harvest"):  # PendingFire (async dispatch-ahead)
            b = b.harvest()
            if b is None:
                continue
        for r in b.to_rows():
            key = (int(r[KEY_ID_FIELD]), int(r[WINDOW_START_FIELD]),
                   int(r[WINDOW_END_FIELD]))
            out[key] = {
                name: float(v) for name, v in r.items()
                if name not in (KEY_ID_FIELD, WINDOW_START_FIELD,
                                WINDOW_END_FIELD, TIMESTAMP_FIELD)
            }


def _diff(expected: Dict[_WindowKey, Dict[str, float]],
          got: Dict[_WindowKey, Dict[str, float]],
          rel_tol: float, abs_tol: float,
          max_report: int = 20) -> List[str]:
    divs: List[str] = []
    for k in sorted(set(expected) | set(got)):
        if len(divs) >= max_report:
            divs.append("... (truncated)")
            break
        if k not in got:
            divs.append(f"missing window {k}: expected {expected[k]}")
        elif k not in expected:
            divs.append(f"spurious window {k}: got {got[k]}")
        else:
            for name, want in expected[k].items():
                have = got[k].get(name)
                if have is None or abs(have - want) > max(
                        abs_tol, rel_tol * abs(want)):
                    divs.append(
                        f"window {k} field {name}: expected {want}, "
                        f"got {have}")
    return divs


def _restore_latest(storage, ckpt_dir: str, engine,
                    report: ChaosReport) -> Optional[int]:
    """The shared restore protocol (single-job and multi-job harness):
    restore ``engine`` from the newest VERIFIED checkpoint, counting a
    ``corrupt_checkpoints_skipped`` whenever verification fell back
    past a newer torn/corrupt snapshot. Returns the restored source
    position, or ``None`` for a cold restart (no usable checkpoint —
    the caller resets its committed output and replays from 0)."""
    from flink_tpu.checkpoint.storage import read_manifest

    newest = storage.latest_checkpoint_id()
    best = storage.latest_checkpoint_id(verify=True)
    if newest is not None and (best is None or best < newest):
        report.corrupt_checkpoints_skipped += 1
    if best is None:
        report.cold_restarts += 1
        return None
    # verify=False: latest_checkpoint_id just CRC-passed this id —
    # don't read it all twice
    states = storage.read_checkpoint(best, verify=False)
    engine.restore(states["engine"])
    manifest = read_manifest(os.path.join(ckpt_dir, f"chk-{best}"))
    report.restores += 1
    return int(manifest["extra"]["source_pos"])


def run_crash_restore_verify(
    make_engine: Callable[[], Any],
    make_oracle: Callable[[], Any],
    steps: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, int]],
    plan: FaultPlan,
    seed: int,
    ckpt_root: str,
    checkpoint_every: int = 2,
    job_name: str = "chaos-harness",
    max_crashes: int = 32,
    async_fires: bool = False,
    rel_tol: float = 1e-4,
    abs_tol: float = 1e-3,
    check: bool = True,
    rescales: Optional[Dict[int, int]] = None,
    rebalances: Optional[Dict[int, Any]] = None,
    metric_group=None,
) -> ChaosReport:
    """Run ``steps`` (list of ``(keys, values, timestamps, watermark)``)
    through a chaotic engine with periodic checkpoints and through a
    fault-free oracle; crash, restore, replay; diff the committed
    output. Raises :class:`ChaosDivergenceError` on any divergence
    (``check=False`` returns the report instead — for tests that PROVE
    the harness catches genuinely lossy faults).

    ``rescales``: {step position -> shard count} — before processing
    that step, the engine LIVE-migrates its key groups
    (``engine.reshard``), proving a mid-stream rescale (optionally
    crashed by a ``rescale.handoff`` fault) stays oracle-identical.
    After a crash-restore, the replayed engine reshards again when it
    re-reaches a scheduled position (the shard count is an
    implementation detail — output equivalence is what the diff pins);
    a position already past the restored source position simply stays
    at the restored engine's default mesh size.

    ``rebalances``: {step position -> key-group assignment, or a
    callable ``engine -> assignment``} — before processing that step,
    the engine live-MOVES key groups between shards at unchanged P
    (``engine.reassign_key_groups``, optionally crashed by a
    ``rebalance.handoff`` fault). The assignment is runtime routing
    state, not checkpointed: a restored engine comes back contiguous
    and re-applies the move when replay re-reaches the position —
    output equivalence is what the diff pins, whichever layout a row
    was fired from."""
    from flink_tpu.checkpoint.storage import CheckpointStorage

    if chaos.armed():
        raise RuntimeError(
            "run_crash_restore_verify arms its own controller — disarm "
            "the ambient one first (the oracle must run fault-free)")

    report = ChaosReport()
    report.events = int(sum(len(s[0]) for s in steps))
    if metric_group is not None:
        report.register_metrics(metric_group)

    # ---- fault-free oracle (single device, unbounded state) ----
    expected: Dict[_WindowKey, Dict[str, float]] = {}
    oracle = make_oracle()
    for keys, vals, ts, wm in steps:
        oracle.process_batch(_keyed_batch(keys, vals, ts))
        _collect(oracle.on_watermark(int(wm)), expected)
    _collect(oracle.on_watermark(FINAL_WATERMARK), expected)

    # ---- chaotic run: process / checkpoint / crash / restore ----
    storage = CheckpointStorage(ckpt_root)
    committed: Dict[_WindowKey, Dict[str, float]] = {}
    epoch: Dict[_WindowKey, Dict[str, float]] = {}
    n_steps = len(steps)
    with chaos.chaos_active(plan, seed) as ctl:
        engine = make_engine()
        pos = 0
        cid = 0
        need_restore = False
        while pos <= n_steps:
            try:
                if need_restore:
                    # a crash here (e.g. an injected checkpoint.read
                    # fault) loops back through the except arm again
                    engine = make_engine()
                    restored = _restore_latest(storage, ckpt_root,
                                               engine, report)
                    if restored is None:
                        committed = {}
                        pos = 0
                    else:
                        pos = restored
                    need_restore = False
                    continue
                if rescales and pos in rescales and \
                        int(getattr(engine, "P", 0)) != rescales[pos]:
                    engine.reshard(rescales[pos])
                    report.live_handoffs += 1
                if rebalances and pos in rebalances:
                    target = rebalances[pos]
                    if callable(target):
                        target = target(engine)
                    rep = engine.reassign_key_groups(target)
                    if rep.get("groups_moved", 0):
                        report.live_handoffs += 1
                if pos == n_steps:
                    # end of input: flush every remaining window
                    _collect(engine.on_watermark(
                        FINAL_WATERMARK,
                        **({"async_ok": True} if async_fires else {})),
                        epoch)
                else:
                    keys, vals, ts, wm = steps[pos]
                    engine.process_batch(_keyed_batch(keys, vals, ts))
                    _collect(engine.on_watermark(
                        int(wm),
                        **({"async_ok": True} if async_fires else {})),
                        epoch)
                next_pos = pos + 1
                if next_pos % checkpoint_every == 0 or next_pos > n_steps:
                    cid += 1
                    storage.write_checkpoint(
                        cid, job_name, {"engine": engine.snapshot()},
                        extra={"source_pos": next_pos})
                    report.checkpoints_written += 1
                    # checkpoint complete => the epoch's output commits
                    # (two-phase: pre-commit buffered, commit on ack)
                    committed.update(epoch)
                    epoch = {}
                pos = next_pos
            except InjectedFault:
                report.crashes += 1
                if report.crashes > max_crashes:
                    raise
                # KILL: discard the engine and all uncommitted output
                epoch = {}
                need_restore = True

        report.faults_injected = dict(ctl.faults_injected)
        report.points_hit = dict(ctl.points_hit)
        report.retries = ctl.retries
        report.recoveries = ctl.recoveries

    report.windows = len(committed)
    report.divergences = _diff(expected, committed, rel_tol, abs_tol)
    if check and report.divergences:
        raise ChaosDivergenceError(
            f"crash-restore output diverged from the fault-free oracle "
            f"({len(report.divergences)} differences):\n  "
            + "\n  ".join(report.divergences))
    return report


def run_crash_restore_verify_multi(
    make_engines: Dict[str, Callable[[], Any]],
    make_oracles: Dict[str, Callable[[], Any]],
    steps_by_job: Dict[str, Sequence[Tuple[np.ndarray, np.ndarray,
                                           np.ndarray, int]]],
    plan: FaultPlan,
    seed: int,
    ckpt_root: str,
    checkpoint_every: int = 2,
    max_crashes: int = 32,
    serve_keys: Optional[Dict[str, Sequence[int]]] = None,
    rel_tol: float = 1e-4,
    abs_tol: float = 1e-3,
    check: bool = True,
) -> Dict[str, ChaosReport]:
    """Multi-tenant form of :func:`run_crash_restore_verify`: N jobs'
    engines share the process (and the mesh, and the compiled-program
    cache) and advance INTERLEAVED, one step per job per round — the
    tenancy session cluster's schedule, collapsed to its essence. Each
    job checkpoints into its own subdirectory of ``ckpt_root``; a crash
    in one job's step kills and restores THAT job only, its siblings'
    engines untouched (independent failure domains — the claim under
    test). Each job's committed output is diffed against its own
    fault-free oracle.

    ``serve_keys``: {job -> key ids} — after every step the job serves a
    batched queryable-state lookup (``engine.query_batch``) through the
    ``serving.lookup`` fault point with the site-local retry wrapper, so
    a plan can (a) inject transient serving faults that must retry
    without corrupting engine state, and (b) crash a job MID-SERVING-
    BURST and still restore oracle-identical."""
    from flink_tpu.checkpoint.storage import CheckpointStorage

    if chaos.armed():
        raise RuntimeError(
            "run_crash_restore_verify_multi arms its own controller — "
            "disarm the ambient one first (oracles must run fault-free)")
    jobs = list(make_engines)
    reports = {j: ChaosReport() for j in jobs}
    expected: Dict[str, Dict[_WindowKey, Dict[str, float]]] = {}
    for j in jobs:
        reports[j].events = int(sum(len(s[0]) for s in steps_by_job[j]))
        exp: Dict[_WindowKey, Dict[str, float]] = {}
        oracle = make_oracles[j]()
        for keys, vals, ts, wm in steps_by_job[j]:
            oracle.process_batch(_keyed_batch(keys, vals, ts))
            _collect(oracle.on_watermark(int(wm)), exp)
        _collect(oracle.on_watermark(FINAL_WATERMARK), exp)
        expected[j] = exp

    storages = {j: CheckpointStorage(os.path.join(ckpt_root, j))
                for j in jobs}
    committed: Dict[str, Dict[_WindowKey, Dict[str, float]]] = {
        j: {} for j in jobs}
    epoch: Dict[str, Dict[_WindowKey, Dict[str, float]]] = {
        j: {} for j in jobs}
    state = {j: {"pos": 0, "cid": 0, "restore": False, "done": False}
             for j in jobs}

    def _serve(job: str, engine) -> None:
        if not serve_keys or job not in serve_keys:
            return

        def _lookup():
            chaos.fault_point("serving.lookup", job=job,
                              keys=len(serve_keys[job]))
            return engine.query_batch(
                np.asarray(serve_keys[job], dtype=np.int64))

        chaos.run_recoverable("serving.lookup", _lookup)

    #: per-job deltas of the controller-global counters, taken around
    #: each job's step — a plan targeting one tenant must show up in
    #: THAT job's report only
    job_faults: Dict[str, Dict[str, int]] = {j: {} for j in jobs}
    job_hits: Dict[str, Dict[str, int]] = {j: {} for j in jobs}
    job_retries = {j: 0 for j in jobs}
    job_recoveries = {j: 0 for j in jobs}
    with chaos.chaos_active(plan, seed) as ctl:
        engines = {j: make_engines[j]() for j in jobs}
        while not all(state[j]["done"] for j in jobs):
            for j in jobs:
                st = state[j]
                if st["done"]:
                    continue
                steps = steps_by_job[j]
                n_steps = len(steps)
                storage = storages[j]
                pre_faults = dict(ctl.faults_injected)
                pre_hits = dict(ctl.points_hit)
                pre_retries, pre_recoveries = ctl.retries, ctl.recoveries
                try:
                    if st["restore"]:
                        engines[j] = make_engines[j]()
                        restored = _restore_latest(
                            storage, os.path.join(ckpt_root, j),
                            engines[j], reports[j])
                        if restored is None:
                            committed[j] = {}
                            st["pos"] = 0
                        else:
                            st["pos"] = restored
                        st["restore"] = False
                        continue
                    pos = st["pos"]
                    if pos == n_steps:
                        _collect(engines[j].on_watermark(FINAL_WATERMARK),
                                 epoch[j])
                    else:
                        keys, vals, ts, wm = steps[pos]
                        engines[j].process_batch(
                            _keyed_batch(keys, vals, ts))
                        _collect(engines[j].on_watermark(int(wm)),
                                 epoch[j])
                    _serve(j, engines[j])
                    next_pos = pos + 1
                    if next_pos % checkpoint_every == 0 \
                            or next_pos > n_steps:
                        st["cid"] += 1
                        storage.write_checkpoint(
                            st["cid"], j,
                            {"engine": engines[j].snapshot()},
                            extra={"source_pos": next_pos})
                        reports[j].checkpoints_written += 1
                        committed[j].update(epoch[j])
                        epoch[j] = {}
                    st["pos"] = next_pos
                    if next_pos > n_steps:
                        st["done"] = True
                except InjectedFault:
                    reports[j].crashes += 1
                    if reports[j].crashes > max_crashes:
                        raise
                    epoch[j] = {}
                    st["restore"] = True
                finally:
                    for point, count in ctl.faults_injected.items():
                        d = count - pre_faults.get(point, 0)
                        if d:
                            job_faults[j][point] = \
                                job_faults[j].get(point, 0) + d
                    # points_hit attributed per job like the fault
                    # counters — a global copy claimed other tenants'
                    # hits in every report
                    for point, count in ctl.points_hit.items():
                        d = count - pre_hits.get(point, 0)
                        if d:
                            job_hits[j][point] = \
                                job_hits[j].get(point, 0) + d
                    job_retries[j] += ctl.retries - pre_retries
                    job_recoveries[j] += ctl.recoveries - pre_recoveries
        for j in jobs:
            reports[j].faults_injected = job_faults[j]
            reports[j].points_hit = job_hits[j]
            reports[j].retries = job_retries[j]
            reports[j].recoveries = job_recoveries[j]

    for j in jobs:
        reports[j].windows = len(committed[j])
        reports[j].divergences = _diff(expected[j], committed[j],
                                       rel_tol, abs_tol)
        if check and reports[j].divergences:
            raise ChaosDivergenceError(
                f"job {j!r}: crash-restore output diverged from its "
                f"fault-free oracle ({len(reports[j].divergences)} "
                "differences):\n  "
                + "\n  ".join(reports[j].divergences))
    return reports


def run_shard_loss_verify(
    make_engine: Callable[[], Any],
    make_oracle: Callable[[], Any],
    steps: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, int]],
    plan: FaultPlan,
    seed: int,
    ckpt_root: str,
    checkpoint_every: int = 2,
    job_name: str = "shard-loss-harness",
    max_shard_losses: int = 4,
    max_crashes: int = 8,
    watchdog_deadline_ms: float = 0.0,
    watchdog_max_misses: int = 3,
    rel_tol: float = 1e-4,
    abs_tol: float = 1e-3,
    check: bool = True,
    metric_group=None,
) -> ChaosReport:
    """Partial-failover form of :func:`run_crash_restore_verify`: the
    unit of failure and recovery is the SHARD (key-group range), not
    the job.

    A :class:`~flink_tpu.runtime.watchdog.DeviceWatchdog` wraps the
    engine's device interactions; a chaos-injected ``device.lost``
    fault (or an escalated ``watchdog.deadline`` miss streak) declares
    one shard dead at a batch boundary. Recovery then

    1. evacuates the SURVIVORS' live rows and rebuilds the mesh over
       the remaining devices (``engine.lose_shard`` — the reshard
       machinery, dirtiness and recency intact),
    2. restores ONLY the dead shard's key groups from their newest
       verified checkpoint unit (``ShardedCheckpointStorage`` — a torn
       unit falls back to that RANGE's unit in an older checkpoint,
       never discarding the whole chk-N), and
    3. replays ONLY that range's records from the unit's source
       position (``records_replayed`` counts them — the bounded-replay
       claim: about ``events/shards`` per loss, not the whole stream).

    Checkpoints are written SHARD-GRANULAR (``engine.snapshot_sharded``
    keyed by key-group range, per-unit source positions in the
    manifest). A non-shard crash (any other injected fault) takes the
    whole-job path: a fresh engine restores ALL units with per-unit
    fallback; ranges whose unit fell back to an older checkpoint are
    GATED during the catch-up replay so ranges already ahead never
    re-absorb older records.

    Committed output must be bit-identical (within float tolerance) to
    the fault-free single-device oracle, and the whole run is
    reproducible from (plan, seed).
    """
    from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage
    from flink_tpu.runtime.watchdog import (
        DeviceWatchdog,
        MeshStalledError,
        ShardFailedError,
    )
    from flink_tpu.state.keygroups import assign_key_groups

    if chaos.armed():
        raise RuntimeError(
            "run_shard_loss_verify arms its own controller — disarm "
            "the ambient one first (the oracle must run fault-free)")

    report = ChaosReport()
    report.events = int(sum(len(s[0]) for s in steps))
    if metric_group is not None:
        report.register_metrics(metric_group)

    # ---- fault-free oracle (single device, unbounded state) ----
    expected: Dict[_WindowKey, Dict[str, float]] = {}
    oracle = make_oracle()
    for keys, vals, ts, wm in steps:
        oracle.process_batch(_keyed_batch(keys, vals, ts))
        _collect(oracle.on_watermark(int(wm)), expected)
    _collect(oracle.on_watermark(FINAL_WATERMARK), expected)

    # ---- chaotic run ----
    storage = ShardedCheckpointStorage(ckpt_root)
    committed: Dict[_WindowKey, Dict[str, float]] = {}
    epoch: Dict[_WindowKey, Dict[str, float]] = {}
    n_steps = len(steps)

    def _attach(engine):
        wd = DeviceWatchdog(engine.P,
                            deadline_ms=watchdog_deadline_ms,
                            max_misses=watchdog_max_misses)
        engine.attach_watchdog(wd)
        return engine

    def _range_mask(keys, g0: int, g1: int) -> np.ndarray:
        kg = assign_key_groups(np.asarray(keys, dtype=np.int64),
                               engine.max_parallelism)
        return (kg >= g0) & (kg <= g1)

    with chaos.chaos_active(plan, seed) as ctl:
        engine = _attach(make_engine())
        pos = 0
        cid = 0
        phase = 0             # 0 = batch pending, 1 = watermark pending
        need_restore = False
        pending_loss: Optional[Tuple[tuple, int]] = None  # (shards, phase)
        #: (g0, g1, pos_r): range already absorbed up to pos_r — skip
        #: its records while pos < pos_r (mixed-age unit restore)
        gates: List[Tuple[int, int, int]] = []
        while pos <= n_steps:
            try:
                if need_restore:
                    engine = _attach(make_engine())
                    found = storage.read_all_units_with_fallback()
                    if found is None:
                        report.cold_restarts += 1
                        committed, epoch = {}, {}
                        pos, phase, gates = 0, 0, []
                        need_restore = False
                        continue
                    newest, units, skipped = found
                    report.corrupt_checkpoints_skipped += skipped
                    states = [state for _, state, _ in units]
                    if len(units) < len(storage.unit_ranges(newest)):
                        # a range with NO restorable unit replays cold
                        # from 0: its staleness guards must roll all
                        # the way back (empty pseudo-unit => the merge
                        # takes the -inf defaults)
                        states = states + [{}]
                    engine.restore(engine.merge_unit_snapshots(states))
                    report.restores += 1
                    positions = {r: p for r, _, p in units}
                    pos = min(positions.values()) \
                        if len(units) == len(
                            storage.unit_ranges(newest)) else 0
                    gates = [(r[0], r[1], p)
                             for r, p in positions.items() if p > pos]
                    phase = 0
                    need_restore = False
                    continue
                if pending_loss is not None:
                    dead_shards, at_phase = pending_loss
                    t0 = time.perf_counter()
                    replayed_before = report.records_replayed
                    # the restore/replay duration is a span in the
                    # default TraceCollector AND the flight recorder's
                    # timeline (the same reporting the executor does
                    # for checkpoints); a failure mid-recovery records
                    # the span with its error instead of leaking it
                    with default_collector().span(
                            "recovery", "shard-failover") as fo_span, \
                            flight.span("failover.replay",
                                        shard=int(dead_shards[0])):
                        # a HostFailedError carries the whole host's
                        # contiguous slice: one evacuation, k units
                        g0, g1 = engine.lose_shards(list(dead_shards))
                        groups = range(g0, g1 + 1)
                        # gates SPLIT around the dead range: the
                        # overlap is being rebuilt from its unit (its
                        # gate is moot), but a partially-overlapping
                        # gate's OUTSIDE sub-ranges still hold state
                        # ahead of pos and must stay gated or they
                        # would re-absorb records they already hold
                        split: List[Tuple[int, int, int]] = []
                        for a, b, p_r in gates:
                            if b < g0 or a > g1:
                                split.append((a, b, p_r))
                                continue
                            if a < g0:
                                split.append((a, g0 - 1, p_r))
                            if b > g1:
                                split.append((g1 + 1, b, p_r))
                        gates = split
                        found = storage.latest_units_for_groups(groups)
                        if found is None:
                            unit_pos = 0
                            # roll the range's staleness guards back to
                            # stream start (cold range replay)
                            engine.restore_key_groups({"table": {}},
                                                      groups)
                        else:
                            _ucid, states, unit_pos = found
                            engine.restore_key_groups(
                                engine.merge_unit_snapshots(states),
                                groups)
                            report.shard_restores += 1
                        # uncommitted output of the range is rolled
                        # back with its state; replay re-produces it
                        if epoch:
                            ekeys = np.asarray([k[0] for k in epoch],
                                               dtype=np.int64)
                            drop = _range_mask(ekeys, g0, g1)
                            epoch = {k: v for k, v, d in zip(
                                epoch, epoch.values(), drop) if not d}
                        # bounded replay: ONLY the range's records,
                        # from the unit's position; the step being
                        # interrupted mid-watermark (at_phase=1)
                        # already absorbed pos's batch on the
                        # survivors, so the range re-absorbs through
                        # pos INCLUSIVE and the main flow refires pos's
                        # watermark for everyone. The replay is a
                        # CRITICAL SECTION: the watchdog detaches for
                        # it — a second loss declared mid-replay would
                        # abandon this range's partially-completed
                        # rebuild; a genuinely dead second device is
                        # declared at the next main-loop boundary
                        # instead
                        wd_held = engine._watchdog
                        engine.attach_watchdog(None)
                        try:
                            upto = pos + (1 if at_phase == 1 else 0)
                            for rpos in range(unit_pos,
                                              min(upto, n_steps)):
                                keys, vals, ts, _wm = steps[rpos]
                                mask = _range_mask(keys, g0, g1)
                                if mask.any():
                                    engine.process_batch(_keyed_batch(
                                        keys[mask], vals[mask],
                                        ts[mask]))
                                    report.records_replayed += int(
                                        mask.sum())
                                if rpos < pos:
                                    _collect(engine.on_watermark(
                                        int(steps[rpos][3])), epoch)
                        finally:
                            engine._watchdog = wd_held
                        fo_span.set_attribute(
                            "shard", int(dead_shards[0]))
                        if len(dead_shards) > 1:
                            fo_span.set_attribute(
                                "shards", [int(s) for s in dead_shards])
                        fo_span.set_attribute("key_groups", [g0, g1])
                        fo_span.set_attribute(
                            "records_replayed",
                            report.records_replayed - replayed_before)
                    report.shard_loss_recovery_ms += (
                        time.perf_counter() - t0) * 1000.0
                    pending_loss = None
                    continue
                if phase == 0:
                    # gate expiry first (also at the final-flush step,
                    # where no batch runs — a stuck gate would defer
                    # the final checkpoint and lose the last epoch)
                    if gates:
                        gates = [g for g in gates if pos < g[2]]
                    if pos < n_steps:
                        keys, vals, ts, _wm = steps[pos]
                        if gates:
                            kg = assign_key_groups(
                                np.asarray(keys, dtype=np.int64),
                                engine.max_parallelism)
                            allow = np.ones(len(keys), dtype=bool)
                            for a, b, p_r in gates:
                                allow &= ~((kg >= a) & (kg <= b))
                            if allow.any():
                                engine.process_batch(_keyed_batch(
                                    keys[allow], vals[allow],
                                    ts[allow]))
                        else:
                            engine.process_batch(
                                _keyed_batch(keys, vals, ts))
                    phase = 1
                    continue
                # phase 1: watermark (FINAL flush at end of input)
                wm = FINAL_WATERMARK if pos == n_steps \
                    else int(steps[pos][3])
                _collect(engine.on_watermark(wm), epoch)
                next_pos = pos + 1
                # checkpoints are DEFERRED while replay gates are
                # active: a gated range's state is already ahead of
                # pos, so recording source_pos=next_pos for its unit
                # would make a later restore double-replay the records
                # it already absorbed (alignment returns within at most
                # checkpoint_every steps, so the deferral is bounded)
                if (next_pos % checkpoint_every == 0
                        or next_pos > n_steps) and not gates:
                    cid += 1
                    units = engine.snapshot_sharded()
                    storage.write_checkpoint(
                        cid, job_name, units,
                        positions={r: next_pos for r in units})
                    report.checkpoints_written += 1
                    committed.update(epoch)
                    epoch = {}
                pos = next_pos
                phase = 0
            except ShardFailedError as sf:
                # a HostFailedError carries the host's whole slice —
                # every member shard counts toward the loss budget
                # (type check, not length: a 1-device-per-host pod
                # loses exactly one shard per host)
                from flink_tpu.runtime.watchdog import HostFailedError

                shards = tuple(getattr(sf, "shards", ()) or (sf.shard,))
                report.shards_lost += len(shards)
                if isinstance(sf, HostFailedError):
                    report.hosts_lost += 1
                if report.shards_lost > max_shard_losses:
                    raise
                pending_loss = (shards, phase)
            except (InjectedFault, MeshStalledError):
                # an unattributable mesh-wide stall takes the same
                # whole-job path a crash does (see MeshStalledError)
                report.crashes += 1
                if report.crashes > max_crashes:
                    raise
                epoch = {}
                pending_loss = None
                need_restore = True

        report.faults_injected = dict(ctl.faults_injected)
        report.points_hit = dict(ctl.points_hit)
        report.retries = ctl.retries
        report.recoveries = ctl.recoveries

    report.windows = len(committed)
    report.divergences = _diff(expected, committed, rel_tol, abs_tol)
    if check and report.divergences:
        raise ChaosDivergenceError(
            f"shard-loss output diverged from the fault-free oracle "
            f"({len(report.divergences)} differences):\n  "
            + "\n  ".join(report.divergences))
    return report
