"""Deterministic, seeded fault injection.

Design (reference: the e2e fault-injection scripts the reference drives
its recovery ITCases with, plus Jepsen/ChaosMonkey-style nemeses —
re-designed as an IN-PROCESS controller because the whole dataflow runs
in one process group here):

- Code under test declares **named fault points**:
  ``chaos.fault_point("shuffle.bucket_send", shard=p)``. With no
  controller armed the call is a no-op costing one module-global load
  and a ``None`` check — cheap enough for per-batch hot paths (the
  tier-1 bench gate pins the disarmed overhead).
- A :class:`FaultPlan` maps point-name PATTERNS (fnmatch) to seeded
  schedules and fault kinds. Any run is exactly reproducible from
  ``(plan, seed)``: nth-hit schedules count matching hits, and the
  probabilistic schedule draws from a per-rule PRNG seeded with
  ``(seed, crc32(pattern), rule_index)`` — never the global RNG, never
  wall-clock.
- Fault kinds: ``raise`` (an :class:`InjectedFault`, optionally
  ``recoverable`` for the retry wrapper), ``delay`` (sleep
  ``delay_ms``), and the payload kinds ``drop`` / ``duplicate`` /
  ``corrupt`` which the instrumented site itself applies (a shard
  bucket dropped, a checkpoint file torn or bit-flipped).
- Recoverable I/O sites (spill page reloads, checkpoint storage) wrap
  their attempt in :func:`run_recoverable`, which retries transient
  ``InjectedFault``s with an ``ExponentialDelayRestartStrategy``
  backoff (reusing ``cluster/restart_strategies``) and counts
  ``retries`` / ``recoveries``.
- ``faults_injected`` / ``retries`` / ``recoveries`` surface through
  the existing metric-group machinery via
  :func:`register_chaos_metrics`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import zlib
from fnmatch import fnmatchcase
from typing import Any, Callable, Dict, List, Optional, Tuple, TypeVar

import numpy as np

T = TypeVar("T")

#: fault kinds a plain (non-payload) fault point honors
POINT_KINDS = ("raise", "delay")
#: additional kinds only a payload-carrying site can apply
PAYLOAD_KINDS = ("drop", "duplicate", "corrupt")
FAULT_KINDS = POINT_KINDS + PAYLOAD_KINDS


class InjectedFault(RuntimeError):
    """A fault raised by the chaos controller at a named fault point.

    ``recoverable`` marks transient faults the site-local retry wrapper
    (:func:`run_recoverable`) may absorb; everything else propagates as
    a process/task crash for the failover layers (restart strategies,
    the chaos harness) to handle.
    """

    def __init__(self, point: str, rule: "FaultRule",
                 recoverable: bool = False) -> None:
        super().__init__(
            f"injected fault at {point!r} (rule {rule.pattern!r}"
            f"{', recoverable' if recoverable else ''})")
        self.point = point
        self.rule = rule
        self.recoverable = recoverable


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One pattern -> schedule -> fault-kind mapping.

    Schedule semantics (hits are counted per rule, over the calls whose
    point name matches ``pattern`` AND whose context matches ``where``):

    - ``nth``   inject on exactly the nth matching hit (1-based)
    - ``every`` inject on every ``every``-th matching hit
    - ``prob``  inject each hit with this probability (per-rule PRNG)

    ``max_injections`` bounds total injections (default 1 — the "once"
    schedule; 0 = unlimited). ``where`` filters on fault-point context,
    e.g. ``{"shard": 3}`` pins a rule to one shard's calls.
    """

    pattern: str
    kind: str = "raise"
    nth: int = 0
    every: int = 0
    prob: float = 0.0
    max_injections: int = 1
    delay_ms: float = 0.0
    recoverable: bool = False
    where: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}")
        if not (self.nth or self.every or self.prob):
            raise ValueError(
                f"rule {self.pattern!r} has no schedule: set nth, every "
                "or prob")
        if isinstance(self.where, dict):  # ergonomic: accept a dict
            object.__setattr__(self, "where", tuple(sorted(
                self.where.items())))


@dataclasses.dataclass
class FaultPlan:
    """An ordered rule list plus the retry policy for recoverable sites.

    The FIRST matching rule that triggers wins a given hit. Retry
    backoff defaults keep tests fast (sub-millisecond waits) while
    still exercising the exponential-delay strategy for real.
    """

    rules: List[FaultRule] = dataclasses.field(default_factory=list)
    retry_max_attempts: int = 4
    retry_initial_ms: int = 0
    retry_max_ms: int = 8
    #: PROCESS-LEVEL retry budget across ALL recoverable sites (0 =
    #: unlimited): per-site backoff bounds one site's attempts, but a
    #: permanently failing tier that keeps "recovering" elsewhere would
    #: otherwise retry forever. When the global budget is spent, the
    #: next recoverable fault ESCALATES to a real (non-recoverable)
    #: failure — the same declare-dead discipline the device watchdog
    #: applies to persistently slow shards, extended to soft faults.
    retry_budget_total: int = 0

    @staticmethod
    def from_spec(spec) -> "FaultPlan":
        """Build from a list of dicts (the JSON/CLI-friendly form):
        ``[{"pattern": "spill.page_reload", "nth": 3,
        "kind": "raise", "recoverable": True}, ...]``."""
        return FaultPlan(rules=[FaultRule(**r) for r in spec])

    def describe(self) -> List[str]:
        out = []
        for r in self.rules:
            sched = (f"nth={r.nth}" if r.nth else
                     f"every={r.every}" if r.every else f"prob={r.prob}")
            out.append(f"{r.pattern} -> {r.kind} ({sched}, "
                       f"max={r.max_injections or 'inf'})")
        return out


class ChaosController:
    """Process-global fault decision engine (see module docstring).

    The controller survives engine kill/rebuild cycles within one armed
    session, so hit counters and ``faults_injected`` accumulate across
    crash-restore rounds — exactly what makes an nth-hit crash fire
    once per run instead of once per engine incarnation.
    """

    def __init__(self, plan: FaultPlan, seed: int) -> None:
        self.plan = plan
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._hits = [0] * len(plan.rules)
        self._injections = [0] * len(plan.rules)
        self._rngs = [
            np.random.default_rng(
                [self.seed, zlib.crc32(r.pattern.encode()), i])
            for i, r in enumerate(plan.rules)
        ]
        #: point name -> number of faults actually injected there
        self.faults_injected: Dict[str, int] = {}
        #: hits observed per point name (armed only; for reachability
        #: assertions and plan debugging)
        self.points_hit: Dict[str, int] = {}
        self.retries = 0
        self.recoveries = 0
        #: recoverable faults escalated to real failures because the
        #: process-level retry budget was exhausted
        self.budget_exhausted = 0

    def consume_retry_budget(self) -> bool:
        """Account one retry against the process-level budget; False
        means the budget is spent and the fault must escalate."""
        with self._lock:
            total = self.plan.retry_budget_total
            if total and self.retries >= total:
                self.budget_exhausted += 1
                return False
            self.retries += 1
            return True

    # ------------------------------------------------------------- decisions

    def _decide(self, point: str, ctx: Dict[str, Any],
                kinds: Tuple[str, ...]) -> Optional[FaultRule]:
        with self._lock:
            self.points_hit[point] = self.points_hit.get(point, 0) + 1
            for i, rule in enumerate(self.plan.rules):
                if rule.kind not in kinds:
                    continue
                if not fnmatchcase(point, rule.pattern):
                    continue
                if rule.where and any(
                        ctx.get(k) != v for k, v in rule.where):
                    continue
                self._hits[i] += 1
                h = self._hits[i]
                if rule.max_injections and \
                        self._injections[i] >= rule.max_injections:
                    continue
                fire = bool(
                    (rule.nth and h == rule.nth)
                    or (rule.every and h % rule.every == 0)
                    or (rule.prob
                        and self._rngs[i].random() < rule.prob))
                if fire:
                    self._injections[i] += 1
                    self.faults_injected[point] = \
                        self.faults_injected.get(point, 0) + 1
                    # correlate the injection into the flight-recorder
                    # timeline: a chaos-driven stall/crash reads as
                    # "injected HERE, under THIS span" in the trace
                    from flink_tpu.observe import flight_recorder as flight

                    flight.instant(
                        "chaos.inject",
                        shard=int(ctx.get("shard", -1))
                        if isinstance(ctx.get("shard"), int) else -1)
                    return rule
            return None

    def _apply_point(self, point: str, ctx: Dict[str, Any]) -> None:
        rule = self._decide(point, ctx, POINT_KINDS)
        if rule is None:
            return
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return
        raise InjectedFault(point, rule, recoverable=rule.recoverable)

    def _apply_payload(self, point: str, ctx: Dict[str, Any],
                       kinds: Tuple[str, ...]) -> Optional[FaultRule]:
        rule = self._decide(point, ctx, kinds)
        if rule is None:
            return None
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return None
        if rule.kind == "raise":
            raise InjectedFault(point, rule, recoverable=rule.recoverable)
        return rule  # drop / duplicate / corrupt: the site applies it

    def note_recovery(self) -> None:
        """Count a site-local recovery (a fault absorbed without
        retrying, e.g. a safely-skipped compaction)."""
        with self._lock:
            self.recoveries += 1

    def make_retry_strategy(self):
        from flink_tpu.cluster.restart_strategies import (
            ExponentialDelayRestartStrategy,
        )

        return ExponentialDelayRestartStrategy(
            initial_ms=self.plan.retry_initial_ms,
            max_ms=self.plan.retry_max_ms,
            max_attempts=self.plan.retry_max_attempts)

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "faults_injected": dict(self.faults_injected),
                "faults_injected_total":
                    sum(self.faults_injected.values()),
                "retries": self.retries,
                "recoveries": self.recoveries,
                "retry_budget_exhausted": self.budget_exhausted,
            }


#: THE process-global controller slot. None = disarmed; every fault
#: point is then one load + one is-None check.
_controller: Optional[ChaosController] = None


def armed() -> bool:
    return _controller is not None


def controller() -> Optional[ChaosController]:
    return _controller


def arm(plan: FaultPlan, seed: int) -> ChaosController:
    global _controller
    if _controller is not None:
        raise RuntimeError("chaos controller already armed — disarm() "
                           "first (plans do not stack)")
    _controller = ChaosController(plan, seed)
    return _controller


def disarm() -> Optional[ChaosController]:
    """Disarm and return the controller (its counters stay readable)."""
    global _controller
    c = _controller
    _controller = None
    return c


@contextlib.contextmanager
def chaos_active(plan: FaultPlan, seed: int):
    c = arm(plan, seed)
    try:
        yield c
    finally:
        disarm()


# --------------------------------------------------------------- fault APIs


def fault_point(point: str, **ctx) -> None:
    """Declare a named fault point: may raise InjectedFault or sleep.

    No-op when disarmed. ``ctx`` kwargs (e.g. ``shard=3``) are matched
    against rules' ``where`` filters."""
    c = _controller
    if c is None:
        return
    c._apply_point(point, ctx)


def payload_action(point: str, kinds: Tuple[str, ...] = FAULT_KINDS,
                   **ctx) -> Optional[FaultRule]:
    """A fault point whose site carries a payload it can drop,
    duplicate or corrupt: returns the triggered drop/duplicate/corrupt
    rule for the CALLER to apply, after handling raise/delay kinds
    itself. ``kinds`` restricts which fault kinds the site supports —
    e.g. a post-rename tear point only accepts ("drop", "corrupt"),
    because raising there would model a failure that never existed
    (the checkpoint IS durable). Returns None when disarmed or nothing
    triggered."""
    c = _controller
    if c is None:
        return None
    return c._apply_payload(point, ctx, kinds)


class RetryBudgetExhaustedError(RuntimeError):
    """The process-level retry budget is spent: a recoverable fault
    escalated to a real failure (permanent soft fault — e.g. a spill
    tier that never stops failing). Carries the original fault."""

    def __init__(self, point: str, fault: InjectedFault) -> None:
        super().__init__(
            f"global retry budget exhausted at {point!r}: recoverable "
            f"fault escalated to a real failure ({fault})")
        self.point = point
        self.fault = fault


def run_recoverable(point: str, fn: Callable[[], T]) -> T:
    """Run ``fn``, retrying transient (``recoverable``) InjectedFaults
    with restart-strategy backoff; counts retries and (on eventual
    success) recoveries. Non-recoverable faults and exhausted per-site
    budgets propagate — they are the crash path. The PROCESS-LEVEL
    budget (``FaultPlan.retry_budget_total``) bounds total retries
    across all sites: once spent, the next recoverable fault escalates
    as :class:`RetryBudgetExhaustedError` instead of retrying forever
    (counted in ``retry_budget_exhausted`` on the ``chaos`` metric
    group)."""
    c = _controller
    if c is None:
        return fn()
    strategy = c.make_retry_strategy()
    retried = False
    while True:
        try:
            out = fn()
            if retried:
                with c._lock:
                    c.recoveries += 1
            return out
        except InjectedFault as f:
            if not f.recoverable:
                raise
            strategy.notify_failure()
            if not strategy.can_restart():
                raise
            if not c.consume_retry_budget():
                raise RetryBudgetExhaustedError(point, f) from f
            retried = True
            backoff = strategy.backoff_ms()
            if backoff:
                time.sleep(backoff / 1000.0)


def io_point(point: str, **ctx) -> None:
    """A recoverable-I/O fault point: transient injected failures retry
    with backoff in place (the storage/spill contract); persistent ones
    raise. No-op when disarmed."""
    c = _controller
    if c is None:
        return
    run_recoverable(point, lambda: fault_point(point, **ctx))


def register_chaos_metrics(group) -> None:
    """Register the armed controller's counters as gauges on an
    existing MetricGroup (job -> chaos scope). Values are read live at
    report time, so gauges registered at job start see every later
    injection. No-op when disarmed."""
    c = _controller
    if c is None:
        return
    g = group.add_group("chaos")
    g.gauge("faults_injected",
            lambda c=c: sum(c.faults_injected.values()))
    g.gauge("retries", lambda c=c: c.retries)
    g.gauge("recoveries", lambda c=c: c.recoveries)
    g.gauge("points_hit", lambda c=c: sum(c.points_hit.values()))
    g.gauge("retry_budget_exhausted", lambda c=c: c.budget_exhausted)
