"""gRPC shuffle transport — record batches between task executors.

reference: the NettyShuffleEnvironment role (io/network/NettyShuffleEnvironment
.java): the default transport moving serialized buffers between TaskManagers,
with credit-based flow control (RemoteInputChannel.java:114,374). Here the
wire unit is a columnar RecordBatch (cloudpickled column dict), the server is
the CONSUMER side (buffers live where they are polled, like the reference's
input gates), and backpressure is the bounded consumer queue: a producer's
push blocks server-side until the subpartition has room, which blocks the
producer's RPC — the same bounded-in-flight property credits give Netty,
traded for per-call latency.

Topology: every process hosts one ``ShuffleServerEndpoint`` on its RpcService.
Partitions are LOCATED AT THEIR CONSUMER: ``RpcShuffleService`` takes a
routing function (partition_id, subpartition) -> gRPC address (None = this
process). Writers route each emit; gates only ever poll local buffers. A
DCN/ICI transport slots in by registering another factory under
``shuffle.service`` — the execution layer never changes (ShuffleServiceFactory
pluggability).
"""

from __future__ import annotations

import queue as _q
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.cluster.rpc import RpcEndpoint, RpcService
from flink_tpu.runtime.shuffle_spi import (
    END_OF_PARTITION,
    Barrier,
    InputGate,
    LocalGate,
    LocalShuffleService,
    ResultPartitionWriter,
    ShuffleService,
    _LocalPartition,
)


def _encode(item) -> bytes:
    if isinstance(item, RecordBatch):
        # record batches are the bulk bytes: native framed codec
        # (compressed + CRC, no pickle on the decode fast path) when the
        # library is available (flink_tpu/native/codec.py; reference:
        # compiled fast coders + lz4 buffer compression)
        from flink_tpu.native.codec import codec_available, encode_batch

        if codec_available():
            return b"B" + encode_batch(item)
        return b"P" + cloudpickle.dumps(("batch", dict(item.columns)))
    if isinstance(item, Barrier):
        return b"P" + cloudpickle.dumps(
            ("barrier", (item.checkpoint_id, item.savepoint, item.stop)))
    if item is END_OF_PARTITION:
        return b"P" + cloudpickle.dumps(("eop", None))
    return b"P" + cloudpickle.dumps(("event", item))


def _decode(payload: bytes):
    tag, payload = payload[:1], memoryview(payload)[1:]
    if tag == b"B":
        from flink_tpu.native.codec import decode_batch

        return decode_batch(payload)
    kind, data = cloudpickle.loads(payload)
    if kind == "batch":
        return RecordBatch(data)
    if kind == "barrier":
        cid, sp, stop = data
        return Barrier(cid, savepoint=sp, stop=stop)
    if kind == "eop":
        return END_OF_PARTITION
    return data


class ShuffleServerEndpoint(RpcEndpoint):
    """Consumer-side buffer server: producers push items into the
    subpartition queues polled by this process's gates.

    ``push`` runs on the RPC worker thread pool, NOT the endpoint main
    thread — a blocked push (backpressure) must not stall control traffic.
    The queue's bound is the credit window.
    """

    def __init__(self, endpoint_id: str = "shuffle-server",
                 credits_per_channel: int = 2):
        super().__init__(endpoint_id)
        self.credits = credits_per_channel
        self._parts: Dict[str, _LocalPartition] = {}
        self._lock = threading.Lock()
        self._cancelled = threading.Event()

    def on_stop(self) -> None:
        # release any producer blocked on backpressure — a push stuck in
        # its credit wait would otherwise pin a gRPC worker thread past
        # server shutdown
        self._cancelled.set()

    def partition(self, partition_id: str, num_subpartitions: int,
                  credits: Optional[int] = None) -> _LocalPartition:
        with self._lock:
            part = self._parts.get(partition_id)
            if part is None:
                part = _LocalPartition(partition_id, num_subpartitions,
                                       credits or self.credits)
                self._parts[partition_id] = part
            else:
                part.ensure(num_subpartitions, credits)
            return part

    def cancel(self) -> None:
        self._cancelled.set()

    # -- remote methods (called via gateway) --------------------------------

    def push(self, partition_id: str, subpartition: int,
             payload: bytes, is_event: bool) -> bool:
        """Blocking enqueue — the producer's RPC completes only once the
        subpartition accepted the item (bounded queue = credit window)."""
        item = _decode(payload)
        part = self.partition(partition_id, subpartition + 1)
        part.subpartitions[subpartition].put(
            item, is_event=is_event, cancelled=self._cancelled.is_set)
        return True

    def _invoke(self, method, args, kwargs, expected_token=None):
        # data-plane pushes bypass the single main thread: they may block
        # on backpressure and MUST NOT serialize behind each other or
        # control traffic (the reference likewise keeps Netty I/O threads
        # apart from the actor main thread)
        if method == "push":
            return self.push(*args, **kwargs)
        return super()._invoke(method, args, kwargs, expected_token)


class _RemoteWriter(ResultPartitionWriter):
    """Producer-side writer routing each subpartition to its consumer."""

    def __init__(self, service: "RpcShuffleService", partition_id: str,
                 num_subpartitions: int):
        self.service = service
        self.partition_id = partition_id
        self.num_subpartitions = num_subpartitions

    def _push(self, subpartition: int, item, is_event: bool) -> None:
        addr = self.service.route(self.partition_id, subpartition)
        if addr is None:
            part = self.service.server.partition(self.partition_id,
                                                 self.num_subpartitions)
            part.subpartitions[subpartition].put(
                item, is_event=is_event,
                cancelled=self.service.server._cancelled.is_set)
            return
        gw = self.service._gateway(addr)
        gw.push(self.partition_id, subpartition, _encode(item), is_event)

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        self._push(subpartition, batch, is_event=False)

    def broadcast_event(self, event) -> None:
        for sub in range(self.num_subpartitions):
            self._push(sub, event, is_event=True)

    def close(self) -> None:
        self.broadcast_event(END_OF_PARTITION)


class RpcShuffleService(ShuffleService):
    """ShuffleService whose channels cross process boundaries over gRPC.

    ``route(partition_id, subpartition)`` returns the consumer's RPC
    address, or None when the consumer lives in this process (then the
    local buffer is used directly — no loopback socket hop)."""

    def __init__(self, rpc_service: RpcService,
                 route: Callable[[str, int], Optional[str]],
                 server: Optional[ShuffleServerEndpoint] = None,
                 credits_per_channel: int = 2):
        self.rpc = rpc_service
        self.route = route
        if server is None:
            # one shuffle server per RpcService: a second service on the
            # same process must SHARE the registered server's buffers
            existing = self.rpc._endpoints.get("shuffle-server")
            server = existing or ShuffleServerEndpoint(
                credits_per_channel=credits_per_channel)
        self.server = server
        if self.server.endpoint_id not in self.rpc._endpoints:
            self.rpc.register(self.server)  # register() starts the endpoint
        self._gateways: Dict[str, object] = {}
        self._gw_lock = threading.Lock()

    def _gateway(self, address: str):
        with self._gw_lock:
            gw = self._gateways.get(address)
            if gw is None:
                gw = self.rpc.connect(address, self.server.endpoint_id)
                self._gateways[address] = gw
            return gw

    def create_partition(self, partition_id: str, num_subpartitions: int,
                         credits_per_channel: int = 2
                         ) -> ResultPartitionWriter:
        """The credit window applies to LOCALLY consumed subpartitions;
        remotely consumed ones are bounded by the CONSUMER's server
        (receiver-side flow control, like the reference's receiver-granted
        credits)."""
        for sub in range(num_subpartitions):
            if self.route(partition_id, sub) is None:
                self.server.partition(partition_id, num_subpartitions,
                                      credits=credits_per_channel)
                break
        return _RemoteWriter(self, partition_id, num_subpartitions)

    def create_gate(self, partition_ids: Sequence[str], subpartition: int
                    ) -> InputGate:
        parts = [self.server.partition(pid, subpartition + 1)
                 for pid in partition_ids]
        return LocalGate(parts, subpartition)

    def cancel(self) -> None:
        self.server.cancel()

    def close(self) -> None:
        self.server.cancel()


def register_grpc_shuffle() -> None:
    """Register 'grpc' in the shuffle factory registry. The standalone
    factory builds a single-process loopback topology (every consumer
    local) — multi-process deployments construct RpcShuffleService with
    their cluster's RpcService + routing table instead."""
    from flink_tpu.runtime.shuffle_spi import register_shuffle_service

    def factory():
        rpc = RpcService()
        return RpcShuffleService(rpc, route=lambda pid, sub: None)

    register_shuffle_service("grpc", factory)


register_grpc_shuffle()
