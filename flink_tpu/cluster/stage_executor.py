"""Stage-parallel execution: ExecutionGraph-style subtask expansion.

reference: the reference expands every JobVertex into `parallelism`
ExecutionVertex subtasks (executiongraph/DefaultExecutionGraph.java,
Execution.java:572 deploy()), routes records between them by key group
(streaming/runtime/partitioner/KeyGroupStreamPartitioner.java:55), and
aligns checkpoint barriers across input channels
(streaming/runtime/io/checkpointing/SingleCheckpointBarrierHandler.java).

Re-design: the job splits into two pipelined stages —

  source stage (S subtasks): source + chained stateless operators;
    each output batch is partitioned by key group into one sub-batch per
    keyed subtask and emitted through the Shuffle SPI
    (flink_tpu/runtime/shuffle_spi.py — pluggable transport, credit-based
    flow control).
  keyed stage (N subtasks): the keyed operator chain + sink; each subtask
    owns a key-group range and runs its own single-device engine instance.
    Watermarks combine per-channel (min across channels, the
    StatusWatermarkValve role); checkpoint Barriers ALIGN: channels that
    delivered the barrier are buffered until all channels have, then the
    subtask snapshots and acks (exactly the reference's aligned barrier
    dance — the in-flight buffer is bounded by the channel credit).

Checkpoints: a coordinator (the run() thread) triggers sources, collects
S + N acks, MERGES the per-subtask operator states into the same logical
format the single-slot executor writes (key-group-indexed rows), and
commits the manifest — so multi-slot checkpoints restore into single-slot
jobs, other subtask counts (key-group re-filtering), and vice versa.

This axis is COMPLEMENTARY to mesh parallelism: a keyed subtask could open
its operator over a device mesh; subtask expansion distributes across
slots/hosts (the reference's distribution model), the mesh distributes
across chips within one program (the SPMD model).
"""

from __future__ import annotations

import queue as _q
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.config import (
    BatchOptions,
    CheckpointOptions,
    Configuration,
    CoreOptions,
    DeploymentOptions,
    StateOptions,
)
from flink_tpu.chaos import injection as chaos
from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import StreamGraph, Transformation
from flink_tpu.runtime.operators import OperatorContext
from flink_tpu.runtime.shuffle_spi import (
    END_OF_PARTITION,
    Barrier,
    LocalShuffleService,
    create_shuffle_service,
)
from flink_tpu.runtime.elements import MAX_WATERMARK
from flink_tpu.state.keygroups import (
    assign_key_groups,
    compute_key_group_range,
    key_group_to_operator_index,
)

__all__ = ["StagePlan", "StagePlanError", "StageParallelExecutor",
           "plan_stages", "merge_subtask_states"]


from flink_tpu.core.annotations import internal

class StagePlanError(ValueError):
    """The graph shape is not supported by stage-parallel execution."""


class StageInput:
    """One input branch of the keyed stage: a source, its chained
    stateless pre-operators (incl. the key_by routing marker), and the
    key field records are hash-exchanged on."""

    def __init__(self, source: Transformation,
                 pre_chain: List[Transformation], key_field: str):
        self.source = source
        self.pre_chain = pre_chain
        self.key_field = key_field


class OutSpec:
    """One outgoing keyed exchange of a producer (a source or a keyed
    stage): optional stateless branch transformations applied in the
    producer subtask (key_by routing markers, maps after a fan-out
    point), then records hash-route on ``key_field`` to ``target_input``
    of stage ``target_stage``."""

    def __init__(self, key_field: str, target_stage: int,
                 target_input: int = 0,
                 branch: Optional[List[Transformation]] = None):
        self.key_field = key_field
        self.target_stage = target_stage
        self.target_input = target_input
        self.branch = branch or []


class SourceSpec:
    """One physical source: the source transformation, its chained
    stateless pre-operators (shared by every output), and the outgoing
    exchanges. Fan-out (multiple outputs) duplicates the stream to every
    exchange — one subtask reads the split once and routes it everywhere
    (reference: a source vertex with multiple output JobEdges)."""

    def __init__(self, source: Transformation,
                 chain: List[Transformation], outputs: List[OutSpec]):
        self.source = source
        self.chain = chain
        self.outputs = outputs

    @property
    def transformations(self) -> List[Transformation]:
        out = list(self.chain)
        for o in self.outputs:
            out.extend(o.branch)
        return out


class KeyedStage:
    """One keyed stage of the DAG: the main operator chain (head is the
    key_by routing marker or a two-input keyed op), optional side-output
    branches executed in the same subtask, and the outgoing exchanges
    (empty = terminal, the chain ends in the sink)."""

    def __init__(self, chain: List[Transformation],
                 side_chains: Optional[
                     List[Tuple[str, List[Transformation]]]] = None,
                 num_inputs: int = 1,
                 outputs: Optional[List[OutSpec]] = None):
        self.chain = chain
        #: (tag, chain) branches fed by TaggedBatch outputs of main-chain
        #: operators; stateless + sink, run inside each subtask
        self.side_chains = side_chains or []
        self.num_inputs = num_inputs
        self.outputs = outputs or []

    @property
    def out_key_field(self) -> Optional[str]:
        return self.outputs[0].key_field if self.outputs else None

    @property
    def operator_transformations(self) -> List[Transformation]:
        out = list(self.chain)
        for _, sc in self.side_chains:
            out.extend(sc)
        for o in self.outputs:
            out.extend(o.branch)
        return out


class StagePlan:
    """Source(s) + keyed stages connected by hash exchanges, as a DAG
    (reference: DefaultExecutionGraph runs any DAG at any per-vertex
    parallelism). Supported: any number of physical sources with output
    fan-out, chains of keyed exchanges, one- and two-input keyed stages
    (joins — fed by sources and/or upstream stages, Q7's diamond), and
    side-output branches."""

    def __init__(self, source_specs: List[SourceSpec],
                 stages: List[KeyedStage]):
        #: one per physical source
        self.source_specs = source_specs
        #: keyed stages in topological order; terminal stages end in sinks
        self.stages = stages

    # -- single-input / single-stage compat views (the linear pipeline's
    # -- and the two-input join's vocabulary, kept for callers/tests)
    @property
    def inputs(self) -> List[StageInput]:
        outs = []
        for spec in self.source_specs:
            for o in spec.outputs:
                if o.target_stage == 0:
                    outs.append((o.target_input, StageInput(
                        spec.source, spec.chain + o.branch, o.key_field)))
        outs.sort(key=lambda x: x[0])
        return [si for _, si in outs]

    @property
    def source(self) -> Transformation:
        return self.source_specs[0].source

    @property
    def pre_chain(self) -> List[Transformation]:
        return self.inputs[0].pre_chain

    @property
    def key_field(self) -> str:
        return self.inputs[0].key_field

    @property
    def keyed_chain(self) -> List[Transformation]:
        return self.stages[0].chain


def plan_stages(graph: StreamGraph) -> StagePlan:
    """Derive the stage DAG from the chained JobGraph
    (flink_tpu/graph/job_graph.py — the StreamingJobGraphGenerator role).

    Supported shapes: any DAG of physical sources (with output fan-out)
    and keyed stages connected by hash exchanges — linear pipelines,
    chains of keyed exchanges (agg -> re-key -> agg), one- and two-input
    keyed stages (joins fed by sources and/or upstream stages, incl.
    Q7's diamond), and side-output branches off keyed stages (stateless
    + sink, executed inside the owning subtask). A ``key_by`` routing
    marker that could not chain into a two-input consumer becomes a
    ROUTING vertex: its chain runs producer-side and its key names the
    exchange (the reference's partitioner-on-the-edge model). Raises
    StagePlanError for anything else (broadcast edges, rebalance,
    exchange unions) — callers fall back to single-slot execution when
    configured to."""
    from flink_tpu.graph.job_graph import FORWARD, HASH, SIDE, \
        build_job_graph
    from flink_tpu.runtime.operators import KeyByOperator

    jg = build_job_graph(graph, default_parallelism=1,
                         respect_parallelism=False)
    if not any(e.ship == HASH for e in jg.edges):
        raise StagePlanError("no keyed exchange — nothing to expand")
    out_edges: Dict[int, List] = {v.vid: [] for v in jg.vertices}
    in_edges: Dict[int, List] = {v.vid: [] for v in jg.vertices}
    for e in jg.edges:
        out_edges[e.source_vid].append(e)
        in_edges[e.target_vid].append(e)

    def _is_routing_vertex(v) -> bool:
        """A key_by marker vertex whose single consumer is a two-input
        stage: it exists only because markers cannot chain into a
        multi-input head — its chain runs producer-side."""
        if v.is_source or v.head.kind == "two_input":
            return False
        if not v.head.keyed or v.head.key_field is None:
            return False
        probe = (v.head.operator_factory()
                 if v.head.operator_factory else None)
        if not isinstance(probe, KeyByOperator):
            return False
        cons = out_edges[v.vid]
        return len(cons) == 1 and \
            jg.vertices[cons[0].target_vid].head.kind == "two_input"

    routing = {v.vid: v for v in jg.vertices if _is_routing_vertex(v)}
    # stage heads: every vertex entered through a hash exchange that is
    # not a routing vertex, in topological (vid) order
    stage_heads = []
    for v in jg.vertices:
        if v.is_source or v.vid in routing:
            continue
        ins = in_edges[v.vid]
        if ins and all(e.ship == HASH for e in ins):
            if not (v.head.keyed or v.head.kind == "two_input"):
                raise StagePlanError(
                    f"exchange target [{v.name}] does not start at a "
                    "keyed operator — only keyed stages shard by key "
                    "group")
            stage_heads.append(v)
    stage_index = {v.vid: m for m, v in enumerate(stage_heads)}
    used: set = set(routing)

    def _resolve_exchange(e) -> OutSpec:
        """A HASH (or partition-preserving FORWARD) edge out of a
        producer -> the OutSpec it denotes: either directly into a
        one-input stage head, or through a routing vertex into one input
        of a two-input stage."""
        tv = jg.vertices[e.target_vid]
        if tv.vid in routing:
            kv2 = jg.vertices[out_edges[tv.vid][0].target_vid]
            if kv2.vid not in stage_index:
                raise StagePlanError(
                    f"routing vertex [{tv.name}] feeds [{kv2.name}], "
                    "which is not a keyed stage")
            idx = next((i for i, it in enumerate(kv2.head.inputs)
                        if it.uid == tv.tail.uid), None)
            if idx is None:
                raise StagePlanError(
                    f"routing vertex [{tv.name}] is not an input of "
                    f"[{kv2.name}]")
            return OutSpec(e.key_field or tv.head.key_field,
                           stage_index[kv2.vid], idx,
                           branch=list(tv.chained))
        if tv.vid in stage_index:
            if tv.head.kind == "two_input":
                raise StagePlanError(
                    f"two-input stage [{tv.name}] must be fed through "
                    "key_by routing vertices (one per input)")
            if e.key_field is None:
                raise StagePlanError(
                    f"keyed exchange into [{tv.name}] has no key field")
            return OutSpec(e.key_field, stage_index[tv.vid], 0)
        raise StagePlanError(
            f"unsupported exchange target [{tv.name}]")

    def _walk_outputs(head_v):
        """From a stage head, absorb FORWARD continuations into the
        chain and SIDE branches into side_chains; every HASH edge (and
        FORWARD edge into a routing vertex) becomes an outgoing
        exchange. Returns (chain, side_chains, outputs)."""
        chain = list(head_v.chained)
        side_chains: List[Tuple[str, List[Transformation]]] = []
        exchange_edges = []
        cur = head_v
        used.add(cur.vid)
        while True:
            outs = out_edges[cur.vid]
            fwd, side, hashed, other = [], [], [], []
            for e in outs:
                if e.ship == HASH or (
                        e.ship == FORWARD and e.target_vid in routing):
                    hashed.append(e)
                elif e.ship == FORWARD:
                    fwd.append(e)
                elif e.ship == SIDE:
                    side.append(e)
                else:
                    other.append(e)
            if other:
                raise StagePlanError(
                    f"unsupported exchange {other[0].ship} out of "
                    f"[{cur.name}]")
            for e in side:
                sv = jg.vertices[e.target_vid]
                if out_edges[sv.vid]:
                    raise StagePlanError(
                        f"side-output branch [{sv.name}] must end in a "
                        "sink (no further exchanges)")
                if sv.tail.kind != "sink":
                    raise StagePlanError(
                        f"side-output branch [{sv.name}] must end in a "
                        "sink")
                if any(t.keyed for t in sv.chained):
                    raise StagePlanError(
                        f"side-output branch [{sv.name}] re-keys — "
                        "keyed side branches are not supported in stage "
                        "mode")
                used.add(sv.vid)
                side_chains.append((sv.head.side_tag, sv.chained))
            exchange_edges.extend(hashed)
            if len(fwd) > 1:
                raise StagePlanError(
                    f"[{cur.name}] has multiple forward continuations — "
                    "not a supported DAG shape")
            if fwd:
                cur = jg.vertices[fwd[0].target_vid]
                used.add(cur.vid)
                chain.extend(cur.chained)
                continue
            break
        return chain, side_chains, [
            _resolve_exchange(e) for e in exchange_edges]

    # physical sources
    source_specs: List[SourceSpec] = []
    for v in jg.vertices:
        if not v.is_source:
            continue
        chain, side_chains, outputs = _walk_outputs(v)
        if side_chains:
            raise StagePlanError(
                f"side outputs on the source stage [{v.name}] are not "
                "supported — move the split after the keyed exchange")
        if not outputs:
            raise StagePlanError(
                f"source [{v.name}] feeds no keyed exchange")
        if chain[-1].kind == "sink":
            raise StagePlanError(
                f"source stage [{v.name}] ends in a sink — nothing to "
                "expand on that branch")
        source_specs.append(SourceSpec(v.head, chain[1:], outputs))

    # keyed stages
    stages: List[KeyedStage] = []
    for m, head_v in enumerate(stage_heads):
        chain, side_chains, outputs = _walk_outputs(head_v)
        num_inputs = 2 if head_v.head.kind == "two_input" else 1
        if num_inputs == 1 and len(in_edges[head_v.vid]) != 1:
            raise StagePlanError(
                f"stage [{head_v.name}] has {len(in_edges[head_v.vid])} "
                "producers — unioning exchanges into one keyed input is "
                "not supported")
        if not outputs and chain[-1].kind != "sink":
            raise StagePlanError("pipeline must end in a sink")
        stages.append(KeyedStage(chain, side_chains=side_chains,
                                 num_inputs=num_inputs, outputs=outputs))
    if not stages:
        raise StagePlanError("no keyed stage")

    # every stage input must be fed exactly once
    feeds: Dict[Tuple[int, int], int] = {}
    for spec in source_specs:
        for o in spec.outputs:
            feeds[(o.target_stage, o.target_input)] = feeds.get(
                (o.target_stage, o.target_input), 0) + 1
    for m, stage in enumerate(stages):
        for o in stage.outputs:
            if o.target_stage <= m:
                raise StagePlanError(
                    "exchange cycles are not supported")
            feeds[(o.target_stage, o.target_input)] = feeds.get(
                (o.target_stage, o.target_input), 0) + 1
    for m, stage in enumerate(stages):
        for i in range(stage.num_inputs):
            if feeds.get((m, i), 0) != 1:
                raise StagePlanError(
                    f"stage {m} input {i} is fed by "
                    f"{feeds.get((m, i), 0)} exchanges (must be exactly "
                    "one)")

    # every vertex must be part of the plan — an unreachable/unsupported
    # branch must fail, not silently drop
    missing = [v for v in jg.vertices if v.vid not in used]
    if missing:
        raise StagePlanError(
            "graph has vertices outside the supported source -> keyed-"
            "stage DAG shape: "
            + "; ".join(f"[{v.name}]" for v in missing))
    return StagePlan(source_specs, stages)


# ---------------------------------------------------------------------------
# state merge (per-subtask -> logical single-slot format)
# ---------------------------------------------------------------------------


def _merge_changelog(values: List[Dict[str, Any]]) -> Dict[str, Any]:
    """GroupAgg changelog rows: concatenate, with per-subtask 'last' column
    sets unioned — a subtask that has not emitted yet has no last-image
    columns, and its rows (all emitted=False) get identity fill."""
    kid = [np.asarray(v["key_id"]) for v in values]
    cols = set()
    for v in values:
        cols.update(v.get("last", {}).keys())
    last: Dict[str, np.ndarray] = {}
    for c in sorted(cols):
        dt = next(np.asarray(v["last"][c]).dtype for v in values
                  if c in v.get("last", {}))
        last[c] = np.concatenate([
            np.asarray(v["last"][c]) if c in v.get("last", {})
            else np.zeros(len(k), dtype=dt)
            for v, k in zip(values, kid)])
    return {
        "key_id": np.concatenate(kid),
        "count": np.concatenate([np.asarray(v["count"]) for v in values]),
        "emitted": np.concatenate([np.asarray(v["emitted"])
                                   for v in values]),
        "dirty": np.concatenate([
            np.asarray(v.get("dirty", np.zeros(len(k), bool)))
            for v, k in zip(values, kid)]),
        "last": last,
    }


def _merge_values(key: str, values: List[Any]):
    """Merge one state field across subtasks by its semantic kind."""
    if key in ("watermark", "max_fired_end", "max_ts", "next_sid",
               "max_fired_watermark"):
        return max(values)
    if key == "late_records_dropped":
        return sum(values)
    if key == "keys_hashed":
        return any(values)
    if key == "pending":
        return sorted({x for v in values for x in v})
    if key in ("um_keys", "um_rows"):
        # upsert-materializer images: key-disjoint lists across subtasks
        return [x for v in values for x in v]
    if key in ("slice_last_window", "sessions", "key_values"):
        merged: Dict = {}
        for v in values:
            merged.update(v)
        return merged
    if key == "changelog":
        return _merge_changelog(values)
    if key in ("left", "right"):
        # interval-join side buffers: lists of column dicts, key-group
        # disjoint across subtasks — union by concatenating the lists
        return [c for v in values for c in v]
    if key == "buf":
        # window-join per-slice side buffers: {slice_end: ([left column
        # dicts], [right column dicts])} — union per slice end
        out: Dict[int, Tuple[List, List]] = {}
        for v in values:
            for se, (l, r) in v.items():
                cur = out.setdefault(se, ([], []))
                cur[0].extend(l)
                cur[1].extend(r)
        return out
    if isinstance(values[0], np.ndarray):
        return np.concatenate([np.asarray(v) for v in values])
    if isinstance(values[0], dict):
        # dict-of-arrays (table leaves) / nested metadata: merge per field
        return {sub: _merge_values(sub, [v[sub] for v in values])
                for sub in values[0]}
    # scalars expected identical (e.g. format flags)
    return values[0]


def merge_subtask_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Union the per-subtask snapshots of ONE operator into the logical
    single-slot format. Table rows (key-group disjoint across subtasks)
    concatenate; metadata merges by kind (max watermarks, union dicts)."""
    states = [s for s in states if s]
    if not states:
        return {}
    if len(states) == 1:
        return states[0]
    return {k: _merge_values(k, [s[k] for s in states])
            for k in states[0]}


# ---------------------------------------------------------------------------
# subtasks
# ---------------------------------------------------------------------------


class _SubtaskFailure(Exception):
    pass


class _SharedSink:
    """Thread-safe facade over ONE sink instance shared by N keyed
    subtasks: writes serialize under a lock, and the underlying sink opens
    once / closes only when the last subtask closes (the reference deploys
    a sink INSTANCE per subtask; collect-style sinks here aggregate in one
    object, so sharing + refcounting is the honest equivalent)."""

    def __init__(self, sink):
        self._sink = sink
        self._lock = threading.Lock()
        self._opens = 0
        self._closes = 0
        self._closed = False

    def open(self, subtask_index: int = 0) -> None:
        with self._lock:
            if self._opens == 0:
                self._sink.open(0)
            self._opens += 1

    def write(self, batch) -> None:
        with self._lock:
            self._sink.write(batch)

    def close(self) -> None:
        with self._lock:
            self._closes += 1
            if self._closes >= self._opens and not self._closed:
                self._closed = True
                self._sink.close()

    def __getattr__(self, name):
        return getattr(self._sink, name)


class _OperatorChain:
    """The fused operator chain of one subtask (reference: OperatorChain —
    direct method-call hand-off between chained operators).

    ``side_chains`` maps side-output tags to branch chains run in the same
    subtask: a TaggedBatch emitted by any main-chain operator is diverted
    to the matching branch (reference: OutputTag routing in OperatorChain)
    instead of continuing down the main chain. process_batch /
    process_watermark / close RETURN the batches that survive past the
    last main-chain operator — empty when the tail is a sink, the
    downstream-exchange payload for intermediate keyed stages."""

    def __init__(self, transformations: Sequence[Transformation],
                 ctx: OperatorContext,
                 shared_sinks: Optional[Dict[int, _SharedSink]] = None,
                 side_chains: Optional[
                     List[Tuple[str, Sequence[Transformation]]]] = None):
        self.transformations = list(transformations)
        self.operators = []
        self._shared_sinks = shared_sinks
        for t in self.transformations:
            self.operators.append(self._make_operator(t, ctx))
        self.side_chains: Dict[str, _OperatorChain] = {}
        for tag, sc in (side_chains or []):
            self.side_chains[tag] = _OperatorChain(
                sc, ctx, shared_sinks=shared_sinks)

    def _make_operator(self, t: Transformation, ctx: OperatorContext):
        op = t.operator_factory() if t.operator_factory else None
        if op is not None:
            if self._shared_sinks is not None and hasattr(op, "sink"):
                # every subtask's factory captured the same sink
                # object — route all of them through one refcounted,
                # locked facade (see _SharedSink)
                op.sink = self._shared_sinks.setdefault(
                    t.uid, _SharedSink(op.sink))
            op.open(ctx)
        return op

    def _route(self, outs: List) -> List[RecordBatch]:
        """Divert TaggedBatch outputs to their side branch; return the
        main-stream batches."""
        from flink_tpu.runtime.process import TaggedBatch

        main: List[RecordBatch] = []
        for b in outs:
            if isinstance(b, TaggedBatch):
                branch = self.side_chains.get(b.tag.name)
                if branch is not None:
                    branch.process_batch(b.batch)
                # unmatched tags drop, like the single-slot router
            else:
                main.append(b)
        return main

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        outs = [batch]
        head = True
        for op in self.operators:
            if op is None:
                continue
            nxt: List[RecordBatch] = []
            for b in outs:
                # only the chain HEAD can be multi-input (a two-input
                # keyed op); everything downstream consumes its single
                # output stream
                nxt.extend(op.process_batch(b, input_index if head else 0))
            head = False
            outs = self._route(nxt)
            if not outs:
                break
        return outs

    def process_watermark(self, wm: int) -> List[RecordBatch]:
        """Advance the watermark through the chain; batches an operator
        fires are fed to the operators AFTER it (then the watermark), and
        whatever survives past the tail is returned."""
        carried: List[RecordBatch] = []
        for op in self.operators:
            if op is None:
                continue
            nxt: List[RecordBatch] = []
            for b in carried:
                nxt.extend(op.process_batch(b))
            nxt.extend(op.process_watermark(wm))
            carried = self._route(nxt)
        return carried

    @property
    def uses_processing_time(self) -> bool:
        return any(getattr(op, "uses_processing_time", False)
                   for op in self.operators if op is not None)

    def tick_processing_time(self, now_ms: int, emit=None) -> None:
        """Wall-clock tick: fire processing-time windows/timers and push
        their output through the rest of the chain. ``emit`` receives
        batches that survive past the LAST operator (source-stage chains
        end at the keyed exchange, not a sink)."""
        for i, op in enumerate(self.operators):
            if op is None or not getattr(op, "uses_processing_time", False):
                continue
            outs = op.on_processing_time(now_ms)
            for out in outs:
                cur = [out]
                for op2 in self.operators[i + 1:]:
                    if op2 is None:
                        continue
                    nxt: List[RecordBatch] = []
                    for b in cur:
                        nxt.extend(op2.process_batch(b))
                    cur = self._route(nxt)
                    if not cur:
                        break
                if emit is not None:
                    for b in cur:
                        emit(b)

    def close(self) -> List[RecordBatch]:
        carried: List[RecordBatch] = []
        for op in self.operators:
            if op is None:
                continue
            nxt: List[RecordBatch] = []
            for b in carried:
                nxt.extend(op.process_batch(b))
            nxt.extend(op.close())
            carried = self._route(nxt)
        for branch in self.side_chains.values():
            branch.close()
        return carried

    def dispose(self) -> None:
        for op in self.operators:
            if op is not None:
                try:
                    op.dispose()
                except Exception:
                    pass
        for branch in self.side_chains.values():
            branch.dispose()

    def snapshot(self, graph: StreamGraph, savepoint: bool = False
                 ) -> Dict[str, Any]:
        snap = {}
        for t, op in zip(self.transformations, self.operators):
            if op is None:
                continue
            if savepoint and hasattr(op, "snapshot_state_savepoint"):
                state = op.snapshot_state_savepoint()
            else:
                state = op.snapshot_state()
            if state:
                snap[graph.stable_id(t)] = state
        for branch in self.side_chains.values():
            snap.update(branch.snapshot(graph, savepoint=savepoint))
        return snap

    def restore(self, graph: StreamGraph, states: Dict[str, Any],
                key_group_filter=None) -> None:
        for branch in self.side_chains.values():
            branch.restore(graph, states, key_group_filter=key_group_filter)
        for t, op in zip(self.transformations, self.operators):
            if op is None:
                continue
            state = states.get(graph.stable_id(t))
            if state is None:
                continue
            if key_group_filter is None:
                op.restore_state(state)
                continue
            import inspect

            sig = inspect.signature(op.restore_state)
            if "key_group_filter" not in sig.parameters:
                # restoring the FULL merged state into every subtask would
                # silently duplicate keyed state (N× timer fires, N×
                # emissions) — fail precisely instead
                raise RuntimeError(
                    f"operator {t.name!r} ({type(op).__name__}) does not "
                    "support key-group-filtered restore; it cannot be "
                    "restored in stage-parallel mode (reference: keyed "
                    "state restore is key-group-range scoped)")
            op.restore_state(state, key_group_filter=key_group_filter)


def _local_combiner_factory(plan: StagePlan):
    """A () -> LocalWindowCombiner factory when the keyed stage starts
    with an aligned event-time window aggregation, else None. Introspects
    a throwaway operator instance (construction is cheap; open() is what
    builds device state)."""
    from flink_tpu.runtime.local_agg import LocalWindowCombiner
    from flink_tpu.runtime.operators import KeyByOperator, WindowAggOperator

    # the keyed chain opens with the key_by routing op; the aggregation
    # is the first operator after it
    head = None
    for t in plan.keyed_chain:
        if t.operator_factory is None:
            return None
        probe = t.operator_factory()
        if isinstance(probe, KeyByOperator):
            continue
        head = t
        break
    if head is None:
        return None
    if type(probe) is not WindowAggOperator:
        return None  # sessions (merging) and non-window heads: no combine
    if probe.assigner is None or probe.assigner.is_merging or \
            getattr(probe, "uses_processing_time", False):
        return None

    def factory():
        op = head.operator_factory()
        return LocalWindowCombiner(op.assigner, op.agg, op.key_field)

    return factory


class _OutputRoute:
    """One outgoing keyed exchange of a producer subtask (source or
    keyed): optional stateless branch operators (key_by routing markers,
    post-fan-out maps) run here, then records hash-route on the exchange
    key to the consuming stage's subtasks — the ONE keyBy routing
    implementation (reference: KeyGroupStreamPartitioner.selectChannel +
    RecordWriter). In batch mode sub-batches coalesce into bulk blocks
    per subpartition (the SortMergeResultPartition role)."""

    def __init__(self, out: OutSpec, writer, num_channels: int,
                 max_parallelism: int, ctx: OperatorContext,
                 batch_mode: bool = False, batch_size: int = 0,
                 combiner=None, recompute_key_id: bool = False):
        from flink_tpu.runtime.shuffle_spi import KeyGroupPartitioner

        self.out = out
        self.writer = writer
        self.num_channels = num_channels
        self.batch_mode = batch_mode
        self.batch_size = batch_size
        #: two-phase agg, local half: at most one row per (key, slice)
        #: leaves this subtask per batch (flink_tpu/runtime/local_agg.py)
        self.combiner = combiner
        #: routes OUT OF a keyed stage must re-hash: the batch carries
        #: the PREVIOUS exchange's __key_id__. Source routes reuse a
        #: present __key_id__ (the key_by marker / local combiner
        #: computed it from this same key field — local_agg.py:95), and
        #: a branch whose own key_by marker re-keys on THIS exchange's
        #: field has already rewritten __key_id__ — recomputing would
        #: hash every row twice
        if recompute_key_id and any(
                t.keyed and t.key_field == out.key_field
                for t in out.branch):
            recompute_key_id = False
        self.recompute_key_id = recompute_key_id
        self.chain = _OperatorChain(out.branch, ctx) if out.branch \
            else None
        self._partitioner = KeyGroupPartitioner("__key_id__",
                                                max_parallelism)
        self._pending: Dict[int, List[RecordBatch]] = {}
        self._pending_rows: Dict[int, int] = {}
        self.records_out = 0

    def process(self, batch: RecordBatch) -> None:
        from flink_tpu.state.keygroups import hash_keys_to_i64

        batches = self.chain.process_batch(batch) if self.chain \
            else [batch]
        for b in batches:
            if self.combiner is not None:
                b = self.combiner.combine(b)
            if self.out.key_field not in b.columns:
                raise _SubtaskFailure(
                    f"exchange key field {self.out.key_field!r} missing "
                    f"from batch columns {b.names()}")
            if self.recompute_key_id or "__key_id__" not in b.columns:
                # ints are identity under hash_keys_to_i64, so routing
                # and downstream state share one key identity
                b = b.with_column(
                    "__key_id__",
                    hash_keys_to_i64(b[self.out.key_field]))
            for sub, part in self._partitioner.partition(
                    b, self.num_channels):
                self.records_out += len(part)
                if not self.batch_mode:
                    self.writer.emit(sub, part)
                    continue
                # batch mode: coalesce into bulk blocks (fewer, larger
                # transfers — the batch-shuffle trade)
                self._pending.setdefault(sub, []).append(part)
                n = self._pending_rows.get(sub, 0) + len(part)
                if n >= self.batch_size:
                    self.writer.emit(sub, RecordBatch.concat(
                        self._pending.pop(sub)))
                    self._pending_rows[sub] = 0
                else:
                    self._pending_rows[sub] = n

    def flush(self) -> None:
        for sub, parts in sorted(self._pending.items()):
            if parts:
                self.writer.emit(sub, RecordBatch.concat(parts))
        self._pending.clear()
        self._pending_rows.clear()

    def broadcast(self, event) -> None:
        self.writer.broadcast_event(event)

    def close(self) -> None:
        self.writer.close()

    def snapshot(self, graph, savepoint: bool = False) -> Dict[str, Any]:
        return self.chain.snapshot(graph, savepoint=savepoint) \
            if self.chain else {}

    def restore(self, graph, states, key_group_filter=None) -> None:
        if self.chain:
            self.chain.restore(graph, states,
                               key_group_filter=key_group_filter)


class _SourceSubtask(threading.Thread):
    """One source-stage subtask: polls its source split, applies the
    shared pre-chain, and emits every batch through each of its output
    routes (fan-out duplicates the stream; each route applies its branch
    ops and hash-partitions on its own exchange key)."""

    def __init__(self, index: int, parallelism: int, spec: SourceSpec,
                 graph: StreamGraph, routes: List[_OutputRoute],
                 max_parallelism: int, batch_size: int,
                 coordinator: "_Coordinator", source,
                 restore_position=None, batch_mode: bool = False,
                 source_index: int = 0):
        self.spec = spec
        self.source_index = source_index
        super().__init__(
            name=f"source-subtask-s{source_index}-{index}", daemon=True)
        #: bounded/batch execution: no intermediate watermarks
        self.batch_mode = batch_mode
        self.index = index
        self.parallelism = parallelism
        self.graph = graph
        self.routes = routes
        self.max_parallelism = max_parallelism
        self.batch_size = batch_size
        self.coordinator = coordinator
        self.source = source
        self.restore_position = restore_position
        self.control: _q.Queue = _q.Queue()
        self.error: Optional[BaseException] = None
        self.wm_gen = spec.source.watermark_strategy.create()
        self.chain: Optional[_OperatorChain] = None
        self.records_polled = 0
        self.batches_polled = 0
        #: position at exit — checkpoints after this subtask drains its
        #: split still record where it ended (restore must not replay it)
        self.final_position = None

    @property
    def records_out(self) -> int:
        return sum(r.records_out for r in self.routes)

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self.coordinator.subtask_failed(self, e)

    def _emit(self, batch: RecordBatch) -> None:
        for r in self.routes:
            r.process(batch)

    def _run(self) -> None:
        spec = self.spec
        ctx = OperatorContext(operator_index=self.index,
                              parallelism=1,
                              max_parallelism=self.max_parallelism)
        self.chain = _OperatorChain(spec.chain, ctx)
        self.source.open(self.index, self.parallelism)
        if self.restore_position is not None:
            self.source.restore_position(self.restore_position)
        stopping = False
        ticks_pt = self.chain.uses_processing_time
        try:
            while not stopping:
                stopping = self._serve_control()
                if stopping:
                    break
                if self.coordinator.cancelled.is_set():
                    return
                if ticks_pt:
                    # pre-chain processing-time timers fire on the wall
                    # clock even between batches (parity with the
                    # single-slot executor's tick)
                    self.chain.tick_processing_time(
                        int(time.time() * 1000), emit=self._emit)
                batch = self.source.poll_batch(self.batch_size)
                if batch is None:
                    break
                if len(batch) == 0:
                    continue
                self.batches_polled += 1
                self.records_polled += len(batch)
                batch = spec.source.watermark_strategy.assign_timestamps(
                    batch)
                wm = self.wm_gen.on_batch(batch)
                for out in self.chain.process_batch(batch):
                    self._emit(out)
                if wm is not None and not self.batch_mode:
                    for r in self.routes:
                        r.broadcast(int(wm))
        finally:
            self.final_position = self.source.snapshot_position()
            self.source.close()
        for r in self.routes:
            r.flush()
        # a barrier enqueued while this loop was finishing must still be
        # served (position + ack + in-band broadcast) before EOP — the
        # coordinator synthesizes acks only for barriers that arrive after
        # the thread is observably dead
        self._serve_control()
        for r in self.routes:
            r.broadcast(MAX_WATERMARK)
            r.close()

    def snapshot_operators(self, graph, savepoint: bool = False
                           ) -> Dict[str, Any]:
        snap = self.chain.snapshot(graph, savepoint=savepoint) \
            if self.chain else {}
        for r in self.routes:
            snap.update(r.snapshot(graph, savepoint=savepoint))
        return snap

    def _serve_control(self) -> bool:
        """Returns True when the job should stop (stop-with-savepoint)."""
        stopping = False
        while True:
            try:
                trigger = self.control.get_nowait()
            except _q.Empty:
                return stopping
            barrier: Barrier = trigger
            snap = {"position": self.source.snapshot_position(),
                    "operators": self.snapshot_operators(
                        self.graph,
                        savepoint=barrier.savepoint is not None)}
            self.coordinator.ack(barrier.checkpoint_id,
                                 ("source", self.source_index, self.index),
                                 snap)
            # coalesced batch-mode blocks hold pre-barrier records — they
            # must reach the channels BEFORE the barrier or they would be
            # cut out of the snapshot yet covered by the position
            for r in self.routes:
                r.flush()
                r.broadcast(barrier)
            if barrier.stop:
                stopping = True


class _KeyedSubtask(threading.Thread):
    """One keyed-stage subtask: owns a key-group range, consumes one gate
    PER INPUT with per-channel watermarking and aligned barriers spanning
    every channel of every gate (reference:
    SingleCheckpointBarrierHandler aligns across all input channels of a
    multi-input task). An INTERMEDIATE stage's subtask additionally owns a
    downstream partition: main-chain output is re-keyed on the stage's
    out_key_field and hash-exchanged to the next stage, and watermarks /
    aligned barriers / end-of-partition forward in-band (reference: a
    non-sink Task's RecordWriter + barrier forwarding)."""

    def __init__(self, index: int, parallelism: int, stage: KeyedStage,
                 graph: StreamGraph, gates, max_parallelism: int,
                 coordinator: "_Coordinator", config: Configuration,
                 shared_sinks: Optional[Dict[int, _SharedSink]] = None,
                 stage_index: int = 0,
                 routes: Optional[List[_OutputRoute]] = None,
                 mesh_devices: int = 0, memory_manager=None):
        super().__init__(
            name=f"keyed-subtask-st{stage_index}-{index}", daemon=True)
        #: managed device-memory pool shared across the job's subtasks
        self.memory_manager = memory_manager
        self.shared_sinks = shared_sinks
        self.index = index
        self.parallelism = parallelism
        self.stage = stage
        self.stage_index = stage_index
        #: outgoing exchanges (empty: terminal stage, sink in-chain)
        self.routes = routes or []
        #: devices per subtask for the mesh x stage composition (0 = one
        #: device per subtask)
        self.mesh_devices = mesh_devices
        self.graph = graph
        #: one gate per keyed-stage input, in head-operator input order
        self.gates = list(gates) if isinstance(gates, (list, tuple)) \
            else [gates]
        self.max_parallelism = max_parallelism
        self.coordinator = coordinator
        self.config = config
        rng = compute_key_group_range(max_parallelism, parallelism, index)
        self.key_groups = range(rng.start, rng.end + 1)
        self.control: _q.Queue = _q.Queue()
        self.error: Optional[BaseException] = None
        self.chain: Optional[_OperatorChain] = None
        self.records_in = 0
        self._restore_states: Optional[Dict[str, Any]] = None
        #: slot -> {"b0": {col: arr}, ...} from an unaligned checkpoint
        self._restore_channel_state: Dict[str, Any] = {}

    @property
    def records_out(self) -> int:
        return sum(r.records_out for r in self.routes)

    def _emit_downstream(self, batch: RecordBatch) -> None:
        for r in self.routes:
            r.process(batch)

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self.coordinator.subtask_failed(self, e)

    def _run(self) -> None:
        ctx = OperatorContext(operator_index=self.index, parallelism=1,
                              max_parallelism=self.max_parallelism,
                              memory_manager=self.memory_manager,
                              shuffle_mode=self.config.get(
                                  DeploymentOptions.SHUFFLE_MODE),
                              host_topology=(self.config.get(
                                  DeploymentOptions.SHUFFLE_HOSTS)
                                  or None))
        if self.mesh_devices > 1:
            # mesh x stage composition: this subtask opens its keyed
            # engine over a private sub-mesh — subtasks distribute across
            # slots/hosts, the sub-mesh distributes across chips within
            # the subtask (see MeshWindowEngine key_group_range)
            import jax

            from flink_tpu.parallel.mesh import make_mesh

            devs = jax.devices()
            # reactive clamp (a mesh must not contain one device twice):
            # at most len(devs) distinct devices per sub-mesh; subtasks
            # whose windows overlap simply share devices across their
            # separate meshes, which is fine
            D = min(self.mesh_devices, len(devs))
            if D < self.mesh_devices:
                import warnings

                warnings.warn(
                    f"execution.stage-mesh-devices={self.mesh_devices} "
                    f"clamped to the {len(devs)} available devices",
                    stacklevel=2)
            lo = (self.index * D) % len(devs)
            sub_devs = [devs[(lo + d) % len(devs)] for d in range(D)]
            ctx.parallelism = D
            ctx.mesh = make_mesh(devices=sub_devs)
            ctx.key_group_range = (self.key_groups.start,
                                   self.key_groups[-1])
        self.chain = _OperatorChain(self.stage.chain, ctx,
                                    shared_sinks=self.shared_sinks,
                                    side_chains=self.stage.side_chains)
        if self._restore_states is not None:
            self.chain.restore(self.graph, self._restore_states,
                               key_group_filter=set(self.key_groups))
            for r in self.routes:
                r.restore(self.graph, self._restore_states,
                          key_group_filter=set(self.key_groups))
        gates = self.gates
        K = len(gates)
        # flat channel addressing across gates: (gate, ch) -> slot
        nch = [g.num_channels for g in gates]
        total = sum(nch)
        base = [sum(nch[:g]) for g in range(K)]
        chan_wm = [-(1 << 62)] * total
        done = [False] * total
        combined = -(1 << 62)
        aligning: Optional[Barrier] = None
        barriered = [False] * total
        buffered: List[Tuple[int, int, Any]] = []
        # unaligned-checkpoint mode (reference: CheckpointedInputGate's
        # priority-barrier path + ChannelStateWriter): operator state is
        # snapshotted at the FIRST barrier, data keeps flowing, and
        # pre-barrier batches from not-yet-barriered channels are copied
        # into channel state while being processed
        ua: Optional[Barrier] = None
        ua_snap: Optional[Dict] = None
        ua_barriered = [False] * total
        ua_chan_state: Dict[int, List] = {}
        stopping = False
        poll_at = 0

        def combined_wm() -> int:
            return min((MAX_WATERMARK if done[c] else chan_wm[c])
                       for c in range(total))

        downstream = bool(self.routes)

        def forward(outs) -> None:
            if downstream:
                for b in outs:
                    if len(b):
                        self._emit_downstream(b)
            # terminal stage: sink is in-chain; trailing output dropped

        def process(item, gi: int, slot: int):
            nonlocal combined, stopping
            if isinstance(item, RecordBatch):
                # chaos: kill one keyed subtask mid-batch; the
                # coordinator fails the attempt and the job-level
                # restart/restore machinery takes over (one pipeline =
                # one failover region)
                chaos.fault_point("task.subtask_batch",
                                  stage=self.stage_index,
                                  subtask=self.index)
                self.records_in += len(item)
                forward(self.chain.process_batch(item, input_index=gi))
            elif isinstance(item, int):
                chan_wm[slot] = max(chan_wm[slot], item)
                new = combined_wm()
                if new > combined:
                    combined = new
                    forward(self.chain.process_watermark(combined))
                    # results precede the watermark that fired them
                    for r in self.routes:
                        r.broadcast(int(combined))

        def aligned_snapshot_ack() -> bool:
            """Snapshot + ack the aligning barrier, then forward it
            downstream (barriers flow through the whole pipeline before
            any post-barrier data); returns stop flag."""
            snap = self.chain.snapshot(
                self.graph, savepoint=aligning.savepoint is not None)
            for r in self.routes:
                snap.update(r.snapshot(
                    self.graph, savepoint=aligning.savepoint is not None))
            self.coordinator.ack(aligning.checkpoint_id,
                                 ("keyed", self.stage_index, self.index),
                                 {"operators": snap})
            for r in self.routes:
                r.flush()
                r.broadcast(aligning)
            return aligning.stop

        def finish() -> None:
            """End of all inputs: flush remaining windows through the
            chain, forward downstream, and close the exchanges."""
            outs = self.chain.close()
            forward(outs)
            for r in self.routes:
                r.flush()
                r.broadcast(MAX_WATERMARK)
                r.close()

        def gate_slot(slot: int) -> Tuple[int, int]:
            for g in range(K - 1, -1, -1):
                if slot >= base[g]:
                    return g, slot - base[g]
            return 0, slot

        def ua_begin(item: Barrier) -> None:
            nonlocal ua, ua_snap, ua_barriered, ua_chan_state
            ua = item
            ua_barriered = [False] * total
            ua_chan_state = {}
            snap = self.chain.snapshot(self.graph, savepoint=False)
            for r in self.routes:
                snap.update(r.snapshot(self.graph, savepoint=False))
            ua_snap = snap
            # forward immediately: the barrier overtakes this subtask's
            # own output queues too, so downstream starts ITS unaligned
            # snapshot without waiting behind the exchange backlog
            for r in self.routes:
                r.flush()
                r.broadcast(item)

        def ua_maybe_complete() -> None:
            nonlocal ua, ua_snap
            if ua is None or not all(
                    ua_barriered[c] or done[c] for c in range(total)):
                return
            payload = {"operators": ua_snap}
            if ua_chan_state:
                payload["channel_state"] = {
                    str(slot): {f"b{i}": dict(b.columns)
                                for i, b in enumerate(batches)}
                    for slot, batches in ua_chan_state.items() if batches}
            self.coordinator.ack(ua.checkpoint_id,
                                 ("keyed", self.stage_index, self.index),
                                 payload)
            ua = None
            ua_snap = None

        if self._restore_channel_state:
            # in-flight batches an unaligned checkpoint persisted: they
            # were consumed from the channels AFTER the snapshot cut, so
            # on restore they replay through the operator first —
            # upstream's positions are already past them (no duplication)
            from flink_tpu.core.records import RecordBatch as _RB

            for slot_str in sorted(self._restore_channel_state, key=int):
                slot = int(slot_str)
                gi0, _ = gate_slot(slot)
                entry = self._restore_channel_state[slot_str]
                for bk in sorted(entry, key=lambda s: int(s[1:])):
                    process(_RB(entry[bk]), gi0, slot)

        ticks_pt = self.chain.uses_processing_time
        while True:
            self._serve_queries()
            if self.coordinator.cancelled.is_set():
                return
            if ticks_pt:
                self.chain.tick_processing_time(
                    int(time.time() * 1000),
                    emit=(self._emit_downstream if downstream else None))
            # non-blocking sweep of every gate first — an idle/exhausted
            # input must not throttle a live one; only when ALL gates are
            # empty does one (rotating) gate take a short blocking poll
            entry = None
            gi = poll_at
            for off in range(K):
                g = (poll_at + off) % K
                entry = gates[g].poll(timeout=0)
                if entry is not None:
                    gi = g
                    break
            if entry is None:
                gi = poll_at
                entry = gates[gi].poll(timeout=0.05)
            poll_at = (gi + 1) % K
            if entry is None:
                continue
            ch, item = entry
            slot = base[gi] + ch
            if isinstance(item, Barrier) and item.unaligned:
                if ua is None or ua.checkpoint_id != item.checkpoint_id:
                    ua_begin(item)
                ua_barriered[slot] = True
                ua_chan_state.setdefault(slot, []).extend(
                    gates[gi].take_inflight(ch, item.checkpoint_id))
                ua_maybe_complete()
                continue
            if isinstance(item, Barrier):
                if aligning is None:
                    aligning = item
                    barriered = [False] * total
                barriered[slot] = True
                if all(barriered[c] or done[c] for c in range(total)):
                    # all channels of all gates aligned: snapshot + ack,
                    # then drain the buffered post-barrier items
                    if aligned_snapshot_ack():
                        stopping = True
                    aligning = None
                    for bgi, bslot, bitem in buffered:
                        process(bitem, bgi, bslot)
                    buffered = []
                    if stopping:
                        # stop-with-savepoint: close WITHOUT forwarding —
                        # post-savepoint output would duplicate on resume
                        self.chain.close()
                        for r in self.routes:
                            r.close()
                        return
                continue
            if item is END_OF_PARTITION:
                done[slot] = True
                ua_maybe_complete()
                if aligning is not None and all(
                        barriered[c] or done[c] for c in range(total)):
                    stop = aligned_snapshot_ack()
                    if stop:
                        # stop-with-savepoint completed by an EOP: stop
                        # exactly like the barrier-completion branch —
                        # post-savepoint output would duplicate on resume
                        aligning = None
                        self.chain.close()
                        for r in self.routes:
                            r.close()
                        return
                    aligning = None
                    for bgi, bslot, bitem in buffered:
                        process(bitem, bgi, bslot)
                    buffered = []
                if all(done):
                    if MAX_WATERMARK > combined:
                        forward(self.chain.process_watermark(
                            MAX_WATERMARK))
                    finish()
                    return
                # a finished channel no longer constrains the watermark
                new = combined_wm()
                if new > combined:
                    combined = new
                    forward(self.chain.process_watermark(combined))
                    for r in self.routes:
                        r.broadcast(int(combined))
                continue
            if aligning is not None and barriered[slot]:
                # aligned-barrier blocking: post-barrier data waits until
                # alignment completes (bounded by channel credits)
                buffered.append((gi, slot, item))
                continue
            if ua is not None and not ua_barriered[slot] and \
                    isinstance(item, RecordBatch):
                # unaligned in progress: pre-barrier data from channels
                # whose barrier has not arrived is BOTH processed (live
                # run continues) and copied into channel state (it is not
                # covered by the already-taken operator snapshot)
                ua_chan_state.setdefault(slot, []).append(item)
            process(item, gi, slot)

    def _serve_queries(self) -> None:
        while True:
            try:
                req = self.control.get_nowait()
            except _q.Empty:
                return
            op_name, key, namespace, reply = req
            try:
                result = None
                for t, op in zip(self.chain.transformations,
                                 self.chain.operators):
                    if t.name != op_name:
                        continue
                    if op is None or not hasattr(op, "query_state"):
                        # same contract as LocalExecutor._serve_query:
                        # a known-but-stateless operator is an ERROR,
                        # not a silent [None]*n answer
                        raise RuntimeError(
                            f"operator {op_name!r} has no queryable "
                            "state")
                    if isinstance(key, list):
                        # batched form: this subtask's whole slice of
                        # the request served by one gather + one
                        # device read (query_state_batch)
                        if hasattr(op, "query_state_batch"):
                            result = op.query_state_batch(key, namespace)
                        else:
                            result = [op.query_state(k, namespace)
                                      for k in key]
                    else:
                        result = op.query_state(key, namespace)
                    break
                reply.put((result, None))
            except BaseException as e:  # noqa: BLE001
                reply.put((None, e))


class _Coordinator:
    """Checkpoint + failure coordination for one stage-parallel job run."""

    def __init__(self, num_acks: int):
        self.num_acks = num_acks
        self.cancelled = threading.Event()
        self.failure: Optional[BaseException] = None
        self._acks: Dict[int, Dict[Tuple[str, int], Dict]] = {}
        self._complete: Dict[int, threading.Event] = {}
        self._lock = threading.Lock()

    def expect(self, checkpoint_id: int) -> threading.Event:
        with self._lock:
            self._acks[checkpoint_id] = {}
            ev = self._complete[checkpoint_id] = threading.Event()
            return ev

    def ack(self, checkpoint_id: int, who: Tuple[str, int],
            snap: Dict) -> None:
        with self._lock:
            acks = self._acks.get(checkpoint_id)
            if acks is None or who in acks:
                # first ack wins: a synthesized end-of-split ack must never
                # replace a real barrier-cut ack (their positions differ)
                return
            acks[who] = snap
            if len(acks) >= self.num_acks:
                self._complete[checkpoint_id].set()

    def collected(self, checkpoint_id: int) -> Dict[Tuple[str, int], Dict]:
        with self._lock:
            return self._acks.pop(checkpoint_id, {})

    def subtask_failed(self, subtask, error: BaseException) -> None:
        self.failure = self.failure or error
        self.cancelled.set()
        with self._lock:
            for ev in self._complete.values():
                ev.set()


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


@internal
class StageParallelExecutor:
    """Same run() contract as LocalExecutor, executing via subtask
    expansion (reference: Execution.deploy — but subtasks here are threads
    wired by the Shuffle SPI; a cross-process transport plugs in via
    ``shuffle.service``)."""

    def __init__(self, config: Optional[Configuration] = None,
                 shuffle_service=None):
        self.config = config or Configuration()
        self._shuffle = shuffle_service

    def run(self, graph: StreamGraph, job_name: str = "job",
            restore_from: Optional[str] = None, cancel_event=None,
            restore_mode: str = "no-claim", control_queue=None):
        from flink_tpu.datastream.environment import JobExecutionResult

        self._cancel_event = cancel_event
        from flink_tpu.core.config import ExecutionModeOptions

        plan = plan_stages(graph)
        src_specs = plan.source_specs
        K = len(src_specs)
        cfg = self.config
        N = cfg.get(DeploymentOptions.STAGE_PARALLELISM)
        S = cfg.get(DeploymentOptions.SOURCE_PARALLELISM)
        max_par = cfg.get(CoreOptions.MAX_PARALLELISM)
        batch_size = cfg.get(BatchOptions.BATCH_SIZE)
        batch_mode = cfg.get(
            ExecutionModeOptions.RUNTIME_MODE) == "batch"
        for spec in src_specs:
            if batch_mode and not getattr(spec.source.source, "bounded",
                                          True):
                raise RuntimeError(
                    "execution.runtime-mode=batch requires bounded "
                    f"sources; {spec.source.name!r} is unbounded")
        if N == -1:
            # adaptive batch parallelism (reference:
            # AdaptiveBatchScheduler decides downstream parallelism from
            # PRODUCED partition volume, not a plan-time guess). Bounded
            # sources are replayable by contract (open() rewinds — see
            # connectors/source_v2.py reset + tests/test_source_v2.py),
            # so the volume is MEASURED with a metering pass through each
            # source; estimate_records() is only the fallback when a
            # source cannot be metered. A wrong or absent estimate
            # therefore cannot missize the stage (it previously silently
            # fell to N=1).
            if not batch_mode:
                raise StagePlanError(
                    "execution.stage-parallelism=-1 (adaptive) requires "
                    "execution.runtime-mode=batch")
            target = cfg.get(
                ExecutionModeOptions.TARGET_RECORDS_PER_SUBTASK)
            if target < 1:
                raise StagePlanError(
                    "execution.batch.target-records-per-subtask must be "
                    f">= 1, got {target}")
            est = 0
            for spec in src_specs:
                src = spec.source.source
                try:
                    src.open(0, 1)
                    meter = 0
                    while True:
                        b = src.poll_batch(1 << 16)
                        if b is None:
                            break
                        meter += len(b)
                    est += meter
                except Exception:
                    est += int(getattr(src, "estimate_records",
                                       lambda: 0)() or 0)
            N = max(1, min(-(-int(est) // target) if est else 1, max_par))
        if N < 1:
            raise StagePlanError("execution.stage-parallelism must be >= 1")

        shuffle = self._shuffle or create_shuffle_service(
            cfg.get(DeploymentOptions.SHUFFLE_SERVICE))
        credits = cfg.get(DeploymentOptions.SHUFFLE_CREDITS)

        ckpt_dir = cfg.get(StateOptions.CHECKPOINT_DIR)
        ckpt_interval = cfg.get(CheckpointOptions.INTERVAL_MS)
        ckpt_every_n = cfg.get(CheckpointOptions.EVERY_N_BATCHES)
        storage = None
        if ckpt_dir and (ckpt_interval or ckpt_every_n):
            from flink_tpu.checkpoint.storage import CheckpointStorage

            storage = CheckpointStorage(
                ckpt_dir, compress=cfg.get(CheckpointOptions.COMPRESSION))

        # restore
        checkpoint_id = 0
        restore_states: Dict[str, Any] = {}
        restore_positions: Dict[int, Any] = {}
        restore_channel_state: Dict[Tuple[int, int], Dict[str, Any]] = {}
        if restore_from is not None:
            from flink_tpu.checkpoint.savepoint import prepare_restore
            from flink_tpu.checkpoint.storage import (
                read_checkpoint_chain,
                read_manifest,
            )

            snap_dir, _ = prepare_restore(restore_from, restore_mode,
                                          own_checkpoint_root=ckpt_dir)
            states = read_checkpoint_chain(snap_dir)
            checkpoint_id = int(read_manifest(snap_dir)["checkpoint_id"])
            src_ids = {graph.stable_id(spec.source): i
                       for i, spec in enumerate(src_specs)}
            known_ids = {graph.stable_id(t)
                         for spec in src_specs
                         for t in spec.transformations
                         if t.operator_factory is not None}
            known_ids.update(
                graph.stable_id(t)
                for stage in plan.stages
                for t in stage.operator_transformations
                if t.operator_factory is not None)
            for sid, state in states.items():
                if sid.startswith("__channel_state__."):
                    _, m_s, j_s, slot_s = sid.rsplit(".", 3)
                    m_i, j_i = int(m_s), int(j_s)
                    if j_i >= N:
                        raise RuntimeError(
                            "unaligned checkpoint holds channel state for "
                            f"subtask {j_i} but execution.stage-parallelism "
                            f"is {N} — restore with the original "
                            "parallelism")
                    restore_channel_state.setdefault(
                        (m_i, j_i), {})[slot_s] = state
                    continue
                if sid in src_ids:
                    pos = state["source"]
                    if isinstance(pos, dict) and "__subtasks__" in pos:
                        per_sub = {int(k): v
                                   for k, v in pos["__subtasks__"].items()}
                        if len(per_sub) != S:
                            raise RuntimeError(
                                "snapshot has positions for "
                                f"{len(per_sub)} source subtasks "
                                f"but execution.source-parallelism is {S} "
                                "— source splits cannot be re-assigned "
                                "across counts (restore with the original "
                                "source parallelism)")
                    else:
                        if S != 1:
                            raise RuntimeError(
                                "snapshot has a single source position "
                                f"but execution.source-parallelism is {S}")
                        per_sub = {0: pos}
                    restore_positions[src_ids[sid]] = per_sub
                elif sid in known_ids:
                    restore_states[sid] = state
                else:
                    # the reference fails on non-restored state by default
                    # (allowNonRestoredState opt-in); dropping it silently
                    # would e.g. restart a renamed source from record 0
                    raise RuntimeError(
                        "checkpoint contains state for operators not "
                        "present in the graph (graph changed since "
                        f"snapshot?): {sid!r}")
            if storage is not None:
                checkpoint_id = max(
                    checkpoint_id, storage.latest_checkpoint_id() or 0)

        M = len(plan.stages)
        coordinator = _Coordinator(num_acks=K * S + M * N)

        # wire exchanges: every OutSpec of every producer is one
        # exchange; producer subtask p owns one partition with N
        # subpartitions, and the consuming stage's subtask j reads
        # subpartition j of every producer partition through one gate
        # per stage INPUT (ordered by the head operator's input index).
        # (reference: IntermediateResultPartition / InputGate wiring in
        # the ExecutionGraph.)
        exchanges = []  # (producer kind, producer idx, out_spec)
        for i, spec in enumerate(src_specs):
            for o in spec.outputs:
                exchanges.append(("src", i, o))
        for m, stage in enumerate(plan.stages):
            for o in stage.outputs:
                exchanges.append(("stage", m, o))

        def xpid(eid: int, p: int) -> str:
            return f"{job_name}-x{eid}-{p}"

        #: eid -> list of per-producer-subtask partition writers
        x_writers: Dict[int, list] = {}
        #: (target_stage, target_input) -> eid
        x_target: Dict[Tuple[int, int], int] = {}
        for eid, (kind, idx, o) in enumerate(exchanges):
            p_count = S if kind == "src" else N
            x_writers[eid] = [
                shuffle.create_partition(xpid(eid, p), N, credits)
                for p in range(p_count)]
            x_target[(o.target_stage, o.target_input)] = eid
        #: stage m, subtask j -> gates ordered by input index
        stage_gates = {
            m: [[shuffle.create_gate(
                [xpid(x_target[(m, i)], p)
                 for p in range(len(x_writers[x_target[(m, i)]]))], j)
                for i in range(stage.num_inputs)]
                for j in range(N)]
            for m, stage in enumerate(plan.stages)}

        combiner_factory = None
        if K == 1 and len(src_specs[0].outputs) == 1 and \
                src_specs[0].outputs[0].target_stage == 0 and \
                not src_specs[0].outputs[0].branch and \
                cfg.get(DeploymentOptions.LOCAL_AGG):
            combiner_factory = _local_combiner_factory(plan)

        def make_routes(kind: str, idx: int, outs: List[OutSpec],
                        sub: int, ctx: OperatorContext,
                        with_combiner: bool = False) -> List[_OutputRoute]:
            routes = []
            for o in outs:
                eid = next(e for e, (k2, i2, o2) in enumerate(exchanges)
                           if k2 == kind and i2 == idx and o2 is o)
                routes.append(_OutputRoute(
                    o, x_writers[eid][sub], N, max_par, ctx,
                    batch_mode=batch_mode, batch_size=batch_size,
                    combiner=(combiner_factory()
                              if with_combiner and combiner_factory
                              else None),
                    recompute_key_id=(kind == "stage")))
            return routes

        sources = []
        import copy as _copy

        for i, spec in enumerate(src_specs):
            per_src_pos = restore_positions.get(i, {})
            for s in range(S):
                src = spec.source.source if S == 1 else _copy.deepcopy(
                    spec.source.source)
                ctx = OperatorContext(operator_index=s, parallelism=1,
                                      max_parallelism=max_par)
                sources.append(_SourceSubtask(
                    s, S, spec, graph,
                    make_routes("src", i, spec.outputs, s, ctx,
                                with_combiner=(i == 0)),
                    max_par, batch_size, coordinator, src,
                    restore_position=per_src_pos.get(s),
                    batch_mode=batch_mode,
                    source_index=i))
        shared_sinks: Dict[int, _SharedSink] = {}
        mesh_devices = cfg.get(DeploymentOptions.STAGE_MESH_DEVICES)
        memory_manager = None
        device_budget = cfg.get(StateOptions.DEVICE_MEMORY_BUDGET)
        if device_budget:
            from flink_tpu.core.memory import MemoryManager

            # one pool across every subtask of the job (they share the
            # process's device)
            memory_manager = MemoryManager(device_budget)
        keyed: List[_KeyedSubtask] = []
        for m, stage in enumerate(plan.stages):
            for j in range(N):
                ctx = OperatorContext(operator_index=j, parallelism=1,
                                      max_parallelism=max_par)
                keyed.append(_KeyedSubtask(
                    j, N, stage, graph, stage_gates[m][j],
                    max_par, coordinator, cfg,
                    shared_sinks=shared_sinks, stage_index=m,
                    routes=make_routes("stage", m, stage.outputs, j, ctx),
                    mesh_devices=mesh_devices,
                    memory_manager=memory_manager))
        for k in keyed:
            if restore_states:
                k._restore_states = restore_states
            cs = restore_channel_state.get((k.stage_index, k.index))
            if cs:
                k._restore_channel_state = cs
        for t in keyed + sources:
            t.start()

        t0 = time.perf_counter()
        savepoint_path = None
        last_ckpt = time.time() * 1000
        last_batches = 0
        try:
            while any(t.is_alive() for t in sources + keyed):
                if cancel_event is not None and cancel_event.is_set():
                    coordinator.cancelled.set()
                    if isinstance(shuffle, LocalShuffleService):
                        shuffle.cancel()
                    from flink_tpu.cluster.local_executor import (
                        JobCancelledError,
                    )

                    raise JobCancelledError(job_name)
                if coordinator.failure is not None:
                    raise coordinator.failure
                # user control: savepoints / queries
                if control_queue is not None:
                    sp = self._serve_control(
                        control_queue, plan, graph, sources, keyed,
                        coordinator, storage, ckpt_dir, job_name,
                        checkpoint_id)
                    if sp is not None:
                        checkpoint_id, savepoint_path, stopped = sp
                        if stopped:
                            break
                # periodic checkpoints (time interval or deterministic
                # every-N-source-batches, like the single-slot executor)
                if storage is not None and any(
                        s.is_alive() for s in sources):
                    total_batches = sum(s.batches_polled for s in sources)
                    due = (ckpt_every_n and total_batches - last_batches
                           >= ckpt_every_n) or (
                        not ckpt_every_n and ckpt_interval
                        and time.time() * 1000 - last_ckpt >= ckpt_interval)
                    if due:
                        checkpoint_id += 1
                        self._checkpoint(
                            checkpoint_id,
                            Barrier(checkpoint_id,
                                    unaligned=cfg.get(
                                        CheckpointOptions.UNALIGNED)),
                            sources, keyed, coordinator, graph, plan,
                            storage=storage, job_name=job_name)
                        last_ckpt = time.time() * 1000
                        last_batches = total_batches
                time.sleep(0.01)
            if coordinator.failure is not None:
                raise coordinator.failure
            for t in sources + keyed:
                t.join(timeout=30)
                if t.error is not None:
                    raise t.error
        except BaseException:
            coordinator.cancelled.set()
            if isinstance(shuffle, LocalShuffleService):
                shuffle.cancel()
            for t in sources + keyed:
                t.join(timeout=5)
            for k in keyed:
                if k.chain is not None:
                    k.chain.dispose()
            raise
        finally:
            if control_queue is not None:
                from flink_tpu.cluster.local_executor import _ControlRequest

                try:
                    while True:
                        req = control_queue.get_nowait()
                        if isinstance(req, _ControlRequest):
                            req.finish(None, RuntimeError(
                                f"job {job_name!r} terminated"))
                except _q.Empty:
                    pass

        elapsed = time.perf_counter() - t0
        total = sum(s.records_polled for s in sources)
        metrics = {
            "records": total,
            "elapsed_s": elapsed,
            "records_per_s": total / elapsed if elapsed else 0.0,
            "stage_parallelism": N,
            "source_parallelism": S,
            # rows that actually crossed the keyed exchange (< records
            # when the local combiner collapsed them — the two-phase win)
            "records_shuffled": sum(s.records_out for s in sources),
            "subtask_records_in": [k.records_in for k in keyed
                                   if k.stage_index == 0],
            **({"keyed_stages": M,
                "per_stage_records_in": [
                    [k.records_in for k in keyed if k.stage_index == m]
                    for m in range(M)]} if M > 1 else {}),
        }
        if savepoint_path:
            metrics["savepoint"] = savepoint_path
        return JobExecutionResult(job_name, metrics)

    # ------------------------------------------------------------- control

    def _serve_control(self, control_queue, plan, graph, sources, keyed,
                       coordinator, storage, ckpt_dir, job_name,
                       checkpoint_id):
        from flink_tpu.cluster.local_executor import (
            SavepointRequest,
            StateQueryBatchRequest,
            StateQueryRequest,
        )

        try:
            req = control_queue.get_nowait()
        except _q.Empty:
            return None

        def _stage_of(operator_name: str) -> int:
            # same contract as LocalExecutor._serve_query: an unknown
            # operator raises (naming what exists) rather than silently
            # routing to stage 0 and answering [None]*n — "no such
            # operator" and "key has no state" must stay distinct errors
            for m, stage in enumerate(plan.stages):
                if any(t.name == operator_name
                       for t in stage.operator_transformations):
                    return m
            raise KeyError(
                f"no operator named {operator_name!r}; available: "
                f"{sorted(t.name for stage in plan.stages for t in stage.operator_transformations)}")

        if isinstance(req, StateQueryBatchRequest):
            try:
                from flink_tpu.state.keygroups import hash_keys_to_i64

                stage_index = _stage_of(req.operator_name)
                N = sum(1 for k in keyed if k.stage_index == stage_index)
                mp = self.config.get(CoreOptions.MAX_PARALLELISM)
                key_ids = hash_keys_to_i64(np.asarray(req.keys))
                owners = key_group_to_operator_index(
                    assign_key_groups(key_ids, mp), mp, N)
                # one batched control message per OWNING subtask: each
                # serves its slice with one gather + one device read
                results: list = [None] * len(req.keys)
                pending = []
                for owner in sorted(set(int(o) for o in owners)):
                    sel = [i for i, o in enumerate(owners)
                           if int(o) == owner]
                    reply: _q.Queue = _q.Queue()
                    keyed[stage_index * N + owner].control.put(
                        (req.operator_name,
                         [req.keys[i] for i in sel],
                         req.namespace, reply))
                    pending.append((sel, reply))
                err = None
                for sel, reply in pending:
                    part, e = reply.get(timeout=30)
                    if e is not None:
                        err = err or e
                        continue
                    for i, r in zip(sel, part or []):
                        results[i] = r
                req.finish(None if err else results, err)
            except BaseException as e:  # noqa: BLE001
                req.finish(None, e)
            return None
        if isinstance(req, StateQueryRequest):
            try:
                from flink_tpu.state.keygroups import (
                    hash_keys_to_i64,
                )

                # the operator names ONE stage; route to that stage's
                # owning subtask (keyed is stage-major: m * N + j)
                stage_index = _stage_of(req.operator_name)
                N = sum(1 for k in keyed if k.stage_index == stage_index)
                key_id = int(hash_keys_to_i64(
                    np.asarray([req.key]))[0])
                group = int(assign_key_groups(
                    np.asarray([key_id]),
                    self.config.get(CoreOptions.MAX_PARALLELISM))[0])
                owner = int(key_group_to_operator_index(
                    np.asarray([group]),
                    self.config.get(CoreOptions.MAX_PARALLELISM),
                    N)[0])
                reply: _q.Queue = _q.Queue()
                keyed[stage_index * N + owner].control.put(
                    (req.operator_name, req.key, req.namespace, reply))
                result, err = reply.get(timeout=30)
                req.finish(result, err)
            except BaseException as e:  # noqa: BLE001
                req.finish(None, e)
            return None
        if isinstance(req, SavepointRequest):
            try:
                new_id = checkpoint_id + 1
                path = self._checkpoint(
                    new_id, Barrier(new_id, savepoint=req.path,
                                    stop=req.stop),
                    sources, keyed, coordinator, graph, plan,
                    savepoint_dir=req.path, job_name=job_name)
                req.finish(path)
                return (new_id, path, req.stop)
            except BaseException as e:  # noqa: BLE001
                req.finish(None, e)
                return None
        req.finish(None, RuntimeError(f"unsupported control {req!r}"))
        return None

    # ---------------------------------------------------------- checkpoint

    def _checkpoint(self, checkpoint_id: int, barrier: Barrier, sources,
                    keyed, coordinator, graph, plan,
                    storage=None, savepoint_dir=None, job_name="job"):
        """Trigger, await S+N acks, merge subtask states into the logical
        single-slot snapshot format, commit."""
        live_sources = [s for s in sources if s.is_alive()]
        if not live_sources:
            raise RuntimeError("cannot checkpoint: all sources finished")
        coordinator.num_acks = len(live_sources) + len(keyed)
        done = coordinator.expect(checkpoint_id)
        for s in live_sources:
            s.control.put(barrier)
        deadline = time.monotonic() + 120
        while not done.wait(timeout=0.1):
            # a source may have drained its split between the is_alive()
            # check and serving the trigger: synthesize its ack from the
            # recorded final position (the thread has exited — its chain
            # is safe to snapshot from here)
            for s in live_sources:
                if not s.is_alive() and s.final_position is not None:
                    coordinator.ack(
                        checkpoint_id,
                        ("source", s.source_index, s.index),
                        {"position": s.final_position,
                         "operators": s.snapshot_operators(graph)})
            # the run loop is parked here — cancellation and subtask death
            # must abort the checkpoint, not wait out the full deadline
            if coordinator.cancelled.is_set() or (
                    self._cancel_event is not None
                    and self._cancel_event.is_set()):
                from flink_tpu.cluster.local_executor import (
                    JobCancelledError,
                )

                raise JobCancelledError("cancelled during checkpoint")
            if coordinator.failure is not None:
                raise coordinator.failure
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"checkpoint {checkpoint_id} timed out")
        if coordinator.failure is not None:
            raise coordinator.failure
        acks = coordinator.collected(checkpoint_id)
        # assemble logical snapshot: per-source positions under each
        # physical source's own transformation id
        positions: Dict[int, Dict[int, Any]] = {}
        for who, sub in acks.items():
            if who[0] == "source":
                positions.setdefault(who[1], {})[who[2]] = sub["position"]
        # finished subtasks that were not in this trigger round still
        # contribute their end-of-split position — omitting them would
        # replay their whole split on restore
        for s in sources:
            per_input = positions.setdefault(s.source_index, {})
            if s.index not in per_input and s.final_position is not None:
                per_input[s.index] = s.final_position
        snap: Dict[str, Any] = {}
        source_parallelism = self.config.get(
            DeploymentOptions.SOURCE_PARALLELISM)
        for i, spec in enumerate(plan.source_specs):
            per_input = positions.get(i, {})
            # the wrap decision is per input from the CONFIGURED source
            # parallelism, not the observed position count — a missing
            # subtask position must fail the checkpoint precisely, not
            # produce a snapshot that later fails restore with a
            # misleading cross-count error
            if len(per_input) != source_parallelism:
                raise RuntimeError(
                    f"checkpoint {checkpoint_id} incomplete: input {i} "
                    f"has positions for {sorted(per_input)} but "
                    f"execution.source-parallelism is {source_parallelism}")
            # a single-subtask source stores its position unwrapped, so
            # the snapshot is restorable by the single-slot executor too;
            # S > 1 wraps per-subtask positions (stage-mode restore only)
            if source_parallelism == 1:
                source_state = {"source": per_input.get(0)}
            else:
                source_state = {"source": {"__subtasks__": {
                    str(s): p for s, p in per_input.items()}}}
            snap[graph.stable_id(spec.source)] = source_state
        per_operator: Dict[str, List[Dict]] = {}
        for who, sub in acks.items():
            for sid, state in sub.get("operators", {}).items():
                per_operator.setdefault(sid, []).append(state)
            if who[0] == "keyed" and sub.get("channel_state"):
                # in-flight batches an unaligned barrier overtook, keyed
                # by (stage, subtask, flat channel) — replayed on restore
                for slot, payload in sub["channel_state"].items():
                    snap[f"__channel_state__.{who[1]}.{who[2]}.{slot}"] = \
                        payload
        for sid, states in per_operator.items():
            snap[sid] = merge_subtask_states(states)
        if savepoint_dir is not None:
            from flink_tpu.checkpoint.savepoint import write_savepoint

            return write_savepoint(savepoint_dir, job_name, snap,
                                   checkpoint_id=checkpoint_id)
        if storage is not None:
            storage.write_checkpoint(checkpoint_id, job_name, snap)
            # bounded disk: same torn-aware GC as the single-slot
            # executor (state.checkpoints.num-retained; retention
            # anchors on VERIFIED checkpoints, so a torn newest never
            # strands the fallback chain)
            from flink_tpu.core.config import retained_checkpoints

            storage.retain(retained_checkpoints(self.config))
        return None


def make_executor(config: Configuration, graph: StreamGraph):
    """LocalExecutor unless ``execution.stage-parallelism`` is set AND the
    graph is expandable — shared by env.execute() and
    TaskExecutor.submit_task so local runs and cluster deployments pick
    the same engine (reference: the scheduler, not the API, decides the
    execution shape)."""
    from flink_tpu.cluster.local_executor import LocalExecutor

    sp = config.get(DeploymentOptions.STAGE_PARALLELISM)
    if sp == -1 or sp > 0:
        try:
            plan_stages(graph)
        except StagePlanError as e:
            if not config.get(DeploymentOptions.STAGE_FALLBACK):
                raise StagePlanError(
                    f"execution.stage-parallelism={sp} requested but {e}. "
                    "Set execution.stage-fallback=true to run single-slot "
                    "instead.") from e
            import warnings

            warnings.warn(
                f"execution.stage-parallelism set but {e}; running "
                "single-slot (execution.stage-fallback=true)",
                stacklevel=2)
            ex = LocalExecutor(config)
            ex.fallback_reason = str(e)
            return ex
        else:
            return StageParallelExecutor(config)
    return LocalExecutor(config)
