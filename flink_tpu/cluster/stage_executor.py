"""Stage-parallel execution: ExecutionGraph-style subtask expansion.

reference: the reference expands every JobVertex into `parallelism`
ExecutionVertex subtasks (executiongraph/DefaultExecutionGraph.java,
Execution.java:572 deploy()), routes records between them by key group
(streaming/runtime/partitioner/KeyGroupStreamPartitioner.java:55), and
aligns checkpoint barriers across input channels
(streaming/runtime/io/checkpointing/SingleCheckpointBarrierHandler.java).

Re-design: the job splits into two pipelined stages —

  source stage (S subtasks): source + chained stateless operators;
    each output batch is partitioned by key group into one sub-batch per
    keyed subtask and emitted through the Shuffle SPI
    (flink_tpu/runtime/shuffle_spi.py — pluggable transport, credit-based
    flow control).
  keyed stage (N subtasks): the keyed operator chain + sink; each subtask
    owns a key-group range and runs its own single-device engine instance.
    Watermarks combine per-channel (min across channels, the
    StatusWatermarkValve role); checkpoint Barriers ALIGN: channels that
    delivered the barrier are buffered until all channels have, then the
    subtask snapshots and acks (exactly the reference's aligned barrier
    dance — the in-flight buffer is bounded by the channel credit).

Checkpoints: a coordinator (the run() thread) triggers sources, collects
S + N acks, MERGES the per-subtask operator states into the same logical
format the single-slot executor writes (key-group-indexed rows), and
commits the manifest — so multi-slot checkpoints restore into single-slot
jobs, other subtask counts (key-group re-filtering), and vice versa.

This axis is COMPLEMENTARY to mesh parallelism: a keyed subtask could open
its operator over a device mesh; subtask expansion distributes across
slots/hosts (the reference's distribution model), the mesh distributes
across chips within one program (the SPMD model).
"""

from __future__ import annotations

import queue as _q
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.config import (
    BatchOptions,
    CheckpointOptions,
    Configuration,
    CoreOptions,
    DeploymentOptions,
    StateOptions,
)
from flink_tpu.core.records import RecordBatch
from flink_tpu.graph.transformations import StreamGraph, Transformation
from flink_tpu.runtime.operators import OperatorContext
from flink_tpu.runtime.shuffle_spi import (
    END_OF_PARTITION,
    Barrier,
    LocalShuffleService,
    create_shuffle_service,
)
from flink_tpu.runtime.elements import MAX_WATERMARK
from flink_tpu.state.keygroups import (
    assign_key_groups,
    compute_key_group_range,
    key_group_to_operator_index,
)

__all__ = ["StagePlan", "StagePlanError", "StageParallelExecutor",
           "plan_stages", "merge_subtask_states"]


from flink_tpu.core.annotations import internal

class StagePlanError(ValueError):
    """The graph shape is not supported by stage-parallel execution."""


class StageInput:
    """One input branch of the keyed stage: a source, its chained
    stateless pre-operators (incl. the key_by routing marker), and the
    key field records are hash-exchanged on."""

    def __init__(self, source: Transformation,
                 pre_chain: List[Transformation], key_field: str):
        self.source = source
        self.pre_chain = pre_chain
        self.key_field = key_field


class StagePlan:
    """Source stage(s) + one keyed stage. One input is the classic linear
    pipeline; two inputs is the join shape (two sources hash-exchanging
    into a two-input keyed operator — reference: DefaultExecutionGraph
    runs any DAG; this covers the two-input keyed family)."""

    def __init__(self, source: Optional[Transformation] = None,
                 pre_chain: Optional[List[Transformation]] = None,
                 keyed_chain: Optional[List[Transformation]] = None,
                 key_field: Optional[str] = None,
                 inputs: Optional[List[StageInput]] = None):
        if inputs is None:
            inputs = [StageInput(source, pre_chain or [], key_field)]
        #: one StageInput per keyed-stage input, in the keyed head
        #: operator's input order
        self.inputs = inputs
        #: keyed operator + everything downstream incl. the sink, chained
        #: into each keyed subtask
        self.keyed_chain = keyed_chain or []

    # single-input views (the linear pipeline's vocabulary)
    @property
    def source(self) -> Transformation:
        return self.inputs[0].source

    @property
    def pre_chain(self) -> List[Transformation]:
        return self.inputs[0].pre_chain

    @property
    def key_field(self) -> str:
        return self.inputs[0].key_field


def plan_stages(graph: StreamGraph) -> StagePlan:
    """Derive the stage split from the chained JobGraph
    (flink_tpu/graph/job_graph.py — the StreamingJobGraphGenerator role).
    Supported shapes: a linear source-stage -> keyed-stage pipeline, and
    the two-input keyed shape (two sources, each key_by-routed into a
    two-input keyed head — joins/co-process). Raises StagePlanError for
    anything else (side outputs, broadcast edges, deeper DAGs) — callers
    fall back to single-slot execution when configured to."""
    from flink_tpu.graph.job_graph import HASH, build_job_graph

    jg = build_job_graph(graph, default_parallelism=1,
                         respect_parallelism=False)
    if not any(e.ship == HASH for e in jg.edges):
        raise StagePlanError("no keyed exchange — nothing to expand")
    if len(graph.sources) == 2:
        return _plan_two_input(graph, jg)
    if len(graph.sources) != 1:
        raise StagePlanError(
            "multi-slot mode supports one source (linear pipeline) or "
            f"two (keyed join); this graph has {len(graph.sources)}")
    if len(jg.vertices) != 2 or len(jg.edges) != 1:
        raise StagePlanError(
            "multi-slot mode supports a linear source-stage -> "
            "keyed-stage pipeline; this job graph has "
            f"{len(jg.vertices)} vertices / {len(jg.edges)} exchanges: "
            + "; ".join(f"[{v.name}]" for v in jg.vertices))
    edge = jg.edges[0]
    src_v = jg.vertices[edge.source_vid]
    keyed_v = jg.vertices[edge.target_vid]
    if not src_v.is_source:
        raise StagePlanError("the exchange's producer stage must begin "
                             "at the source")
    if keyed_v.tail.kind != "sink":
        raise StagePlanError("pipeline must end in a sink")
    return StagePlan(src_v.head, src_v.chained[1:], keyed_v.chained,
                     edge.key_field)


def _plan_two_input(graph: StreamGraph, jg) -> StagePlan:
    """The join shape: src -> key_by(k_l) \\
                                            two-input keyed op -> sink
                       src -> key_by(k_r) /
    Each input's key_by marker (and any stateless ops chained around it)
    runs source-side; the hash exchange routes on that input's key field;
    the two-input operator + downstream run in the keyed subtasks."""
    from flink_tpu.runtime.operators import KeyByOperator

    two_in = [v for v in jg.vertices if v.head.kind == "two_input"]
    if len(two_in) != 1:
        raise StagePlanError(
            "two-source stage mode requires exactly one two-input keyed "
            f"operator; found {len(two_in)}")
    kv = two_in[0]
    if kv.tail.kind != "sink":
        raise StagePlanError("pipeline must end in a sink")
    head = kv.head
    if not head.keyed:
        raise StagePlanError(
            f"two-input operator {head.name!r} is not keyed — only keyed "
            "two-input stages shard by key group")
    if len(jg.vertices) != 5:
        raise StagePlanError(
            "two-source stage mode supports exactly src -> key_by -> "
            f"join -> sink per branch; this job graph has "
            f"{len(jg.vertices)} vertices: "
            + "; ".join(f"[{v.name}]" for v in jg.vertices))
    inputs: List[StageInput] = []
    for in_t in head.inputs:
        mv = jg.vertex_of(in_t)
        if mv.vid == kv.vid or mv.tail.uid != in_t.uid:
            raise StagePlanError(
                f"join input {in_t.name!r} is not the tail of its own "
                "stage vertex")
        probe = (mv.head.operator_factory()
                 if mv.head.operator_factory else None)
        if not isinstance(probe, KeyByOperator) or \
                mv.head.key_field is None:
            raise StagePlanError(
                "each join input must be keyed (key_by -> join); input "
                f"vertex [{mv.name}] does not start at a key_by marker")
        feeders = [e for e in jg.edges if e.target_vid == mv.vid]
        if len(feeders) != 1:
            raise StagePlanError(
                f"join input vertex [{mv.name}] must have exactly one "
                "producer")
        sv = jg.vertices[feeders[0].source_vid]
        if not sv.is_source:
            raise StagePlanError(
                f"join input [{mv.name}] must begin at a source")
        inputs.append(StageInput(sv.head,
                                 sv.chained[1:] + mv.chained,
                                 mv.head.key_field))
    return StagePlan(inputs=inputs, keyed_chain=kv.chained)


# ---------------------------------------------------------------------------
# state merge (per-subtask -> logical single-slot format)
# ---------------------------------------------------------------------------


def _merge_changelog(values: List[Dict[str, Any]]) -> Dict[str, Any]:
    """GroupAgg changelog rows: concatenate, with per-subtask 'last' column
    sets unioned — a subtask that has not emitted yet has no last-image
    columns, and its rows (all emitted=False) get identity fill."""
    kid = [np.asarray(v["key_id"]) for v in values]
    cols = set()
    for v in values:
        cols.update(v.get("last", {}).keys())
    last: Dict[str, np.ndarray] = {}
    for c in sorted(cols):
        dt = next(np.asarray(v["last"][c]).dtype for v in values
                  if c in v.get("last", {}))
        last[c] = np.concatenate([
            np.asarray(v["last"][c]) if c in v.get("last", {})
            else np.zeros(len(k), dtype=dt)
            for v, k in zip(values, kid)])
    return {
        "key_id": np.concatenate(kid),
        "count": np.concatenate([np.asarray(v["count"]) for v in values]),
        "emitted": np.concatenate([np.asarray(v["emitted"])
                                   for v in values]),
        "dirty": np.concatenate([
            np.asarray(v.get("dirty", np.zeros(len(k), bool)))
            for v, k in zip(values, kid)]),
        "last": last,
    }


def _merge_values(key: str, values: List[Any]):
    """Merge one state field across subtasks by its semantic kind."""
    if key in ("watermark", "max_fired_end", "max_ts", "next_sid",
               "max_fired_watermark"):
        return max(values)
    if key == "late_records_dropped":
        return sum(values)
    if key == "keys_hashed":
        return any(values)
    if key == "pending":
        return sorted({x for v in values for x in v})
    if key in ("slice_last_window", "sessions", "key_values"):
        merged: Dict = {}
        for v in values:
            merged.update(v)
        return merged
    if key == "changelog":
        return _merge_changelog(values)
    if key in ("left", "right"):
        # interval-join side buffers: lists of column dicts, key-group
        # disjoint across subtasks — union by concatenating the lists
        return [c for v in values for c in v]
    if key == "buf":
        # window-join per-slice side buffers: {slice_end: ([left column
        # dicts], [right column dicts])} — union per slice end
        out: Dict[int, Tuple[List, List]] = {}
        for v in values:
            for se, (l, r) in v.items():
                cur = out.setdefault(se, ([], []))
                cur[0].extend(l)
                cur[1].extend(r)
        return out
    if isinstance(values[0], np.ndarray):
        return np.concatenate([np.asarray(v) for v in values])
    if isinstance(values[0], dict):
        # dict-of-arrays (table leaves) / nested metadata: merge per field
        return {sub: _merge_values(sub, [v[sub] for v in values])
                for sub in values[0]}
    # scalars expected identical (e.g. format flags)
    return values[0]


def merge_subtask_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Union the per-subtask snapshots of ONE operator into the logical
    single-slot format. Table rows (key-group disjoint across subtasks)
    concatenate; metadata merges by kind (max watermarks, union dicts)."""
    states = [s for s in states if s]
    if not states:
        return {}
    if len(states) == 1:
        return states[0]
    return {k: _merge_values(k, [s[k] for s in states])
            for k in states[0]}


# ---------------------------------------------------------------------------
# subtasks
# ---------------------------------------------------------------------------


class _SubtaskFailure(Exception):
    pass


class _SharedSink:
    """Thread-safe facade over ONE sink instance shared by N keyed
    subtasks: writes serialize under a lock, and the underlying sink opens
    once / closes only when the last subtask closes (the reference deploys
    a sink INSTANCE per subtask; collect-style sinks here aggregate in one
    object, so sharing + refcounting is the honest equivalent)."""

    def __init__(self, sink):
        self._sink = sink
        self._lock = threading.Lock()
        self._opens = 0
        self._closes = 0
        self._closed = False

    def open(self, subtask_index: int = 0) -> None:
        with self._lock:
            if self._opens == 0:
                self._sink.open(0)
            self._opens += 1

    def write(self, batch) -> None:
        with self._lock:
            self._sink.write(batch)

    def close(self) -> None:
        with self._lock:
            self._closes += 1
            if self._closes >= self._opens and not self._closed:
                self._closed = True
                self._sink.close()

    def __getattr__(self, name):
        return getattr(self._sink, name)


class _OperatorChain:
    """The fused operator chain of one subtask (reference: OperatorChain —
    direct method-call hand-off between chained operators)."""

    def __init__(self, transformations: Sequence[Transformation],
                 ctx: OperatorContext,
                 shared_sinks: Optional[Dict[int, _SharedSink]] = None):
        self.transformations = list(transformations)
        self.operators = []
        for t in self.transformations:
            op = t.operator_factory() if t.operator_factory else None
            if op is not None:
                if shared_sinks is not None and hasattr(op, "sink"):
                    # every subtask's factory captured the same sink
                    # object — route all of them through one refcounted,
                    # locked facade (see _SharedSink)
                    op.sink = shared_sinks.setdefault(
                        t.uid, _SharedSink(op.sink))
                op.open(ctx)
            self.operators.append(op)

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        outs = [batch]
        head = True
        for op in self.operators:
            if op is None:
                continue
            nxt: List[RecordBatch] = []
            for b in outs:
                # only the chain HEAD can be multi-input (a two-input
                # keyed op); everything downstream consumes its single
                # output stream
                nxt.extend(op.process_batch(b, input_index if head else 0))
            head = False
            outs = nxt
            if not outs:
                break
        return outs

    def process_watermark(self, wm: int) -> None:
        carried: List[RecordBatch] = []
        for op in self.operators:
            if op is None:
                continue
            for b in carried:
                op.process_batch(b)
            carried = op.process_watermark(wm)
        # trailing emissions past the last operator are dropped only if the
        # last op emitted (sinks emit nothing)

    @property
    def uses_processing_time(self) -> bool:
        return any(getattr(op, "uses_processing_time", False)
                   for op in self.operators if op is not None)

    def tick_processing_time(self, now_ms: int, emit=None) -> None:
        """Wall-clock tick: fire processing-time windows/timers and push
        their output through the rest of the chain. ``emit`` receives
        batches that survive past the LAST operator (source-stage chains
        end at the keyed exchange, not a sink)."""
        for i, op in enumerate(self.operators):
            if op is None or not getattr(op, "uses_processing_time", False):
                continue
            outs = op.on_processing_time(now_ms)
            for out in outs:
                cur = [out]
                for op2 in self.operators[i + 1:]:
                    if op2 is None:
                        continue
                    nxt: List[RecordBatch] = []
                    for b in cur:
                        nxt.extend(op2.process_batch(b))
                    cur = nxt
                    if not cur:
                        break
                if emit is not None:
                    for b in cur:
                        emit(b)

    def close(self) -> None:
        carried: List[RecordBatch] = []
        for op in self.operators:
            if op is None:
                continue
            for b in carried:
                op.process_batch(b)
            carried = op.close()

    def dispose(self) -> None:
        for op in self.operators:
            if op is not None:
                try:
                    op.dispose()
                except Exception:
                    pass

    def snapshot(self, graph: StreamGraph, savepoint: bool = False
                 ) -> Dict[str, Any]:
        snap = {}
        for t, op in zip(self.transformations, self.operators):
            if op is None:
                continue
            if savepoint and hasattr(op, "snapshot_state_savepoint"):
                state = op.snapshot_state_savepoint()
            else:
                state = op.snapshot_state()
            if state:
                snap[graph.stable_id(t)] = state
        return snap

    def restore(self, graph: StreamGraph, states: Dict[str, Any],
                key_group_filter=None) -> None:
        for t, op in zip(self.transformations, self.operators):
            if op is None:
                continue
            state = states.get(graph.stable_id(t))
            if state is None:
                continue
            if key_group_filter is None:
                op.restore_state(state)
                continue
            import inspect

            sig = inspect.signature(op.restore_state)
            if "key_group_filter" not in sig.parameters:
                # restoring the FULL merged state into every subtask would
                # silently duplicate keyed state (N× timer fires, N×
                # emissions) — fail precisely instead
                raise RuntimeError(
                    f"operator {t.name!r} ({type(op).__name__}) does not "
                    "support key-group-filtered restore; it cannot be "
                    "restored in stage-parallel mode (reference: keyed "
                    "state restore is key-group-range scoped)")
            op.restore_state(state, key_group_filter=key_group_filter)


def _local_combiner_factory(plan: StagePlan):
    """A () -> LocalWindowCombiner factory when the keyed stage starts
    with an aligned event-time window aggregation, else None. Introspects
    a throwaway operator instance (construction is cheap; open() is what
    builds device state)."""
    from flink_tpu.runtime.local_agg import LocalWindowCombiner
    from flink_tpu.runtime.operators import KeyByOperator, WindowAggOperator

    # the keyed chain opens with the key_by routing op; the aggregation
    # is the first operator after it
    head = None
    for t in plan.keyed_chain:
        if t.operator_factory is None:
            return None
        probe = t.operator_factory()
        if isinstance(probe, KeyByOperator):
            continue
        head = t
        break
    if head is None:
        return None
    if type(probe) is not WindowAggOperator:
        return None  # sessions (merging) and non-window heads: no combine
    if probe.assigner is None or probe.assigner.is_merging or \
            getattr(probe, "uses_processing_time", False):
        return None

    def factory():
        op = head.operator_factory()
        return LocalWindowCombiner(op.assigner, op.agg, op.key_field)

    return factory


class _SourceSubtask(threading.Thread):
    """One source-stage subtask: polls its source split, applies the
    pre-chain, partitions by key group, emits through the shuffle —
    optionally collapsing each batch to per-(key, slice) partial
    aggregates first (two-phase agg; flink_tpu/runtime/local_agg.py)."""

    def __init__(self, index: int, parallelism: int, spec: StageInput,
                 graph: StreamGraph, writer, num_keyed: int,
                 max_parallelism: int, batch_size: int,
                 coordinator: "_Coordinator", source,
                 restore_position=None, batch_mode: bool = False,
                 combiner=None, input_index: int = 0):
        self.combiner = combiner
        self.spec = spec
        self.input_index = input_index
        super().__init__(
            name=f"source-subtask-in{input_index}-{index}", daemon=True)
        #: bounded/batch execution: no intermediate watermarks, and
        #: sub-batches coalesce into bulk blocks per subpartition before
        #: emission (the SortMergeResultPartition role — batch shuffle
        #: optimizes for throughput, not latency)
        self.batch_mode = batch_mode
        self._pending: Dict[int, List[RecordBatch]] = {}
        self._pending_rows: Dict[int, int] = {}
        self.index = index
        self.parallelism = parallelism
        self.graph = graph
        self.writer = writer
        self.num_keyed = num_keyed
        self.max_parallelism = max_parallelism
        self.batch_size = batch_size
        self.coordinator = coordinator
        self.source = source
        self.restore_position = restore_position
        self.control: _q.Queue = _q.Queue()
        self.error: Optional[BaseException] = None
        self.wm_gen = spec.source.watermark_strategy.create()
        self.chain: Optional[_OperatorChain] = None
        self.records_out = 0
        self.records_polled = 0
        self.batches_polled = 0
        from flink_tpu.runtime.shuffle_spi import KeyGroupPartitioner

        # routes on the pre-hashed __key_id__ column (ints are identity
        # under hash_keys_to_i64), so routing and downstream state use the
        # same key identity
        self._partitioner = KeyGroupPartitioner("__key_id__",
                                                max_parallelism)
        #: position at exit — checkpoints after this subtask drains its
        #: split still record where it ended (restore must not replay it)
        self.final_position = None

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self.coordinator.subtask_failed(self, e)

    def _run(self) -> None:
        spec = self.spec
        ctx = OperatorContext(operator_index=self.index,
                              parallelism=1,
                              max_parallelism=self.max_parallelism)
        self.chain = _OperatorChain(spec.pre_chain, ctx)
        self.source.open(self.index, self.parallelism)
        if self.restore_position is not None:
            self.source.restore_position(self.restore_position)
        key_field = spec.key_field
        stopping = False
        ticks_pt = self.chain.uses_processing_time
        try:
            while not stopping:
                stopping = self._serve_control()
                if stopping:
                    break
                if self.coordinator.cancelled.is_set():
                    return
                if ticks_pt:
                    # pre-chain processing-time timers fire on the wall
                    # clock even between batches (parity with the
                    # single-slot executor's tick)
                    self.chain.tick_processing_time(
                        int(time.time() * 1000),
                        emit=lambda b: self._emit_partitioned(b, key_field))
                batch = self.source.poll_batch(self.batch_size)
                if batch is None:
                    break
                if len(batch) == 0:
                    continue
                self.batches_polled += 1
                self.records_polled += len(batch)
                batch = spec.source.watermark_strategy.assign_timestamps(
                    batch)
                wm = self.wm_gen.on_batch(batch)
                for out in self.chain.process_batch(batch):
                    self._emit_partitioned(out, key_field)
                if wm is not None and not self.batch_mode:
                    self.writer.broadcast_event(int(wm))
        finally:
            self.final_position = self.source.snapshot_position()
            self.source.close()
        self._flush_pending()
        # a barrier enqueued while this loop was finishing must still be
        # served (position + ack + in-band broadcast) before EOP — the
        # coordinator synthesizes acks only for barriers that arrive after
        # the thread is observably dead
        self._serve_control()
        self.writer.broadcast_event(MAX_WATERMARK)
        self.writer.close()

    def _emit_partitioned(self, batch: RecordBatch, key_field: str) -> None:
        from flink_tpu.state.keygroups import hash_keys_to_i64

        if key_field not in batch.columns:
            raise _SubtaskFailure(
                f"key field {key_field!r} missing from batch columns "
                f"{batch.names()}")
        if self.combiner is not None:
            # two-phase agg, local half: at most one row per (key, slice)
            # leaves this subtask per batch — hot keys collapse here
            # before they converge on the owning keyed subtask
            batch = self.combiner.combine(batch)
        if "__key_id__" not in batch.columns:
            batch = batch.with_column("__key_id__",
                                      hash_keys_to_i64(batch[key_field]))
        # the ONE keyBy routing implementation (reference:
        # KeyGroupStreamPartitioner.selectChannel)
        for sub, part in self._partitioner.partition(batch,
                                                     self.num_keyed):
            self.records_out += len(part)
            if not self.batch_mode:
                self.writer.emit(sub, part)
                continue
            # batch mode: coalesce into bulk blocks (fewer, larger
            # transfers — the batch-shuffle trade)
            self._pending.setdefault(sub, []).append(part)
            n = self._pending_rows.get(sub, 0) + len(part)
            if n >= self.batch_size:
                self.writer.emit(sub, RecordBatch.concat(
                    self._pending.pop(sub)))
                self._pending_rows[sub] = 0
            else:
                self._pending_rows[sub] = n

    def _flush_pending(self) -> None:
        for sub, parts in sorted(self._pending.items()):
            if parts:
                self.writer.emit(sub, RecordBatch.concat(parts))
        self._pending.clear()
        self._pending_rows.clear()

    def _serve_control(self) -> bool:
        """Returns True when the job should stop (stop-with-savepoint)."""
        stopping = False
        while True:
            try:
                trigger = self.control.get_nowait()
            except _q.Empty:
                return stopping
            barrier: Barrier = trigger
            snap = {"position": self.source.snapshot_position(),
                    "operators": self.chain.snapshot(
                        self.graph, savepoint=barrier.savepoint is not None)}
            self.coordinator.ack(barrier.checkpoint_id,
                                 ("source", self.input_index, self.index),
                                 snap)
            # coalesced batch-mode blocks hold pre-barrier records — they
            # must reach the channels BEFORE the barrier or they would be
            # cut out of the snapshot yet covered by the position
            self._flush_pending()
            self.writer.broadcast_event(barrier)
            if barrier.stop:
                stopping = True


class _KeyedSubtask(threading.Thread):
    """One keyed-stage subtask: owns a key-group range, consumes one gate
    PER INPUT with per-channel watermarking and aligned barriers spanning
    every channel of every gate (reference:
    SingleCheckpointBarrierHandler aligns across all input channels of a
    multi-input task)."""

    def __init__(self, index: int, parallelism: int, plan: StagePlan,
                 graph: StreamGraph, gates, max_parallelism: int,
                 coordinator: "_Coordinator", config: Configuration,
                 shared_sinks: Optional[Dict[int, _SharedSink]] = None):
        super().__init__(name=f"keyed-subtask-{index}", daemon=True)
        self.shared_sinks = shared_sinks
        self.index = index
        self.parallelism = parallelism
        self.plan = plan
        self.graph = graph
        #: one gate per keyed-stage input, in head-operator input order
        self.gates = list(gates) if isinstance(gates, (list, tuple)) \
            else [gates]
        self.max_parallelism = max_parallelism
        self.coordinator = coordinator
        self.config = config
        rng = compute_key_group_range(max_parallelism, parallelism, index)
        self.key_groups = range(rng.start, rng.end + 1)
        self.control: _q.Queue = _q.Queue()
        self.error: Optional[BaseException] = None
        self.chain: Optional[_OperatorChain] = None
        self.records_in = 0
        self._restore_states: Optional[Dict[str, Any]] = None

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self.coordinator.subtask_failed(self, e)

    def _run(self) -> None:
        ctx = OperatorContext(operator_index=self.index, parallelism=1,
                              max_parallelism=self.max_parallelism)
        self.chain = _OperatorChain(self.plan.keyed_chain, ctx,
                                    shared_sinks=self.shared_sinks)
        if self._restore_states is not None:
            self.chain.restore(self.graph, self._restore_states,
                               key_group_filter=set(self.key_groups))
        gates = self.gates
        K = len(gates)
        # flat channel addressing across gates: (gate, ch) -> slot
        nch = [g.num_channels for g in gates]
        total = sum(nch)
        base = [sum(nch[:g]) for g in range(K)]
        chan_wm = [-(1 << 62)] * total
        done = [False] * total
        combined = -(1 << 62)
        aligning: Optional[Barrier] = None
        barriered = [False] * total
        buffered: List[Tuple[int, int, Any]] = []
        stopping = False
        poll_at = 0

        def combined_wm() -> int:
            return min((MAX_WATERMARK if done[c] else chan_wm[c])
                       for c in range(total))

        def process(item, gi: int, slot: int):
            nonlocal combined, stopping
            if isinstance(item, RecordBatch):
                self.records_in += len(item)
                for out in self.chain.process_batch(item, input_index=gi):
                    pass  # sink is in-chain; trailing output dropped
            elif isinstance(item, int):
                chan_wm[slot] = max(chan_wm[slot], item)
                new = combined_wm()
                if new > combined:
                    combined = new
                    self.chain.process_watermark(combined)

        def aligned_snapshot_ack() -> bool:
            """Snapshot + ack the aligning barrier; returns stop flag."""
            snap = {"operators": self.chain.snapshot(
                self.graph, savepoint=aligning.savepoint is not None)}
            self.coordinator.ack(aligning.checkpoint_id,
                                 ("keyed", self.index), snap)
            return aligning.stop

        ticks_pt = self.chain.uses_processing_time
        while True:
            self._serve_queries()
            if self.coordinator.cancelled.is_set():
                return
            if ticks_pt:
                self.chain.tick_processing_time(int(time.time() * 1000))
            # non-blocking sweep of every gate first — an idle/exhausted
            # input must not throttle a live one; only when ALL gates are
            # empty does one (rotating) gate take a short blocking poll
            entry = None
            gi = poll_at
            for off in range(K):
                g = (poll_at + off) % K
                entry = gates[g].poll(timeout=0)
                if entry is not None:
                    gi = g
                    break
            if entry is None:
                gi = poll_at
                entry = gates[gi].poll(timeout=0.05)
            poll_at = (gi + 1) % K
            if entry is None:
                continue
            ch, item = entry
            slot = base[gi] + ch
            if isinstance(item, Barrier):
                if aligning is None:
                    aligning = item
                    barriered = [False] * total
                barriered[slot] = True
                if all(barriered[c] or done[c] for c in range(total)):
                    # all channels of all gates aligned: snapshot + ack,
                    # then drain the buffered post-barrier items
                    if aligned_snapshot_ack():
                        stopping = True
                    aligning = None
                    for bgi, bslot, bitem in buffered:
                        process(bitem, bgi, bslot)
                    buffered = []
                    if stopping:
                        self.chain.close()
                        return
                continue
            if item is END_OF_PARTITION:
                done[slot] = True
                if aligning is not None and all(
                        barriered[c] or done[c] for c in range(total)):
                    stop = aligned_snapshot_ack()
                    if stop:
                        # stop-with-savepoint completed by an EOP: stop
                        # exactly like the barrier-completion branch —
                        # post-savepoint output would duplicate on resume
                        aligning = None
                        self.chain.close()
                        return
                    aligning = None
                    for bgi, bslot, bitem in buffered:
                        process(bitem, bgi, bslot)
                    buffered = []
                if all(done):
                    new = MAX_WATERMARK
                    if new > combined:
                        self.chain.process_watermark(new)
                    self.chain.close()
                    return
                # a finished channel no longer constrains the watermark
                new = combined_wm()
                if new > combined:
                    combined = new
                    self.chain.process_watermark(combined)
                continue
            if aligning is not None and barriered[slot]:
                # aligned-barrier blocking: post-barrier data waits until
                # alignment completes (bounded by channel credits)
                buffered.append((gi, slot, item))
                continue
            process(item, gi, slot)

    def _serve_queries(self) -> None:
        while True:
            try:
                req = self.control.get_nowait()
            except _q.Empty:
                return
            op_name, key, namespace, reply = req
            try:
                result = None
                for t, op in zip(self.chain.transformations,
                                 self.chain.operators):
                    if op is not None and t.name == op_name and \
                            hasattr(op, "query_state"):
                        result = op.query_state(key, namespace)
                        break
                reply.put((result, None))
            except BaseException as e:  # noqa: BLE001
                reply.put((None, e))


class _Coordinator:
    """Checkpoint + failure coordination for one stage-parallel job run."""

    def __init__(self, num_acks: int):
        self.num_acks = num_acks
        self.cancelled = threading.Event()
        self.failure: Optional[BaseException] = None
        self._acks: Dict[int, Dict[Tuple[str, int], Dict]] = {}
        self._complete: Dict[int, threading.Event] = {}
        self._lock = threading.Lock()

    def expect(self, checkpoint_id: int) -> threading.Event:
        with self._lock:
            self._acks[checkpoint_id] = {}
            ev = self._complete[checkpoint_id] = threading.Event()
            return ev

    def ack(self, checkpoint_id: int, who: Tuple[str, int],
            snap: Dict) -> None:
        with self._lock:
            acks = self._acks.get(checkpoint_id)
            if acks is None or who in acks:
                # first ack wins: a synthesized end-of-split ack must never
                # replace a real barrier-cut ack (their positions differ)
                return
            acks[who] = snap
            if len(acks) >= self.num_acks:
                self._complete[checkpoint_id].set()

    def collected(self, checkpoint_id: int) -> Dict[Tuple[str, int], Dict]:
        with self._lock:
            return self._acks.pop(checkpoint_id, {})

    def subtask_failed(self, subtask, error: BaseException) -> None:
        self.failure = self.failure or error
        self.cancelled.set()
        with self._lock:
            for ev in self._complete.values():
                ev.set()


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


@internal
class StageParallelExecutor:
    """Same run() contract as LocalExecutor, executing via subtask
    expansion (reference: Execution.deploy — but subtasks here are threads
    wired by the Shuffle SPI; a cross-process transport plugs in via
    ``shuffle.service``)."""

    def __init__(self, config: Optional[Configuration] = None,
                 shuffle_service=None):
        self.config = config or Configuration()
        self._shuffle = shuffle_service

    def run(self, graph: StreamGraph, job_name: str = "job",
            restore_from: Optional[str] = None, cancel_event=None,
            restore_mode: str = "no-claim", control_queue=None):
        from flink_tpu.datastream.environment import JobExecutionResult

        self._cancel_event = cancel_event
        from flink_tpu.core.config import ExecutionModeOptions

        plan = plan_stages(graph)
        specs = plan.inputs
        K = len(specs)
        cfg = self.config
        N = cfg.get(DeploymentOptions.STAGE_PARALLELISM)
        S = cfg.get(DeploymentOptions.SOURCE_PARALLELISM)
        max_par = cfg.get(CoreOptions.MAX_PARALLELISM)
        batch_size = cfg.get(BatchOptions.BATCH_SIZE)
        batch_mode = cfg.get(
            ExecutionModeOptions.RUNTIME_MODE) == "batch"
        for spec in specs:
            if batch_mode and not getattr(spec.source.source, "bounded",
                                          True):
                raise RuntimeError(
                    "execution.runtime-mode=batch requires bounded "
                    f"sources; {spec.source.name!r} is unbounded")
        if N == -1:
            # adaptive batch parallelism: size the keyed stage from the
            # estimated source volume (reference: AdaptiveBatchScheduler
            # decides parallelism from produced data volume)
            if not batch_mode:
                raise StagePlanError(
                    "execution.stage-parallelism=-1 (adaptive) requires "
                    "execution.runtime-mode=batch")
            est = sum(
                int(spec.source.source.estimate_records() or 0)
                for spec in specs)
            target = cfg.get(
                ExecutionModeOptions.TARGET_RECORDS_PER_SUBTASK)
            if target < 1:
                raise StagePlanError(
                    "execution.batch.target-records-per-subtask must be "
                    f">= 1, got {target}")
            N = max(1, min(-(-int(est) // target) if est else 1, max_par))
        if N < 1:
            raise StagePlanError("execution.stage-parallelism must be >= 1")

        shuffle = self._shuffle or create_shuffle_service(
            cfg.get(DeploymentOptions.SHUFFLE_SERVICE))
        credits = cfg.get(DeploymentOptions.SHUFFLE_CREDITS)

        ckpt_dir = cfg.get(StateOptions.CHECKPOINT_DIR)
        ckpt_interval = cfg.get(CheckpointOptions.INTERVAL_MS)
        ckpt_every_n = cfg.get(CheckpointOptions.EVERY_N_BATCHES)
        storage = None
        if ckpt_dir and (ckpt_interval or ckpt_every_n):
            from flink_tpu.checkpoint.storage import CheckpointStorage

            storage = CheckpointStorage(
                ckpt_dir, compress=cfg.get(CheckpointOptions.COMPRESSION))

        # restore
        checkpoint_id = 0
        restore_states: Dict[str, Any] = {}
        restore_positions: Dict[int, Any] = {}
        if restore_from is not None:
            from flink_tpu.checkpoint.savepoint import prepare_restore
            from flink_tpu.checkpoint.storage import (
                read_checkpoint_chain,
                read_manifest,
            )

            snap_dir, _ = prepare_restore(restore_from, restore_mode,
                                          own_checkpoint_root=ckpt_dir)
            states = read_checkpoint_chain(snap_dir)
            checkpoint_id = int(read_manifest(snap_dir)["checkpoint_id"])
            src_ids = {graph.stable_id(spec.source): i
                       for i, spec in enumerate(specs)}
            known_ids = {graph.stable_id(t)
                         for spec in specs for t in spec.pre_chain
                         if t.operator_factory is not None}
            known_ids.update(graph.stable_id(t) for t in plan.keyed_chain
                             if t.operator_factory is not None)
            for sid, state in states.items():
                if sid in src_ids:
                    pos = state["source"]
                    if isinstance(pos, dict) and "__subtasks__" in pos:
                        per_sub = {int(k): v
                                   for k, v in pos["__subtasks__"].items()}
                        if len(per_sub) != S:
                            raise RuntimeError(
                                "snapshot has positions for "
                                f"{len(per_sub)} source subtasks "
                                f"but execution.source-parallelism is {S} "
                                "— source splits cannot be re-assigned "
                                "across counts (restore with the original "
                                "source parallelism)")
                    else:
                        if S != 1:
                            raise RuntimeError(
                                "snapshot has a single source position "
                                f"but execution.source-parallelism is {S}")
                        per_sub = {0: pos}
                    restore_positions[src_ids[sid]] = per_sub
                elif sid in known_ids:
                    restore_states[sid] = state
                else:
                    # the reference fails on non-restored state by default
                    # (allowNonRestoredState opt-in); dropping it silently
                    # would e.g. restart a renamed source from record 0
                    raise RuntimeError(
                        "checkpoint contains state for operators not "
                        "present in the graph (graph changed since "
                        f"snapshot?): {sid!r}")
            if storage is not None:
                checkpoint_id = max(
                    checkpoint_id, storage.latest_checkpoint_id() or 0)

        coordinator = _Coordinator(num_acks=K * S + N)

        # wire partitions: source subtask s of input i owns one partition
        # with N subpartitions; keyed subtask j consumes subpartition j of
        # every partition of every input through one gate PER input
        def pid(i: int, s: int) -> str:
            # keep the legacy id format for the linear pipeline (external
            # shuffle services key their buffers by these names)
            return (f"{job_name}-src-{s}" if K == 1
                    else f"{job_name}-in{i}-src-{s}")

        writers = {(i, s): shuffle.create_partition(pid(i, s), N, credits)
                   for i in range(K) for s in range(S)}
        gates = [[shuffle.create_gate([pid(i, s) for s in range(S)], j)
                  for i in range(K)]
                 for j in range(N)]

        combiner_factory = None
        if K == 1 and cfg.get(DeploymentOptions.LOCAL_AGG):
            combiner_factory = _local_combiner_factory(plan)

        sources = []
        import copy as _copy

        for i, spec in enumerate(specs):
            per_input_pos = restore_positions.get(i, {})
            for s in range(S):
                src = spec.source.source if S == 1 else _copy.deepcopy(
                    spec.source.source)
                sources.append(_SourceSubtask(
                    s, S, spec, graph, writers[(i, s)], N, max_par,
                    batch_size, coordinator, src,
                    restore_position=per_input_pos.get(s),
                    batch_mode=batch_mode,
                    combiner=combiner_factory() if combiner_factory
                    else None,
                    input_index=i))
        shared_sinks: Dict[int, _SharedSink] = {}
        keyed = [_KeyedSubtask(j, N, plan, graph, gates[j], max_par,
                               coordinator, cfg, shared_sinks=shared_sinks)
                 for j in range(N)]
        for k in keyed:
            if restore_states:
                k._restore_states = restore_states
        for t in keyed + sources:
            t.start()

        t0 = time.perf_counter()
        savepoint_path = None
        last_ckpt = time.time() * 1000
        last_batches = 0
        try:
            while any(t.is_alive() for t in sources + keyed):
                if cancel_event is not None and cancel_event.is_set():
                    coordinator.cancelled.set()
                    if isinstance(shuffle, LocalShuffleService):
                        shuffle.cancel()
                    from flink_tpu.cluster.local_executor import (
                        JobCancelledError,
                    )

                    raise JobCancelledError(job_name)
                if coordinator.failure is not None:
                    raise coordinator.failure
                # user control: savepoints / queries
                if control_queue is not None:
                    sp = self._serve_control(
                        control_queue, plan, graph, sources, keyed,
                        coordinator, storage, ckpt_dir, job_name,
                        checkpoint_id)
                    if sp is not None:
                        checkpoint_id, savepoint_path, stopped = sp
                        if stopped:
                            break
                # periodic checkpoints (time interval or deterministic
                # every-N-source-batches, like the single-slot executor)
                if storage is not None and any(
                        s.is_alive() for s in sources):
                    total_batches = sum(s.batches_polled for s in sources)
                    due = (ckpt_every_n and total_batches - last_batches
                           >= ckpt_every_n) or (
                        not ckpt_every_n and ckpt_interval
                        and time.time() * 1000 - last_ckpt >= ckpt_interval)
                    if due:
                        checkpoint_id += 1
                        self._checkpoint(
                            checkpoint_id, Barrier(checkpoint_id),
                            sources, keyed, coordinator, graph, plan,
                            storage=storage, job_name=job_name)
                        last_ckpt = time.time() * 1000
                        last_batches = total_batches
                time.sleep(0.01)
            if coordinator.failure is not None:
                raise coordinator.failure
            for t in sources + keyed:
                t.join(timeout=30)
                if t.error is not None:
                    raise t.error
        except BaseException:
            coordinator.cancelled.set()
            if isinstance(shuffle, LocalShuffleService):
                shuffle.cancel()
            for t in sources + keyed:
                t.join(timeout=5)
            for k in keyed:
                if k.chain is not None:
                    k.chain.dispose()
            raise
        finally:
            if control_queue is not None:
                from flink_tpu.cluster.local_executor import _ControlRequest

                try:
                    while True:
                        req = control_queue.get_nowait()
                        if isinstance(req, _ControlRequest):
                            req.finish(None, RuntimeError(
                                f"job {job_name!r} terminated"))
                except _q.Empty:
                    pass

        elapsed = time.perf_counter() - t0
        total = sum(s.records_polled for s in sources)
        metrics = {
            "records": total,
            "elapsed_s": elapsed,
            "records_per_s": total / elapsed if elapsed else 0.0,
            "stage_parallelism": N,
            "source_parallelism": S,
            # rows that actually crossed the keyed exchange (< records
            # when the local combiner collapsed them — the two-phase win)
            "records_shuffled": sum(s.records_out for s in sources),
            "subtask_records_in": [k.records_in for k in keyed],
        }
        if savepoint_path:
            metrics["savepoint"] = savepoint_path
        return JobExecutionResult(job_name, metrics)

    # ------------------------------------------------------------- control

    def _serve_control(self, control_queue, plan, graph, sources, keyed,
                       coordinator, storage, ckpt_dir, job_name,
                       checkpoint_id):
        from flink_tpu.cluster.local_executor import (
            SavepointRequest,
            StateQueryRequest,
        )

        try:
            req = control_queue.get_nowait()
        except _q.Empty:
            return None
        if isinstance(req, StateQueryRequest):
            try:
                from flink_tpu.state.keygroups import (
                    hash_keys_to_i64,
                )

                key_id = int(hash_keys_to_i64(
                    np.asarray([req.key]))[0])
                group = int(assign_key_groups(
                    np.asarray([key_id]),
                    self.config.get(CoreOptions.MAX_PARALLELISM))[0])
                owner = int(key_group_to_operator_index(
                    np.asarray([group]),
                    self.config.get(CoreOptions.MAX_PARALLELISM),
                    len(keyed))[0])
                reply: _q.Queue = _q.Queue()
                keyed[owner].control.put(
                    (req.operator_name, req.key, req.namespace, reply))
                result, err = reply.get(timeout=30)
                req.finish(result, err)
            except BaseException as e:  # noqa: BLE001
                req.finish(None, e)
            return None
        if isinstance(req, SavepointRequest):
            try:
                new_id = checkpoint_id + 1
                path = self._checkpoint(
                    new_id, Barrier(new_id, savepoint=req.path,
                                    stop=req.stop),
                    sources, keyed, coordinator, graph, plan,
                    savepoint_dir=req.path, job_name=job_name)
                req.finish(path)
                return (new_id, path, req.stop)
            except BaseException as e:  # noqa: BLE001
                req.finish(None, e)
                return None
        req.finish(None, RuntimeError(f"unsupported control {req!r}"))
        return None

    # ---------------------------------------------------------- checkpoint

    def _checkpoint(self, checkpoint_id: int, barrier: Barrier, sources,
                    keyed, coordinator, graph, plan,
                    storage=None, savepoint_dir=None, job_name="job"):
        """Trigger, await S+N acks, merge subtask states into the logical
        single-slot snapshot format, commit."""
        live_sources = [s for s in sources if s.is_alive()]
        if not live_sources:
            raise RuntimeError("cannot checkpoint: all sources finished")
        coordinator.num_acks = len(live_sources) + len(keyed)
        done = coordinator.expect(checkpoint_id)
        for s in live_sources:
            s.control.put(barrier)
        deadline = time.monotonic() + 120
        while not done.wait(timeout=0.1):
            # a source may have drained its split between the is_alive()
            # check and serving the trigger: synthesize its ack from the
            # recorded final position (the thread has exited — its chain
            # is safe to snapshot from here)
            for s in live_sources:
                if not s.is_alive() and s.final_position is not None:
                    coordinator.ack(
                        checkpoint_id,
                        ("source", s.input_index, s.index),
                        {"position": s.final_position,
                         "operators": s.chain.snapshot(graph)
                         if s.chain else {}})
            # the run loop is parked here — cancellation and subtask death
            # must abort the checkpoint, not wait out the full deadline
            if coordinator.cancelled.is_set() or (
                    self._cancel_event is not None
                    and self._cancel_event.is_set()):
                from flink_tpu.cluster.local_executor import (
                    JobCancelledError,
                )

                raise JobCancelledError("cancelled during checkpoint")
            if coordinator.failure is not None:
                raise coordinator.failure
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"checkpoint {checkpoint_id} timed out")
        if coordinator.failure is not None:
            raise coordinator.failure
        acks = coordinator.collected(checkpoint_id)
        # assemble logical snapshot: per-input source positions under each
        # input's own source transformation id
        positions: Dict[int, Dict[int, Any]] = {}
        for who, sub in acks.items():
            if who[0] == "source":
                positions.setdefault(who[1], {})[who[2]] = sub["position"]
        # finished subtasks that were not in this trigger round still
        # contribute their end-of-split position — omitting them would
        # replay their whole split on restore
        for s in sources:
            per_input = positions.setdefault(s.input_index, {})
            if s.index not in per_input and s.final_position is not None:
                per_input[s.index] = s.final_position
        snap: Dict[str, Any] = {}
        per_input_subtasks = max(
            (len(p) for p in positions.values()), default=1)
        for i, spec in enumerate(plan.inputs):
            per_input = positions.get(i, {})
            # a single-subtask source stores its position unwrapped, so
            # the snapshot is restorable by the single-slot executor too;
            # S > 1 wraps per-subtask positions (stage-mode restore only)
            if per_input_subtasks == 1:
                source_state = {"source": per_input.get(0)}
            else:
                source_state = {"source": {"__subtasks__": {
                    str(s): p for s, p in per_input.items()}}}
            snap[graph.stable_id(spec.source)] = source_state
        per_operator: Dict[str, List[Dict]] = {}
        for who, sub in acks.items():
            for sid, state in sub.get("operators", {}).items():
                per_operator.setdefault(sid, []).append(state)
        for sid, states in per_operator.items():
            snap[sid] = merge_subtask_states(states)
        if savepoint_dir is not None:
            from flink_tpu.checkpoint.savepoint import write_savepoint

            return write_savepoint(savepoint_dir, job_name, snap,
                                   checkpoint_id=checkpoint_id)
        if storage is not None:
            storage.write_checkpoint(checkpoint_id, job_name, snap)
        return None


def make_executor(config: Configuration, graph: StreamGraph):
    """LocalExecutor unless ``execution.stage-parallelism`` is set AND the
    graph is expandable — shared by env.execute() and
    TaskExecutor.submit_task so local runs and cluster deployments pick
    the same engine (reference: the scheduler, not the API, decides the
    execution shape)."""
    from flink_tpu.cluster.local_executor import LocalExecutor

    sp = config.get(DeploymentOptions.STAGE_PARALLELISM)
    if sp == -1 or sp > 0:
        try:
            plan_stages(graph)
        except StagePlanError as e:
            if not config.get(DeploymentOptions.STAGE_FALLBACK):
                raise StagePlanError(
                    f"execution.stage-parallelism={sp} requested but {e}. "
                    "Set execution.stage-fallback=true to run single-slot "
                    "instead.") from e
            import warnings

            warnings.warn(
                f"execution.stage-parallelism set but {e}; running "
                "single-slot (execution.stage-fallback=true)",
                stacklevel=2)
            ex = LocalExecutor(config)
            ex.fallback_reason = str(e)
            return ex
        else:
            return StageParallelExecutor(config)
    return LocalExecutor(config)
